"""swarmctl: operator CLI over the Control API (reference swarmd/cmd/swarmctl).

    swarmctl --addr 127.0.0.1:4242 --identity /tmp/m1 service create \
        --name web --command "sleep 3600" --replicas 3
    swarmctl ... service ls
    swarmctl ... node ls / node promote <id> / node demote <id>
    swarmctl ... secret create my-secret --data-stdin < secret.txt
    swarmctl ... logs <service-name>

Identity: `--identity` points at a node state dir (cert.pem/key.json/ca.pem,
as written by swarmd); the control surface requires a manager certificate.
Env fallbacks: SWARMCTL_ADDR, SWARMCTL_IDENTITY.
"""
from __future__ import annotations

import argparse
import os
import sys


def _die(msg: str) -> "NoReturn":  # noqa: F821
    print(f"swarmctl: {msg}", file=sys.stderr)
    sys.exit(1)


def _load_identity(state_dir: str):
    from ..ca import SecurityConfig

    try:
        return SecurityConfig.load_from_dir(state_dir)
    except OSError as exc:
        _die(f"cannot load identity from {state_dir}: {exc}")


def _control(args):
    from ..rpc.services import RemoteControl

    if getattr(args, "socket", None):
        # local unix control socket: no TLS identity needed (xnet)
        return RemoteControl(f"unix://{args.socket}", None)
    if not args.addr:
        _die("need --addr (or --socket for a local manager)")
    return RemoteControl(args.addr, _load_identity(args.identity))


def _fmt_table(rows: list[list[str]], header: list[str]) -> str:
    rows = [header] + rows
    widths = [max(len(str(r[i])) for r in rows) for i in range(len(header))]
    out = []
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def _short(obj_id: str) -> str:
    return obj_id[:12]


def _state_name(state) -> str:
    return getattr(state, "name", str(state)).lower()


def _find_service(ctl, ref: str):
    from ..controlapi.control import ListFilters

    svcs = ctl.list_services(ListFilters(names=[ref]))
    if not svcs:
        svcs = ctl.list_services(ListFilters(id_prefixes=[ref]))
    if not svcs:
        _die(f"service {ref!r} not found")
    if len(svcs) > 1:
        _die(f"service reference {ref!r} is ambiguous")
    return svcs[0]


def _find_node(ctl, ref: str):
    nodes = [n for n in ctl.list_nodes() if n.id.startswith(ref)
             or (n.description and n.description.hostname == ref)]
    if not nodes:
        _die(f"node {ref!r} not found")
    if len(nodes) > 1:
        _die(f"node reference {ref!r} is ambiguous")
    return nodes[0]


# ------------------------------------------------------------------ service

def cmd_service_create(args):
    from ..api.specs import (
        Annotations, ContainerSpec, JobSpec, ServiceSpec, TaskSpec,
        UpdateConfig)
    from ..api.types import ServiceMode

    import shlex

    runtime = ContainerSpec(
        image=args.image or "",
        command=shlex.split(args.command) if args.command else [],
        env=[e for e in (args.env or [])],
    )
    spec = ServiceSpec(
        annotations=Annotations(name=args.name,
                                labels=dict(kv.split("=", 1)
                                            for kv in (args.label or []))),
        task=TaskSpec(runtime=runtime),
        replicas=args.replicas,
        mode=ServiceMode(args.mode),
    )
    spec.task.placement.constraints = list(args.constraint or [])
    ctl = _control(args)
    # --secret/--config NAME[:TARGET]: resolve name -> id and attach a
    # reference (reference swarmctl/service/flagparser/secret.go)
    from ..api.specs import ConfigReference, SecretReference
    from ..controlapi.control import ListFilters

    for ref in args.secret or []:
        name, _, target = ref.partition(":")
        found = ctl.list_secrets(ListFilters(names=[name]))
        if not found:
            _die(f"secret {name!r} not found")
        runtime.secrets.append(SecretReference(
            secret_id=found[0].id, secret_name=name,
            target=target or name))
    for ref in args.config or []:
        name, _, target = ref.partition(":")
        found = ctl.list_configs(ListFilters(names=[name]))
        if not found:
            _die(f"config {name!r} not found")
        runtime.configs.append(ConfigReference(
            config_id=found[0].id, config_name=name,
            target=target or name))
    for ref in args.network or []:
        n = _find_network(ctl, ref)
        from ..api.specs import NetworkAttachmentConfig

        spec.task.networks.append(NetworkAttachmentConfig(target=n.id))
    for pub in args.publish or []:
        # TARGET[:PUBLISHED][/PROTOCOL], docker-style
        from ..api.specs import PortConfig

        body, _, proto = pub.partition("/")
        target, _, published = body.partition(":")
        spec.endpoint.ports.append(PortConfig(
            protocol=proto or "tcp",
            target_port=int(target),
            published_port=int(published) if published else 0,
            publish_mode=args.publish_mode))
    if args.update_parallelism or args.update_delay:
        spec.update = UpdateConfig(
            parallelism=args.update_parallelism or 1,
            delay=args.update_delay or 0.0)
    if args.mode in ("replicated_job", "global_job"):
        spec.job = JobSpec(total_completions=args.replicas)
    svc = ctl.create_service(spec)
    print(svc.id)


def cmd_service_ls(args):
    ctl = _control(args)
    from ..api.types import ServiceMode, TaskState

    tasks = ctl.list_tasks()
    running = {}
    for t in tasks:
        if t.status.state == TaskState.RUNNING:
            running[t.service_id] = running.get(t.service_id, 0) + 1
    rows = []
    for s in ctl.list_services():
        mode = s.spec.mode.value if hasattr(s.spec.mode, "value") else s.spec.mode
        desired = s.spec.replicas if s.spec.mode == ServiceMode.REPLICATED else "-"
        rows.append([_short(s.id), s.spec.annotations.name, mode,
                     f"{running.get(s.id, 0)}/{desired}"])
    print(_fmt_table(rows, ["ID", "NAME", "MODE", "REPLICAS"]))


def cmd_service_inspect(args):
    import json

    ctl = _control(args)
    s = _find_service(ctl, args.service)
    runtime = s.spec.task.runtime
    print(json.dumps({
        "id": s.id,
        "name": s.spec.annotations.name,
        "mode": str(s.spec.mode),
        "replicas": s.spec.replicas,
        "command": runtime.command if runtime else None,
        "image": runtime.image if runtime else None,
        "constraints": s.spec.task.placement.constraints,
        "version": s.meta.version.index,
    }, indent=2))


def cmd_service_update(args):
    ctl = _control(args)
    s = _find_service(ctl, args.service)
    spec = s.spec
    if getattr(args, "rollback", False):
        # revert to previous_spec (service.go UpdateService rollback)
        updated = ctl.update_service(s.id, s.meta.version, spec,
                                     rollback=True)
        print(updated.id)
        return
    if args.replicas is not None:
        spec.replicas = args.replicas
    if args.command is not None or args.image is not None:
        if spec.task.runtime is None:
            from ..api.specs import ContainerSpec

            spec.task.runtime = ContainerSpec()
        if args.command is not None:
            import shlex

            spec.task.runtime.command = shlex.split(args.command)
        if args.image is not None:
            spec.task.runtime.image = args.image
    if args.update_parallelism is not None or args.update_delay is not None \
            or args.update_order is not None:
        from ..api.specs import UpdateConfig, UpdateOrder

        cfg = spec.update or UpdateConfig()
        if args.update_parallelism is not None:
            cfg.parallelism = args.update_parallelism
        if args.update_delay is not None:
            cfg.delay = args.update_delay
        if args.update_order is not None:
            cfg.order = UpdateOrder(args.update_order.replace("-", "_"))
        spec.update = cfg
    if args.env is not None or args.constraint is not None:
        if spec.task.runtime is None:
            from ..api.specs import ContainerSpec

            spec.task.runtime = ContainerSpec()
        if args.env is not None:
            # full replacement, like the reference flagparser's env flag
            spec.task.runtime.env = list(args.env)
        if args.constraint is not None:
            spec.task.placement.constraints = list(args.constraint)
    for kv in args.label_add or []:
        k, _, v = kv.partition("=")
        spec.annotations.labels[k] = v
    for k in args.label_rm or []:
        spec.annotations.labels.pop(k, None)
    if args.force:
        spec.task.force_update += 1
    updated = ctl.update_service(s.id, s.meta.version, spec)
    print(updated.id)


def cmd_service_rm(args):
    ctl = _control(args)
    s = _find_service(ctl, args.service)
    ctl.remove_service(s.id)
    print(s.id)


def cmd_service_scale(args):
    name, _, n = args.target.partition("=")
    if not n.isdigit():
        _die("usage: service scale <name>=<replicas>")
    ctl = _control(args)
    s = _find_service(ctl, name)
    s.spec.replicas = int(n)
    ctl.update_service(s.id, s.meta.version, s.spec)
    print(f"{name} scaled to {n}")


# --------------------------------------------------------------------- task

def cmd_task_ls(args):
    from ..controlapi.control import ListFilters

    ctl = _control(args)
    filters = None
    if args.service:
        svc = _find_service(ctl, args.service)
        filters = ListFilters(service_ids=[svc.id])
    nodes = {n.id: (n.description.hostname if n.description else n.id[:8])
             for n in ctl.list_nodes()}
    rows = []
    for t in sorted(ctl.list_tasks(filters),
                    key=lambda t: (t.service_id, t.slot)):
        rows.append([
            _short(t.id), t.annotations.name or f"slot.{t.slot}",
            _state_name(t.status.state), _state_name(t.desired_state),
            nodes.get(t.node_id, t.node_id[:8] if t.node_id else "-"),
            t.status.err or "",
        ])
    print(_fmt_table(rows, ["ID", "NAME", "STATE", "DESIRED", "NODE", "ERR"]))


# --------------------------------------------------------------------- node

def cmd_node_ls(args):
    ctl = _control(args)
    rows = []
    for n in sorted(ctl.list_nodes(), key=lambda n: n.id):
        ms = n.manager_status
        rows.append([
            _short(n.id),
            n.description.hostname if n.description else "",
            _state_name(n.status.state),
            getattr(n.spec.availability, "name", "active").lower(),
            ("leader" if ms and ms.leader else
             "reachable" if ms and ms.addr else ""),
        ])
    print(_fmt_table(rows,
                     ["ID", "HOSTNAME", "STATUS", "AVAILABILITY", "MANAGER"]))


def cmd_node_inspect(args):
    import json

    ctl = _control(args)
    n = _find_node(ctl, args.node)
    print(json.dumps({
        "id": n.id,
        "hostname": n.description.hostname if n.description else None,
        "role": getattr(n.role, "name", str(n.role)).lower(),
        "desired_role": getattr(n.spec.desired_role, "name",
                                str(n.spec.desired_role)).lower(),
        "status": _state_name(n.status.state),
        "availability": getattr(n.spec.availability, "name", "active").lower(),
        "labels": dict(n.spec.annotations.labels),
        "manager": ({"addr": n.manager_status.addr,
                     "leader": n.manager_status.leader,
                     "raft_id": n.manager_status.raft_id}
                    if n.manager_status else None),
    }, indent=2))


def _set_node(args, mutate):
    ctl = _control(args)
    n = _find_node(ctl, args.node)
    mutate(n.spec)
    ctl.update_node(n.id, n.meta.version, n.spec)
    print(n.id)


def cmd_node_update(args):
    """Node spec update: labels (+availability) — reference
    swarmctl/node/update.go (label flags) + drain/activate semantics."""
    def mutate(spec):
        changed = False
        for kv in args.label_add or []:
            k, _, v = kv.partition("=")
            spec.annotations.labels[k] = v
            changed = True
        for k in args.label_rm or []:
            if spec.annotations.labels.pop(k, None) is not None:
                changed = True
        if args.availability:
            from ..api.types import NodeAvailability

            spec.availability = NodeAvailability[args.availability.upper()]
            changed = True
        if not changed:
            _die(f"no change for node {args.node}")

    _set_node(args, mutate)


def cmd_node_promote(args):
    from ..api.types import NodeRole

    _set_node(args, lambda spec: setattr(spec, "desired_role",
                                         NodeRole.MANAGER))


def cmd_node_demote(args):
    from ..api.types import NodeRole

    _set_node(args, lambda spec: setattr(spec, "desired_role",
                                         NodeRole.WORKER))


def cmd_node_drain(args):
    from ..api.types import NodeAvailability

    _set_node(args, lambda spec: setattr(spec, "availability",
                                         NodeAvailability.DRAIN))


def cmd_node_pause(args):
    # pause: no NEW placements, existing tasks keep running (the scheduler
    # filter only admits ACTIVE; drain additionally evicts)
    from ..api.types import NodeAvailability

    _set_node(args, lambda spec: setattr(spec, "availability",
                                         NodeAvailability.PAUSE))


def cmd_node_activate(args):
    from ..api.types import NodeAvailability

    _set_node(args, lambda spec: setattr(spec, "availability",
                                         NodeAvailability.ACTIVE))


def cmd_node_rm(args):
    ctl = _control(args)
    n = _find_node(ctl, args.node)
    ctl.remove_node(n.id, force=args.force)
    print(n.id)


# ------------------------------------------------------------------ cluster

def cmd_cluster_inspect(args):
    import json

    ctl = _control(args)
    clusters = ctl.list_clusters()
    out = []
    for c in clusters:
        out.append({
            "id": c.id,
            "name": c.spec.annotations.name,
            "worker_join_token": (c.root_ca.join_token_worker
                                  if c.root_ca else None),
            "manager_join_token": (c.root_ca.join_token_manager
                                   if c.root_ca else None),
        })
    print(json.dumps(out, indent=2))


def _update_cluster_retry(ctl, mutate_spec=None, **rotations):
    """Version-checked update raced by background cluster writers
    (keymanager etc.): retry on sequence conflicts like any client.
    `mutate_spec(spec)` re-applies the caller's spec edits on every
    attempt (each retry starts from a FRESH read)."""
    import time as _time

    for _ in range(20):
        c = ctl.list_clusters()[0]
        if mutate_spec is not None:
            mutate_spec(c.spec)
        try:
            return ctl.update_cluster(c.id, c.meta.version, c.spec,
                                      **rotations)
        except Exception as exc:
            if "out of sequence" not in str(exc):
                raise
            _time.sleep(0.1)
    _die("cluster update kept conflicting; try again")


def cmd_cluster_update(args):
    """Token rotation + CA steering (reference swarmctl/cluster/update.go;
    CA flags mirror `docker swarm ca --rotate` / update-cluster CAConfig)."""
    ctl = _control(args)

    def mutate_spec(spec):
        if getattr(args, "rotate_ca", False):
            spec.ca.force_rotate += 1
            if not getattr(args, "signing_ca_cert", None):
                # a fresh-root rotation: clear any stale signing pin so
                # the API can't read residue as intent to re-target it
                spec.ca.signing_ca_cert = b""
                spec.ca.signing_ca_key = b""
        cert_path = getattr(args, "signing_ca_cert", None)
        key_path = getattr(args, "signing_ca_key", None)
        if cert_path:
            with open(cert_path, "rb") as f:
                spec.ca.signing_ca_cert = f.read()
        if key_path:
            with open(key_path, "rb") as f:
                spec.ca.signing_ca_key = f.read()
        if getattr(args, "external_ca", None):
            entries = []
            for spec_str in args.external_ca:
                # url[,ca_cert=<path>] — protocol is always cfssl (the only
                # one the reference supports in-tree, cli/external_ca.go)
                parts = spec_str.split(",")
                entry = {"protocol": "cfssl", "url": parts[0]}
                for extra in parts[1:]:
                    k, _, v = extra.partition("=")
                    if k == "ca_cert":
                        with open(v, "rb") as f:
                            entry["ca_cert"] = f.read()
                    elif k == "protocol":
                        entry["protocol"] = v
                    else:
                        _die(f"unknown external-ca option {k!r}")
                entries.append(entry)
            spec.ca.external_cas = entries
        if getattr(args, "cert_expiry", None):
            spec.ca.node_cert_expiry = float(args.cert_expiry)

    c = _update_cluster_retry(
        ctl, mutate_spec=mutate_spec,
        rotate_worker_token=args.rotate_worker_token,
        rotate_manager_token=args.rotate_manager_token,
        rotate_unlock_key=args.rotate_unlock_key)
    if args.rotate_worker_token:
        print(f"SWARM_WORKER_TOKEN={c.root_ca.join_token_worker}")
    if args.rotate_manager_token:
        print(f"SWARM_MANAGER_TOKEN={c.root_ca.join_token_manager}")
    if getattr(args, "rotate_ca", False) or getattr(args, "signing_ca_cert",
                                                    None):
        rot = c.root_ca.root_rotation if c.root_ca else None
        print("CA_ROTATION=in-progress" if rot else "CA_ROTATION=complete")


def cmd_cluster_unlockkey(args):
    """Show (or rotate) the autolock unlock key via the sanctioned
    GetUnlockKey path — cluster reads redact key material
    (reference swarmctl/cluster/unlockkey.go; ca.proto GetUnlockKey)."""
    ctl = _control(args)
    c = ctl.list_clusters()[0]
    if args.rotate:
        c = _update_cluster_retry(ctl, rotate_unlock_key=True)
    key = ctl.get_unlock_key(c.id)
    print(key if key else "autolock is not enabled")


def _find_task(ctl, ref: str):
    tasks = ctl.list_tasks()
    exact = [t for t in tasks if t.id == ref]
    if exact:
        return exact[0]
    matches = [t for t in tasks if t.id.startswith(ref)]
    if not matches:
        _die(f"task {ref!r} not found")
    if len(matches) > 1:
        _die(f"task {ref!r} is ambiguous")
    return matches[0]


def cmd_task_inspect(args):
    import json

    ctl = _control(args)
    t = _find_task(ctl, args.task)
    from swarmkit_tpu.api.types import TaskState

    print(json.dumps({
        "id": t.id,
        "service_id": t.service_id,
        "slot": t.slot,
        "node_id": t.node_id,
        "state": TaskState(t.status.state).name.lower(),
        "desired_state": TaskState(t.desired_state).name.lower(),
        "message": t.status.message,
        "err": t.status.err,
        "networks": [a for a in (t.networks or []) if isinstance(a, dict)],
    }, indent=2))


# ------------------------------------------------------------ secret/config

def _read_data(args) -> bytes:
    if args.data is not None:
        return args.data.encode()
    return sys.stdin.buffer.read()


def cmd_network_create(args):
    from ..api.specs import Annotations, NetworkSpec

    ctl = _control(args)
    spec = NetworkSpec(annotations=Annotations(name=args.name),
                       ingress=args.ingress)
    if args.subnet:
        spec.ipam = {"subnet": args.subnet}
    n = ctl.create_network(spec)
    print(n.id)


def cmd_network_ls(args):
    ctl = _control(args)
    rows = []
    for n in ctl.list_networks():
        state = n.driver_state or {}
        rows.append([_short(n.id), n.spec.annotations.name,
                     state.get("subnet", ""), state.get("gateway", ""),
                     "ingress" if n.spec.ingress else ""])
    print(_fmt_table(rows, ["ID", "NAME", "SUBNET", "GATEWAY", "FLAGS"]))


def _find_network(ctl, ref):
    matches = [n for n in ctl.list_networks()
               if n.id == ref or n.id.startswith(ref)
               or n.spec.annotations.name == ref]
    if not matches:
        _die(f"network {ref!r} not found")
    if len(matches) > 1:
        _die(f"network {ref!r} is ambiguous")
    return matches[0]


def cmd_network_inspect(args):
    import json as _json

    ctl = _control(args)
    n = _find_network(ctl, args.network)
    state = n.driver_state or {}
    print(_json.dumps({
        "id": n.id,
        "name": n.spec.annotations.name,
        "ingress": n.spec.ingress,
        "subnet": state.get("subnet"),
        "gateway": state.get("gateway"),
        "pending_delete": n.pending_delete,
    }, indent=2))


def cmd_network_rm(args):
    ctl = _control(args)
    n = _find_network(ctl, args.network)
    ctl.remove_network(n.id)


def cmd_secret_create(args):
    from ..api.specs import Annotations, SecretSpec

    ctl = _control(args)
    s = ctl.create_secret(SecretSpec(
        annotations=Annotations(name=args.name),
        data=_read_data(args),
        templating=bool(getattr(args, "templating", False))))
    print(s.id)


def cmd_secret_ls(args):
    ctl = _control(args)
    rows = [[_short(s.id), s.spec.annotations.name, len(s.spec.data)]
            for s in ctl.list_secrets()]
    print(_fmt_table(rows, ["ID", "NAME", "BYTES"]))


def cmd_secret_rm(args):
    from ..controlapi.control import ListFilters

    ctl = _control(args)
    secrets = ctl.list_secrets(ListFilters(names=[args.name]))
    if not secrets:
        _die(f"secret {args.name!r} not found")
    ctl.remove_secret(secrets[0].id)
    print(secrets[0].id)


def cmd_volume_create(args):
    from ..api.specs import Annotations, VolumeAccessMode, VolumeSpec

    ctl = _control(args)
    v = ctl.create_volume(VolumeSpec(
        annotations=Annotations(name=args.name),
        driver=args.driver,
        group=args.group or "",
        access_mode=VolumeAccessMode(scope=args.scope,
                                     sharing=args.sharing)))
    print(v.id)


def cmd_volume_ls(args):
    ctl = _control(args)
    rows = []
    for v in ctl.list_volumes():
        info = v.volume_info
        published = len(v.publish_status or [])
        if v.pending_delete:
            # still reserves its name until the CSI manager finishes the
            # teardown — hiding it would make the conflict undiagnosable
            state = "<removing>"
        elif info:
            state = info.volume_id
        else:
            state = "<creating>"
        rows.append([_short(v.id), v.spec.annotations.name, v.spec.driver,
                     v.spec.group or "-", state, published])
    print(_fmt_table(rows, ["ID", "NAME", "DRIVER", "GROUP", "PLUGIN ID",
                            "PUBLISHED"]))


def cmd_volume_rm(args):
    from ..controlapi.control import ListFilters

    ctl = _control(args)
    vols = ctl.list_volumes(ListFilters(names=[args.name]))
    if not vols:
        _die(f"volume {args.name!r} not found")
    ctl.remove_volume(vols[0].id, force=args.force)
    print(vols[0].id)


def cmd_config_create(args):
    from ..api.specs import Annotations, ConfigSpec

    ctl = _control(args)
    c = ctl.create_config(ConfigSpec(
        annotations=Annotations(name=args.name),
        data=_read_data(args),
        templating=bool(getattr(args, "templating", False))))
    print(c.id)


def cmd_config_ls(args):
    ctl = _control(args)
    rows = [[_short(c.id), c.spec.annotations.name, len(c.spec.data)]
            for c in ctl.list_configs()]
    print(_fmt_table(rows, ["ID", "NAME", "BYTES"]))


def cmd_config_rm(args):
    from ..controlapi.control import ListFilters

    ctl = _control(args)
    configs = ctl.list_configs(ListFilters(names=[args.name]))
    if not configs:
        _die(f"config {args.name!r} not found")
    ctl.remove_config(configs[0].id)
    print(configs[0].id)


# --------------------------------------------------------------------- logs

def _snap_hist_quantile(fam: dict | None, p: float):
    """Nearest-rank bucket-upper-bound estimate over a snapshot-encoded
    histogram family (all series summed) — the swarmctl-side mirror of
    utils/slo.histogram_quantile for codec dicts."""
    import math

    if not fam:
        return None
    buckets = fam.get("buckets", ())
    agg = [0] * len(buckets)
    n = 0
    for series in fam.get("series", ()):
        counts, cnt = series[1], series[3]
        n += cnt
        for i, c in enumerate(counts[:len(buckets)]):
            agg[i] += c
    if n == 0:
        return None
    rank = max(1, math.ceil(p / 100.0 * n))
    cum = 0
    for b, c in zip(buckets, agg):
        cum += c
        if cum >= rank:
            return b
    return math.inf


def cmd_top(args):
    """One-shot cluster telemetry table (ISSUE 15): node freshness,
    task-state census, startup percentiles, raft durability and
    dispatcher flush rates out of `control.get_cluster_telemetry`."""
    import json

    ctl = _control(args)
    t = ctl.get_cluster_telemetry(window=args.window)
    if args.json:
        print(json.dumps(t, indent=2))
        return
    if not t.get("armed"):
        print("telemetry plane disarmed (start swarmd with "
              "SWARMKIT_TPU_TELEMETRY=1 and arm the agents)"
              if t.get("aggregator", True) else
              "no telemetry aggregator on this manager (not the leader?)")
        return
    nodes = t.get("nodes", {})
    cluster = t.get("cluster", {})
    manager = t.get("manager", {})
    rows = [["nodes reported", nodes.get("reported", 0)],
            ["nodes fresh", nodes.get("fresh", 0)],
            ["nodes stale", len(nodes.get("stale", ()))]]
    if nodes.get("stale"):
        rows.append(["stale", ", ".join(nodes["stale"][:8])
                     + (" ..." if len(nodes["stale"]) > 8 else "")])
    flaps = sum(nodes.get("flaps", {}).values())
    if flaps:
        rows.append(["node flaps", flaps])
    census = sorted((k[len("tasks_"):], v)
                    for k, v in cluster.get("gauges", {}).items()
                    if k.startswith("tasks_"))
    if census:
        rows.append(["task census",
                     " ".join(f"{s}={n}" for s, n in census)])
    startup = cluster.get("histograms", {}).get("task_startup_seconds")
    p50 = _snap_hist_quantile(startup, 50)
    p99 = _snap_hist_quantile(startup, 99)
    if p50 is not None:
        rows.append(["startup p50/p99",
                     f"<={p50:g}s / <={p99:g}s (bucket bounds)"])
    raft = manager.get("raft", {})
    if raft:
        commit = raft.get("commit_index", 0)
        fsyncs = raft.get("wal_fsyncs", 0)
        per = f" ({fsyncs / commit:.3f}/commit)" if commit else ""
        rows.append(["raft", f"commit={commit} wal_fsyncs={fsyncs}{per}"])
        lease = raft.get("read_lease", {})
        if lease.get("lease_duration_s"):
            rows.append(["read lease",
                         f"ttl={lease['lease_duration_s']:g}s "
                         f"quorum_contact_age="
                         f"{lease.get('quorum_contact_age_s', 0):g}s"])
    disp = manager.get("dispatcher", {})
    if disp:
        rows.append(["dispatcher",
                     f"flushes={disp.get('flushes', 0)} "
                     f"ships={disp.get('ships', 0)} "
                     f"last_flush={disp.get('last_flush_s', 0.0):.4f}s"])
    lb = manager.get("logbroker", {})
    if lb:
        rows.append(["logbroker",
                     f"published={lb.get('published', 0)} "
                     f"delivered={lb.get('delivered', 0)} "
                     f"shed={lb.get('shed', 0)} "
                     f"subs={lb.get('pending_subscriptions', 0)} "
                     f"listeners={lb.get('listeners', 0)}"])
    for name, qs in sorted(t.get("windows", {}).items()):
        rows.append([f"window {name}",
                     " ".join(f"{k}={v:g}" for k, v in qs.items()
                              if v is not None)])
    print(_fmt_table(rows, ["metric", "value"]))


def cmd_logs(args):
    from ..logbroker.broker import (LogSelector, LogShedRecord,
                                    SubscriptionComplete)
    from ..rpc.client import RPCClient
    from ..store.watch import ChannelClosed

    ctl = _control(args)
    svc = _find_service(ctl, args.service)
    if getattr(args, "socket", None):
        client = RPCClient(f"unix://{args.socket}")
    else:
        client = RPCClient(args.addr,
                           security=_load_identity(args.identity))
    ch = client.stream("logs.subscribe",
                       LogSelector(service_ids=[svc.id]), follow=args.follow)
    try:
        while True:
            try:
                msg = ch.get(timeout=1.0)
            except TimeoutError:
                if not args.follow:
                    break
                continue
            except ChannelClosed:
                break
            if isinstance(msg, SubscriptionComplete):
                # terminal record: every publisher closed
                if msg.error:
                    print(msg.error, file=sys.stderr)
                break
            if isinstance(msg, LogShedRecord):
                # bounded-lag plane (ISSUE 20): a counted, resumable
                # loss window — announce it and keep streaming
                print(f"... {msg.count} log message(s) shed "
                      f"(seq {msg.first_seq}..{msg.last_seq}); "
                      f"stream resumes", file=sys.stderr)
                continue
            data = msg.data.decode(errors="replace") if msg.data else ""
            task = msg.context.task_id[:8] if msg.context else "?"
            print(f"{task} | {data}")
    except KeyboardInterrupt:
        pass
    finally:
        client.close()


# --------------------------------------------------------------------- watch

def cmd_watch(args):
    """Stream matching store events over the Watch API (server-side
    selectors — watchapi.WatchSelector; reference swarmctl has no watch
    command, but the API it drives is manager/watchapi/watch.go)."""
    from ..api.types import NodeRole, TaskState
    from ..rpc.client import RPCClient
    from ..store.watch import ChannelClosed
    from ..watchapi.watch import WatchSelector

    def parse_kv(items):
        out = {}
        for it in items or []:
            k, _, v = it.partition("=")
            out[k] = v
        return out

    sel = WatchSelector(
        kind=args.kind or "",
        id=args.id or "",
        id_prefix=args.id_prefix or "",
        name=args.name or "",
        name_prefix=args.name_prefix or "",
        labels=parse_kv(args.label),
        custom=parse_kv(args.custom),
    )
    if args.service:
        ctl = _control(args)
        sel.kind = sel.kind or "task"
        sel.service_id = _find_service(ctl, args.service).id
    if args.node:
        sel.kind = sel.kind or "task"
        sel.node_id = args.node
    if args.slot is not None:
        sel.kind = sel.kind or "task"
        sel.slot = args.slot
    if args.desired_state:
        sel.kind = sel.kind or "task"
        try:
            sel.desired_state = TaskState[args.desired_state.upper()]
        except KeyError:
            _die(f"unknown task state {args.desired_state!r} (one of: "
                 + ", ".join(s.name.lower() for s in TaskState) + ")")
    if args.role:
        sel.kind = sel.kind or "node"
        try:
            sel.role = NodeRole[args.role.upper()]
        except KeyError:
            _die(f"unknown node role {args.role!r} (worker or manager)")
    try:
        sel.validate()                      # fail here, not as a bare
    except ValueError as exc:               # server-side stream close
        _die(str(exc))

    if getattr(args, "socket", None):
        client = RPCClient(f"unix://{args.socket}")
    else:
        client = RPCClient(args.addr, security=_load_identity(args.identity))
    ch = client.stream("watch.events", selectors=[sel],
                       since_version=args.resume_from)
    try:
        while True:
            try:
                ev = ch.get(timeout=1.0)
            except TimeoutError:
                continue
            except ChannelClosed as exc:
                if getattr(exc, "error", None) is not None:
                    _die(f"watch failed: {exc.error}")
                break
            obj = getattr(ev, "obj", None)
            if obj is None:
                continue
            action = type(ev).__name__.removeprefix("Event").lower()
            extra = ""
            if obj.TABLE == "task":
                extra = (f" service={obj.service_id} slot={obj.slot}"
                         f" node={obj.node_id or '-'}"
                         f" state={_state_name(obj.status.state)}")
            print(f"{action} {obj.TABLE} {_short(obj.id)}{extra}",
                  flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        client.close()


# --------------------------------------------------------------------- main

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="swarmctl")
    ap.add_argument("--addr", default=os.environ.get("SWARMCTL_ADDR"),
                    help="manager RPC address (host:port)")
    ap.add_argument("--identity",
                    default=os.environ.get("SWARMCTL_IDENTITY"),
                    help="node state dir holding cert.pem/key.json/ca.pem")
    ap.add_argument("--socket", default=os.environ.get("SWARMCTL_SOCKET"),
                    help="local manager control socket "
                         "(<state-dir>/swarmd.sock); no TLS identity needed")
    sub = ap.add_subparsers(dest="cmd", required=True)

    # service
    svc = sub.add_parser("service").add_subparsers(dest="sub", required=True)
    p = svc.add_parser("create")
    p.add_argument("--name", required=True)
    p.add_argument("--image", default=None)
    p.add_argument("--command", default=None,
                   help="shell-quoted command to run (subprocess executor)")
    p.add_argument("--replicas", type=int, default=1)
    p.add_argument("--mode", default="replicated",
                   choices=["replicated", "global", "replicated_job",
                            "global_job"])
    p.add_argument("--constraint", action="append")
    p.add_argument("--label", action="append")
    p.add_argument("--env", action="append")
    p.add_argument("--network", action="append",
                   help="attach to a network (name or id); repeatable")
    p.add_argument("--publish", action="append", metavar="TARGET[:PUB][/P]",
                   help="publish a port, e.g. 80, 80:8080, 53:53/udp")
    p.add_argument("--publish-mode", default="ingress",
                   choices=["ingress", "host"])
    p.add_argument("--secret", action="append", metavar="NAME[:TARGET]",
                   help="attach a secret by name; repeatable")
    p.add_argument("--config", action="append", metavar="NAME[:TARGET]",
                   help="attach a config by name; repeatable")
    p.add_argument("--update-parallelism", type=int, default=None)
    p.add_argument("--update-delay", type=float, default=None)
    p.set_defaults(func=cmd_service_create)
    p = svc.add_parser("ls")
    p.set_defaults(func=cmd_service_ls)
    p = svc.add_parser("inspect")
    p.add_argument("service")
    p.set_defaults(func=cmd_service_inspect)
    p = svc.add_parser("update")
    p.add_argument("service")
    p.add_argument("--replicas", type=int, default=None)
    p.add_argument("--command", default=None)
    p.add_argument("--image", default=None)
    p.add_argument("--env", action="append", default=None,
                   help="replace the env list; repeatable")
    p.add_argument("--constraint", action="append", default=None,
                   help="replace placement constraints; repeatable")
    p.add_argument("--label-add", action="append", metavar="K=V")
    p.add_argument("--label-rm", action="append", metavar="K")
    p.add_argument("--force", action="store_true")
    p.add_argument("--rollback", action="store_true",
                   help="revert to the previous service spec")
    p.add_argument("--update-parallelism", type=int, default=None)
    p.add_argument("--update-delay", type=float, default=None)
    p.add_argument("--update-order", default=None,
                   choices=["stop-first", "start-first"])
    p.set_defaults(func=cmd_service_update)
    p = svc.add_parser("rm")
    p.add_argument("service")
    p.set_defaults(func=cmd_service_rm)
    p = svc.add_parser("scale")
    p.add_argument("target", help="<service>=<replicas>")
    p.set_defaults(func=cmd_service_scale)

    # task
    task = sub.add_parser("task").add_subparsers(dest="sub", required=True)
    p = task.add_parser("ls")
    p.add_argument("--service", default=None)
    p.set_defaults(func=cmd_task_ls)
    p = task.add_parser("inspect")
    p.add_argument("task")
    p.set_defaults(func=cmd_task_inspect)

    # watch
    p = sub.add_parser("watch")
    p.add_argument("--kind", default=None,
                   help="object kind (task/node/service/…); inferred from "
                        "kind-specific flags when omitted")
    p.add_argument("--id", default=None)
    p.add_argument("--id-prefix", default=None)
    p.add_argument("--name", default=None)
    p.add_argument("--name-prefix", default=None)
    p.add_argument("--label", action="append", metavar="K=V")
    p.add_argument("--custom", action="append", metavar="K=V",
                   help="custom index (Annotations.indices) equality")
    p.add_argument("--service", default=None,
                   help="tasks of this service (name or id)")
    p.add_argument("--node", default=None, help="tasks on this node id")
    p.add_argument("--slot", type=int, default=None)
    p.add_argument("--desired-state", default=None,
                   help="task desired state name, e.g. running")
    p.add_argument("--role", default=None,
                   help="node role name (worker/manager)")
    p.add_argument("--resume-from", type=int, default=None,
                   help="replay committed changes after this store version")
    p.set_defaults(func=cmd_watch)

    # node
    node = sub.add_parser("node").add_subparsers(dest="sub", required=True)
    p = node.add_parser("ls")
    p.set_defaults(func=cmd_node_ls)
    p = node.add_parser("inspect")
    p.add_argument("node")
    p.set_defaults(func=cmd_node_inspect)
    p = node.add_parser("update")
    p.add_argument("node")
    p.add_argument("--label-add", action="append", metavar="K=V")
    p.add_argument("--label-rm", action="append", metavar="K")
    p.add_argument("--availability", default=None,
                   choices=["active", "pause", "drain"])
    p.set_defaults(func=cmd_node_update)
    for name, fn in (("promote", cmd_node_promote),
                     ("demote", cmd_node_demote),
                     ("drain", cmd_node_drain),
                     ("pause", cmd_node_pause),
                     ("activate", cmd_node_activate)):
        p = node.add_parser(name)
        p.add_argument("node")
        p.set_defaults(func=fn)
    p = node.add_parser("rm")
    p.add_argument("node")
    p.add_argument("--force", action="store_true")
    p.set_defaults(func=cmd_node_rm)

    # cluster
    cluster = sub.add_parser("cluster").add_subparsers(dest="sub",
                                                       required=True)
    p = cluster.add_parser("inspect")
    p.set_defaults(func=cmd_cluster_inspect)
    p = cluster.add_parser("update")
    p.add_argument("--rotate-worker-token", action="store_true")
    p.add_argument("--rotate-manager-token", action="store_true")
    p.add_argument("--rotate-unlock-key", action="store_true")
    p.add_argument("--rotate-ca", action="store_true",
                   help="force a root CA rotation to a fresh root")
    p.add_argument("--signing-ca-cert", metavar="PEM_FILE",
                   help="rotate to this root certificate")
    p.add_argument("--signing-ca-key", metavar="PEM_FILE",
                   help="private key for --signing-ca-cert")
    p.add_argument("--external-ca", action="append", metavar="URL[,opts]",
                   help="external cfssl CA: url[,ca_cert=path]; repeatable")
    p.add_argument("--cert-expiry", type=float, default=None,
                   help="node certificate lifetime in seconds")
    p.set_defaults(func=cmd_cluster_update)
    p = cluster.add_parser("unlockkey")
    p.add_argument("--rotate", action="store_true")
    p.set_defaults(func=cmd_cluster_unlockkey)

    # secret / config
    net = sub.add_parser("network").add_subparsers(dest="sub", required=True)
    p = net.add_parser("create")
    p.add_argument("name")
    p.add_argument("--subnet", default=None, help="CIDR, e.g. 10.5.0.0/24")
    p.add_argument("--ingress", action="store_true")
    p.set_defaults(func=cmd_network_create)
    p = net.add_parser("ls")
    p.set_defaults(func=cmd_network_ls)
    p = net.add_parser("inspect")
    p.add_argument("network")
    p.set_defaults(func=cmd_network_inspect)
    p = net.add_parser("rm")
    p.add_argument("network")
    p.set_defaults(func=cmd_network_rm)

    sec = sub.add_parser("secret").add_subparsers(dest="sub", required=True)
    p = sec.add_parser("create")
    p.add_argument("name")
    p.add_argument("--data", default=None,
                   help="literal value (default: read stdin)")
    p.add_argument("--templating", action="store_true",
                   help="expand template placeholders in the payload at "
                        "delivery (reference SecretSpec.Templating)")
    p.set_defaults(func=cmd_secret_create)
    p = sec.add_parser("ls")
    p.set_defaults(func=cmd_secret_ls)
    p = sec.add_parser("rm")
    p.add_argument("name")
    p.set_defaults(func=cmd_secret_rm)

    cfg = sub.add_parser("config").add_subparsers(dest="sub", required=True)
    p = cfg.add_parser("create")
    p.add_argument("name")
    p.add_argument("--data", default=None)
    p.add_argument("--templating", action="store_true",
                   help="expand template placeholders in the payload at "
                        "delivery (reference ConfigSpec.Templating)")
    p.set_defaults(func=cmd_config_create)
    p = cfg.add_parser("ls")
    p.set_defaults(func=cmd_config_ls)
    p = cfg.add_parser("rm")
    p.add_argument("name")
    p.set_defaults(func=cmd_config_rm)

    vol = sub.add_parser("volume").add_subparsers(dest="sub", required=True)
    p = vol.add_parser("create")
    p.add_argument("name")
    p.add_argument("--driver", required=True,
                   help="CSI plugin name (see swarmd --csi-plugin)")
    p.add_argument("--group", default=None)
    p.add_argument("--scope", default="multi", choices=["single", "multi"])
    p.add_argument("--sharing", default="all",
                   choices=["none", "readonly", "onewriter", "all"])
    p.set_defaults(func=cmd_volume_create)
    p = vol.add_parser("ls")
    p.set_defaults(func=cmd_volume_ls)
    p = vol.add_parser("rm")
    p.add_argument("name")
    p.add_argument("--force", action="store_true",
                   help="remove even while published")
    p.set_defaults(func=cmd_volume_rm)

    # top — one-shot cluster telemetry rollup (ISSUE 15)
    p = sub.add_parser("top")
    p.add_argument("--window", type=float, default=None,
                   help="also report ring percentiles over the trailing "
                        "window (seconds)")
    p.add_argument("--json", action="store_true",
                   help="raw rollup JSON instead of the table")
    p.set_defaults(func=cmd_top)

    # logs
    p = sub.add_parser("logs")
    p.add_argument("service")
    p.add_argument("--follow", "-f", action="store_true")
    p.set_defaults(func=cmd_logs)

    args = ap.parse_args(argv)
    if not args.socket:
        if not args.addr:
            _die("--addr (or SWARMCTL_ADDR), or --socket, is required")
        if not args.identity:
            _die("--identity (or SWARMCTL_IDENTITY) is required")
    args.func(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
