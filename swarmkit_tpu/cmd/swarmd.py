"""swarmd: the cluster node daemon (reference swarmd/cmd/swarmd/main.go).

    # first manager (creates the cluster)
    python -m swarmkit_tpu.cmd.swarmd --state-dir /tmp/m1 \
        --listen-addr 127.0.0.1:4242

    # additional manager / worker (token decides the role)
    python -m swarmkit_tpu.cmd.swarmd --state-dir /tmp/m2 \
        --listen-addr 127.0.0.1:4243 \
        --join-addr 127.0.0.1:4242 --join-token SWMTKN-1-…

On startup the first manager prints both join tokens. The daemon runs until
SIGINT/SIGTERM.
"""
from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def _connect_csi_plugins(sockets):
    if not sockets:
        return None
    from ..csi.plugin import PluginGetter
    from ..csi.wire import RemoteCSIPlugin

    getter = PluginGetter()
    seen: dict[str, str] = {}
    for sock in sockets:
        plugin = RemoteCSIPlugin(sock).connect()
        if plugin.name in seen:
            raise SystemExit(
                f"error: CSI plugins at {seen[plugin.name]} and {sock} "
                f"both report the name {plugin.name!r}; give one a "
                "distinct --name")
        seen[plugin.name] = sock
        getter.add(plugin)
        print(f"SWARM_CSI_PLUGIN name={plugin.name} socket={sock}",
              flush=True)
    return getter


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="swarmd", description="swarmkit-tpu cluster node daemon")
    ap.add_argument("--state-dir", required=True,
                    help="directory for identity, raft WAL, task state")
    ap.add_argument("--listen-addr", default="127.0.0.1:0",
                    help="host:port for the RPC listener (managers)")
    ap.add_argument("--advertise-addr", default=None,
                    help="externally dialable address (defaults to listen)")
    ap.add_argument("--join-addr", default=None,
                    help="comma-separated manager endpoints to join via")
    ap.add_argument("--join-token", default=None,
                    help="cluster join token (role is derived from it)")
    ap.add_argument("--executor", choices=["subprocess", "fake"],
                    default="subprocess",
                    help="task executor: real child processes, or a no-op "
                         "fake for load/testing")
    ap.add_argument("--hostname", default=None)
    ap.add_argument("--heartbeat-period", type=float, default=5.0)
    ap.add_argument("--tick-interval", type=float, default=0.1,
                    help="raft logical-clock tick (election ~10-20 ticks)")
    ap.add_argument("--scheduler-backend",
                    choices=["auto", "cpu", "jax", "mesh"],
                    default="auto",
                    help="placement backend: auto picks per tick by "
                         "task-times-node product against --jax-threshold; "
                         "cpu/jax pin the path; mesh pins jax AND shards "
                         "the device-resident node state over every "
                         "visible device (parallel/mesh.py) (SURVEY §7)")
    ap.add_argument("--jax-threshold", type=int, default=None,
                    metavar="PRODUCT",
                    help="task*node product above which auto uses the "
                         "accelerator (default 200000; tune ~100x lower "
                         "for PCIe/on-host devices than for a tunneled "
                         "dev link — see BASELINE.md)")
    ap.add_argument("--scheduler-strategy",
                    choices=["spread", "binpack", "topology"],
                    default="spread",
                    help="placement scoring engine (ISSUE 19): spread "
                         "balances, binpack fills the fullest feasible "
                         "node first (preferences ignored), topology "
                         "spreads with --scheduler-topology as the "
                         "outermost balance axis")
    ap.add_argument("--scheduler-topology", default=None,
                    metavar="DESCRIPTOR",
                    help="topology descriptor for "
                         "--scheduler-strategy topology, e.g. "
                         "node.labels.zone")
    ap.add_argument("--scheduler-pipeline", action="store_true",
                    help="pipeline scheduler ticks on the jax backend: "
                         "commit wave k under wave k+1's device transfer "
                         "(sustained-load throughput; +1 debounce latency)")
    ap.add_argument("--scheduler-async-commit", action="store_true",
                    help="with --scheduler-pipeline: run the commit's "
                         "heavy half (slot materialization, add_task "
                         "walk, store write-back) on a background "
                         "commit plane overlapping the next wave's "
                         "device dispatch and transfer (ops/commit.py)")
    ap.add_argument("--dispatcher-shards", type=int, default=None,
                    metavar="P",
                    help="dispatcher fan-out shard count (session flush "
                         "plane + heartbeat wheel slices); default "
                         "min(4, cores)")
    ap.add_argument("--force-new-cluster", action="store_true",
                    help="disaster recovery: restart as a single-member "
                         "quorum keeping replicated state")
    ap.add_argument("--listen-metrics", default=None, metavar="ADDR",
                    help="serve /metrics /healthz /debug/* on host:port")
    ap.add_argument("--listen-debug", default=None, metavar="ADDR",
                    help="alias for --listen-metrics (reference has both)")
    ap.add_argument("--no-control-socket", action="store_true",
                    help="do not serve the local unix control socket "
                         "(<state-dir>/swarmd.sock)")
    ap.add_argument("--cert-expiry", type=float, default=None,
                    metavar="SECONDS", help="node certificate lifetime")
    ap.add_argument("--external-ca", default=None, metavar="URL",
                    help="cfssl-compatible signing endpoint "
                         "(protocol=cfssl,url=… also accepted)")
    ap.add_argument("--csi-plugin", action="append", default=[],
                    metavar="SOCKET",
                    help="attach an external CSI plugin by its unix "
                         "socket (repeatable); the plugin process must "
                         "speak the swarmkit_tpu.csi.wire protocol "
                         "(see csi_plugin_example)")
    ap.add_argument("--fips", action="store_true",
                    help="run in FIPS mode; bootstrapping with this flag "
                         "creates a mandatory-FIPS cluster that only "
                         "FIPS-enabled nodes may join")
    ap.add_argument("--autolock", action="store_true",
                    help="seal the raft DEK under an operator-held key; "
                         "printed once as SWARM_UNLOCK_KEY")
    ap.add_argument("--unlock-key", default=None,
                    help="key to unseal an autolocked state dir")
    ap.add_argument("--generic-node-resources", default=None,
                    metavar="SPEC", help="comma list like gpu=4,fpga=1 "
                    "advertised as generic resources")
    ap.add_argument("--log-level", default="info",
                    choices=["debug", "info", "warning", "error"])
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper()),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    if args.executor == "subprocess":
        from ..agent.subprocexec import SubprocessExecutor

        executor = SubprocessExecutor(args.state_dir, hostname=args.hostname)
    else:
        from ..agent.testutils import FakeExecutor

        executor = FakeExecutor({"*": {"run_forever": True}},
                                hostname=args.hostname or "fake")

    from ..node.daemon import SwarmNode

    generic = None
    if args.generic_node_resources:
        from ..api.genericresource import GenericResourceError, parse_cmd

        try:
            generic = parse_cmd(args.generic_node_resources)
        except GenericResourceError as exc:
            ap.error(str(exc))

    external_ca = None
    if args.external_ca:
        from ..ca.external import ExternalCA

        # reference cli/external_ca.go accepts protocol=cfssl,url=…
        url = args.external_ca
        for field in url.split(","):
            k, _, v = field.partition("=")
            if k.strip() == "url":
                url = v.strip()
        external_ca = ExternalCA(url)

    try:
        csi_plugins = _connect_csi_plugins(args.csi_plugin)
    except Exception as exc:
        print(f"error: cannot attach CSI plugin: {exc}", file=sys.stderr,
              flush=True)
        return 1

    node = SwarmNode(
        state_dir=args.state_dir,
        executor=executor,
        listen_addr=args.listen_addr,
        advertise_addr=args.advertise_addr,
        join_addr=args.join_addr,
        join_token=args.join_token,
        heartbeat_period=args.heartbeat_period,
        tick_interval=args.tick_interval,
        force_new_cluster=args.force_new_cluster,
        control_socket=not args.no_control_socket,
        cert_expiry=args.cert_expiry,
        external_ca=external_ca,
        generic_resources=generic,
        autolock=args.autolock,
        kek=args.unlock_key.encode() if args.unlock_key else None,
        fips=args.fips,
        csi_plugins=csi_plugins,
        scheduler_backend=args.scheduler_backend,
        jax_threshold=args.jax_threshold,
        scheduler_pipeline=args.scheduler_pipeline,
        scheduler_async_commit=args.scheduler_async_commit,
        scheduler_strategy=args.scheduler_strategy,
        scheduler_topology=args.scheduler_topology,
        dispatcher_shards=args.dispatcher_shards,
    )
    try:
        node.start()
    except SwarmNode.MandatoryFIPSError as exc:
        print(f"error: {exc}", file=sys.stderr, flush=True)
        sys.exit(1)

    debug_server = None
    debug_addr = args.listen_metrics or args.listen_debug
    if debug_addr:
        from ..node.debugserver import DebugServer

        debug_server = DebugServer(debug_addr, node)
        debug_server.start()
        print(f"SWARM_METRICS_ADDR={debug_server.addr}", flush=True)
    if args.autolock and node.kek:
        print(f"SWARM_UNLOCK_KEY={node.kek.decode()}", flush=True)

    log = logging.getLogger("swarmd")
    log.info("node %s up (role=%s, addr=%s)", node.node_id,
             "manager" if node.manager is not None else "worker", node.addr)
    if node.manager is not None and node.join_addr is None:
        # freshly bootstrapped cluster: print tokens for joiners. Cluster
        # seeding runs on the manager leadership thread — wait for it.
        import time

        cluster = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            cluster = node.store.view(
                lambda tx: tx.get_cluster(node.manager.cluster_id))
            if cluster is not None and cluster.root_ca is not None:
                break
            time.sleep(0.2)
        if cluster is not None and cluster.root_ca is not None:
            print(f"SWARM_MANAGER_TOKEN={cluster.root_ca.join_token_manager}",
                  flush=True)
            print(f"SWARM_WORKER_TOKEN={cluster.root_ca.join_token_worker}",
                  flush=True)
        else:
            log.warning("cluster object not seeded after 30s; "
                        "join tokens unavailable")
    print(f"SWARM_NODE_READY addr={node.addr or ''} id={node.node_id}",
          flush=True)

    stop = threading.Event()

    def on_signal(_sig, _frame):
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    stop.wait()
    if debug_server is not None:
        debug_server.stop()
    node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
