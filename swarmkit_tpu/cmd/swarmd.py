"""swarmd: the cluster node daemon (reference swarmd/cmd/swarmd/main.go).

    # first manager (creates the cluster)
    python -m swarmkit_tpu.cmd.swarmd --state-dir /tmp/m1 \
        --listen-addr 127.0.0.1:4242

    # additional manager / worker (token decides the role)
    python -m swarmkit_tpu.cmd.swarmd --state-dir /tmp/m2 \
        --listen-addr 127.0.0.1:4243 \
        --join-addr 127.0.0.1:4242 --join-token SWMTKN-1-…

On startup the first manager prints both join tokens. The daemon runs until
SIGINT/SIGTERM.
"""
from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="swarmd", description="swarmkit-tpu cluster node daemon")
    ap.add_argument("--state-dir", required=True,
                    help="directory for identity, raft WAL, task state")
    ap.add_argument("--listen-addr", default="127.0.0.1:0",
                    help="host:port for the RPC listener (managers)")
    ap.add_argument("--advertise-addr", default=None,
                    help="externally dialable address (defaults to listen)")
    ap.add_argument("--join-addr", default=None,
                    help="comma-separated manager endpoints to join via")
    ap.add_argument("--join-token", default=None,
                    help="cluster join token (role is derived from it)")
    ap.add_argument("--executor", choices=["subprocess", "fake"],
                    default="subprocess",
                    help="task executor: real child processes, or a no-op "
                         "fake for load/testing")
    ap.add_argument("--hostname", default=None)
    ap.add_argument("--heartbeat-period", type=float, default=5.0)
    ap.add_argument("--tick-interval", type=float, default=0.1,
                    help="raft logical-clock tick (election ~10-20 ticks)")
    ap.add_argument("--force-new-cluster", action="store_true",
                    help="disaster recovery: restart as a single-member "
                         "quorum keeping replicated state")
    ap.add_argument("--log-level", default="info",
                    choices=["debug", "info", "warning", "error"])
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper()),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    if args.executor == "subprocess":
        from ..agent.subprocexec import SubprocessExecutor

        executor = SubprocessExecutor(args.state_dir, hostname=args.hostname)
    else:
        from ..agent.testutils import FakeExecutor

        executor = FakeExecutor({"*": {"run_forever": True}},
                                hostname=args.hostname or "fake")

    from ..node.daemon import SwarmNode

    node = SwarmNode(
        state_dir=args.state_dir,
        executor=executor,
        listen_addr=args.listen_addr,
        advertise_addr=args.advertise_addr,
        join_addr=args.join_addr,
        join_token=args.join_token,
        heartbeat_period=args.heartbeat_period,
        tick_interval=args.tick_interval,
        force_new_cluster=args.force_new_cluster,
    )
    node.start()

    log = logging.getLogger("swarmd")
    log.info("node %s up (role=%s, addr=%s)", node.node_id,
             "manager" if node.manager is not None else "worker", node.addr)
    if node.manager is not None and node.join_addr is None:
        # freshly bootstrapped cluster: print tokens for joiners. Cluster
        # seeding runs on the manager leadership thread — wait for it.
        import time

        cluster = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            cluster = node.store.view(
                lambda tx: tx.get_cluster(node.manager.cluster_id))
            if cluster is not None and cluster.root_ca is not None:
                break
            time.sleep(0.2)
        if cluster is not None and cluster.root_ca is not None:
            print(f"SWARM_MANAGER_TOKEN={cluster.root_ca.join_token_manager}",
                  flush=True)
            print(f"SWARM_WORKER_TOKEN={cluster.root_ca.join_token_worker}",
                  flush=True)
        else:
            log.warning("cluster object not seeded after 30s; "
                        "join tokens unavailable")
    print(f"SWARM_NODE_READY addr={node.addr or ''} id={node.node_id}",
          flush=True)

    stop = threading.Event()

    def on_signal(_sig, _frame):
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    stop.wait()
    node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
