"""swarm-rafttool: offline raft state inspection (reference
swarmd/cmd/swarm-rafttool/{dump,common}.go).

Decrypts a stopped manager's raft WAL + snapshot using the DEK stored in
the node key file's headers and dumps them in human/JSON form — the
disaster-inspection tool you reach for when a manager won't start.

    python -m swarmkit_tpu.cmd.rafttool dump --state-dir /tmp/m1
    python -m swarmkit_tpu.cmd.rafttool dump-wal --state-dir /tmp/m1
    python -m swarmkit_tpu.cmd.rafttool dump-snapshot --state-dir /tmp/m1
    python -m swarmkit_tpu.cmd.rafttool dump-object --state-dir /tmp/m1 \
        --kind tasks
    python -m swarmkit_tpu.cmd.rafttool renewcert --state-dir /tmp/m1

renewcert re-issues an expired manager TLS cert offline from the CA
material in the raft log (reference swarm-rafttool/renewcert.go).
"""
from __future__ import annotations

import argparse
import dataclasses
import enum
import json
import os
import sys


def _die(msg: str):
    print(f"rafttool: {msg}", file=sys.stderr)
    sys.exit(1)


def _jsonable(obj, depth=0):
    if depth > 12:
        return "…"
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _jsonable(getattr(obj, f.name), depth + 1)
                for f in dataclasses.fields(obj)}
    if isinstance(obj, enum.Enum):
        return obj.name
    if isinstance(obj, bytes):
        try:
            return obj.decode()
        except UnicodeDecodeError:
            return f"<{len(obj)} bytes>"
    if isinstance(obj, dict):
        return {str(k): _jsonable(v, depth + 1) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v, depth + 1) for v in obj]
    return obj


def _open_storage(args):
    """DEK from the node key file headers → RaftStorage over the state dir
    (manager/deks.go keeps raft DEKs in the TLS key's headers)."""
    from ..ca import KeyReadWriter
    from ..raft.storage import RaftStorage

    key_path = os.path.join(args.state_dir, "key.json")
    kek = args.kek.encode() if args.kek else None
    try:
        _key, headers = KeyReadWriter(key_path, kek).read()
    except OSError as exc:
        _die(f"cannot read {key_path}: {exc}")
    dek_hex = (headers or {}).get("raft-dek")
    if not dek_hex:
        _die("no raft DEK in the key file headers (not a manager state dir?)")
    return RaftStorage(os.path.join(args.state_dir, "raft"),
                       dek=dek_hex.encode())


def _load_storage(args):
    state = _open_storage(args).load()
    if state is None:
        _die("no persisted raft state found")
    return state


def cmd_dump(args):
    state = _load_storage(args)
    print(json.dumps({
        "term": state.term,
        "voted_for": state.voted_for,
        "commit_index": state.commit_index,
        "snapshot_index": state.snapshot_index,
        "snapshot_term": state.snapshot_term,
        "wal_entries": len(state.entries),
        "first_wal_index": state.entries[0].index if state.entries else None,
        "last_wal_index": state.entries[-1].index if state.entries else None,
        "members": {rid: {"node_id": p.node_id, "addr": p.addr}
                    for rid, p in state.members.items()},
        "has_snapshot": state.snapshot_data is not None,
    }, indent=2))


def cmd_dump_wal(args):
    state = _load_storage(args)
    for e in state.entries:
        kind = "conf-change" if e.kind == 1 else "entry"
        summary = None
        if e.kind == 1:
            summary = _jsonable(e.data)
        elif e.data is not None:
            summary = [
                {"action": getattr(a, "kind", "?"),
                 "object": type(getattr(a, "obj", None)).__name__,
                 "id": getattr(getattr(a, "obj", None), "id", None)}
                for a in e.data
            ]
        print(json.dumps({"index": e.index, "term": e.term, "kind": kind,
                          "request_id": e.request_id or None,
                          "data": summary}))


def cmd_dump_snapshot(args):
    state = _load_storage(args)
    if state.snapshot_data is None:
        _die("no snapshot present")
    snap = state.snapshot_data
    out = {"snapshot_index": state.snapshot_index,
           "snapshot_term": state.snapshot_term}
    if isinstance(snap, dict):
        out["tables"] = {k: (len(v) if isinstance(v, (list, dict)) else "?")
                         for k, v in snap.items()}
    print(json.dumps(out, indent=2))


def _replay_store(args):
    """Reconstruct the replicated store at the WAL tail (snapshot + WAL
    replay through the same proposer seam the live manager uses)."""
    from ..raft.node import RaftNode
    from ..raft.proposer import RaftProposer
    from ..store.memory import MemoryStore

    class _NullTransport:
        def send(self, msg):
            pass

        def active(self, peer_id):
            return False

    storage = _open_storage(args)
    node = RaftNode(raft_id=0, transport=_NullTransport(), storage=storage,
                    auto_recover=False)
    proposer = RaftProposer(node)
    store = MemoryStore(proposer=proposer)
    proposer.attach_store(store)  # replays snapshot + WAL into the store
    return store


def cmd_dump_object(args):
    """Reconstruct the store at the WAL tail and dump one table."""
    store = _replay_store(args)

    finders = {
        "tasks": lambda tx: tx.find_tasks(),
        "services": lambda tx: tx.find_services(),
        "nodes": lambda tx: tx.find_nodes(),
        "clusters": lambda tx: tx.find_clusters(),
        "secrets": lambda tx: tx.find_secrets(),
        "configs": lambda tx: tx.find_configs(),
        "networks": lambda tx: tx.find_networks(),
        "volumes": lambda tx: tx.find_volumes(),
    }
    finder = finders.get(args.kind)
    if finder is None:
        _die(f"unknown kind {args.kind!r}; one of {sorted(finders)}")
    objs = store.view(finder)
    for o in objs:
        print(json.dumps(_jsonable(o)))


def cmd_renewcert(args):
    """Offline TLS-certificate renewal from a downed manager's own state
    dir (reference swarm-rafttool/renewcert.go:16-101): an EXPIRED manager
    cert can't reach any CA server — nothing will accept the dial — so
    the cert is re-issued directly from the cluster CA material in the
    raft log. Preserves the node's CN/OU/O identity and the key file's
    headers (the raft DEKs live there); refreshes ca.pem in case the
    trust anchor rotated while the node was down."""
    from ..ca import KeyReadWriter
    from ..ca.certificates import RootCA, create_csr, parse_cert_identity

    key_path = os.path.join(args.state_dir, "key.json")
    cert_path = os.path.join(args.state_dir, "cert.pem")
    kek = args.kek.encode() if args.kek else None
    krw = KeyReadWriter(key_path, kek)
    try:
        _old_key, headers = krw.read()
        with open(cert_path, "rb") as f:
            old_cert = f.read()
    except OSError as exc:
        _die(f"cannot load node identity: {exc}")
    # identity from the (possibly expired) cert — expiry is irrelevant,
    # only the subject matters; a new cert is issued regardless
    ident = parse_cert_identity(old_cert)

    store = _replay_store(args)
    clusters = store.view(lambda tx: tx.find_clusters())
    if not clusters:
        _die("no cluster object in the raft log; cannot renew")
    rca = clusters[0].root_ca
    if rca is None or not rca.ca_cert_pem or not rca.ca_key_pem:
        _die("no CA key material in the raft log (external CA?); "
             "cannot renew offline")
    expiry = clusters[0].spec.ca.node_cert_expiry
    root = RootCA(rca.ca_cert_pem, rca.ca_key_pem)

    new_key, csr = create_csr(ident.node_id, ident.role, ident.org)
    new_cert = root.sign_csr(csr, expiry=expiry,
                             subject=(ident.node_id, ident.role, ident.org))
    # key.json and cert.pem are two files: a crash between their writes
    # leaves a mismatched identity. Minimize the window to back-to-back
    # atomic renames by staging EVERYTHING first (the slow IO), and note
    # that any intermediate state is healed by simply re-running this
    # command (identity comes from the cert subject, which both old and
    # new certs share; nothing here validates key/cert pairing).
    tmp = cert_path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(new_cert)
        f.flush()
        os.fsync(f.fileno())
    ca_tmp = os.path.join(args.state_dir, "ca.pem.tmp")
    with open(ca_tmp, "wb") as f:
        f.write(root.cert_pem)
        f.flush()
        os.fsync(f.fileno())
    krw.write(new_key, headers)        # headers (raft DEKs) ride along
    os.replace(tmp, cert_path)
    os.replace(ca_tmp, os.path.join(args.state_dir, "ca.pem"))
    print(json.dumps({"renewed": ident.node_id,
                      "role": ident.role, "org": ident.org}))


def main(argv=None) -> int:
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--state-dir", required=True)
    common.add_argument("--kek", default=None,
                        help="key-encryption key if the node key is sealed")
    ap = argparse.ArgumentParser(prog="swarm-rafttool")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("dump", parents=[common]).set_defaults(func=cmd_dump)
    sub.add_parser("dump-wal", parents=[common]).set_defaults(
        func=cmd_dump_wal)
    sub.add_parser("dump-snapshot", parents=[common]).set_defaults(
        func=cmd_dump_snapshot)
    p = sub.add_parser("dump-object", parents=[common])
    p.add_argument("--kind", required=True)
    p.set_defaults(func=cmd_dump_object)
    sub.add_parser("renewcert", parents=[common]).set_defaults(
        func=cmd_renewcert)
    args = ap.parse_args(argv)
    try:
        args.func(args)
    except BrokenPipeError:
        # `| head` closed stdout; normal for a dump tool
        try:
            sys.stdout.close()
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
