"""swarm-rafttool: offline raft state inspection (reference
swarmd/cmd/swarm-rafttool/{dump,common}.go).

Decrypts a stopped manager's raft WAL + snapshot using the DEK stored in
the node key file's headers and dumps them in human/JSON form — the
disaster-inspection tool you reach for when a manager won't start.

    python -m swarmkit_tpu.cmd.rafttool dump --state-dir /tmp/m1
    python -m swarmkit_tpu.cmd.rafttool dump-wal --state-dir /tmp/m1
    python -m swarmkit_tpu.cmd.rafttool dump-snapshot --state-dir /tmp/m1
    python -m swarmkit_tpu.cmd.rafttool dump-object --state-dir /tmp/m1 \
        --kind tasks
"""
from __future__ import annotations

import argparse
import dataclasses
import enum
import json
import os
import sys


def _die(msg: str):
    print(f"rafttool: {msg}", file=sys.stderr)
    sys.exit(1)


def _jsonable(obj, depth=0):
    if depth > 12:
        return "…"
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _jsonable(getattr(obj, f.name), depth + 1)
                for f in dataclasses.fields(obj)}
    if isinstance(obj, enum.Enum):
        return obj.name
    if isinstance(obj, bytes):
        try:
            return obj.decode()
        except UnicodeDecodeError:
            return f"<{len(obj)} bytes>"
    if isinstance(obj, dict):
        return {str(k): _jsonable(v, depth + 1) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v, depth + 1) for v in obj]
    return obj


def _open_storage(args):
    """DEK from the node key file headers → RaftStorage over the state dir
    (manager/deks.go keeps raft DEKs in the TLS key's headers)."""
    from ..ca import KeyReadWriter
    from ..raft.storage import RaftStorage

    key_path = os.path.join(args.state_dir, "key.json")
    kek = args.kek.encode() if args.kek else None
    try:
        _key, headers = KeyReadWriter(key_path, kek).read()
    except OSError as exc:
        _die(f"cannot read {key_path}: {exc}")
    dek_hex = (headers or {}).get("raft-dek")
    if not dek_hex:
        _die("no raft DEK in the key file headers (not a manager state dir?)")
    return RaftStorage(os.path.join(args.state_dir, "raft"),
                       dek=dek_hex.encode())


def _load_storage(args):
    state = _open_storage(args).load()
    if state is None:
        _die("no persisted raft state found")
    return state


def cmd_dump(args):
    state = _load_storage(args)
    print(json.dumps({
        "term": state.term,
        "voted_for": state.voted_for,
        "commit_index": state.commit_index,
        "snapshot_index": state.snapshot_index,
        "snapshot_term": state.snapshot_term,
        "wal_entries": len(state.entries),
        "first_wal_index": state.entries[0].index if state.entries else None,
        "last_wal_index": state.entries[-1].index if state.entries else None,
        "members": {rid: {"node_id": p.node_id, "addr": p.addr}
                    for rid, p in state.members.items()},
        "has_snapshot": state.snapshot_data is not None,
    }, indent=2))


def cmd_dump_wal(args):
    state = _load_storage(args)
    for e in state.entries:
        kind = "conf-change" if e.kind == 1 else "entry"
        summary = None
        if e.kind == 1:
            summary = _jsonable(e.data)
        elif e.data is not None:
            summary = [
                {"action": getattr(a, "kind", "?"),
                 "object": type(getattr(a, "obj", None)).__name__,
                 "id": getattr(getattr(a, "obj", None), "id", None)}
                for a in e.data
            ]
        print(json.dumps({"index": e.index, "term": e.term, "kind": kind,
                          "request_id": e.request_id or None,
                          "data": summary}))


def cmd_dump_snapshot(args):
    state = _load_storage(args)
    if state.snapshot_data is None:
        _die("no snapshot present")
    snap = state.snapshot_data
    out = {"snapshot_index": state.snapshot_index,
           "snapshot_term": state.snapshot_term}
    if isinstance(snap, dict):
        out["tables"] = {k: (len(v) if isinstance(v, (list, dict)) else "?")
                         for k, v in snap.items()}
    print(json.dumps(out, indent=2))


def cmd_dump_object(args):
    """Reconstruct the store at the WAL tail and dump one table."""
    from ..raft.node import RaftNode
    from ..raft.proposer import RaftProposer
    from ..store.memory import MemoryStore

    class _NullTransport:
        def send(self, msg):
            pass

        def active(self, peer_id):
            return False

    storage = _open_storage(args)
    node = RaftNode(raft_id=0, transport=_NullTransport(), storage=storage,
                    auto_recover=False)
    proposer = RaftProposer(node)
    store = MemoryStore(proposer=proposer)
    proposer.attach_store(store)  # replays snapshot + WAL into the store

    finders = {
        "tasks": lambda tx: tx.find_tasks(),
        "services": lambda tx: tx.find_services(),
        "nodes": lambda tx: tx.find_nodes(),
        "clusters": lambda tx: tx.find_clusters(),
        "secrets": lambda tx: tx.find_secrets(),
        "configs": lambda tx: tx.find_configs(),
        "networks": lambda tx: tx.find_networks(),
        "volumes": lambda tx: tx.find_volumes(),
    }
    finder = finders.get(args.kind)
    if finder is None:
        _die(f"unknown kind {args.kind!r}; one of {sorted(finders)}")
    objs = store.view(finder)
    for o in objs:
        print(json.dumps(_jsonable(o)))


def main(argv=None) -> int:
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--state-dir", required=True)
    common.add_argument("--kek", default=None,
                        help="key-encryption key if the node key is sealed")
    ap = argparse.ArgumentParser(prog="swarm-rafttool")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("dump", parents=[common]).set_defaults(func=cmd_dump)
    sub.add_parser("dump-wal", parents=[common]).set_defaults(
        func=cmd_dump_wal)
    sub.add_parser("dump-snapshot", parents=[common]).set_defaults(
        func=cmd_dump_snapshot)
    p = sub.add_parser("dump-object", parents=[common])
    p.add_argument("--kind", required=True)
    p.set_defaults(func=cmd_dump_object)
    args = ap.parse_args(argv)
    try:
        args.func(args)
    except BrokenPipeError:
        # `| head` closed stdout; normal for a dump tool
        try:
            sys.stdout.close()
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
