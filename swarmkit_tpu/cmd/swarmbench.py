"""swarm-bench: cluster load generator + task-startup SLO harness
(reference cmd/swarm-bench).

The reference creates an N-replica service and has the containers phone
home over UDP (`collector.go`), reporting time-to-RUNNING percentiles.
This port reads the same signal from the cluster's own event plane: a
**watch-API collector** subscribes to task events
(`watch.events` stream, manager/watchapi) and stamps each task at
CREATE and at its first observed RUNNING — no store scans, no polling
bias. `--poll` keeps the original list_tasks scan loop as a fallback
for clusters without a reachable watch stream.

Two modes:

  * one-shot (default): create a service, measure time-to-RUNNING for
    every replica, report percentiles (the reference's shape);
  * `--churn`: a continuous load generator — rollout storms (every task
    replaced) alternating with scale up/down against one or more
    services for `--duration` seconds, collecting NEW→RUNNING samples
    the whole time. With `--slo "p50:0.5,p99:2.0"` the exit code
    asserts the objectives; the JSON report carries the percentiles,
    the SLO results, and (when the manager's lifecycle plane is armed —
    SWARMKIT_TPU_LIFECYCLE=1) the server-side stage-attribution report
    from `control.get_slo_report`.

Percentile math is the shared nearest-rank helper in utils/slo.py (the
old local `int(p/100*len(lat))` was biased: p50 of 2 samples returned
the max).

    python -m swarmkit_tpu.cmd.swarmbench --addr 127.0.0.1:4242 \
        --identity /tmp/m1 --replicas 100
    python -m swarmkit_tpu.cmd.swarmbench --addr ... --identity ... \
        --churn --duration 30 --replicas 20 --slo p50:1.0,p99:5.0
"""
from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time


# --------------------------------------------------------------- collector
class StartupCollector:
    """Accumulates per-task time-to-RUNNING from task events.

    Feed it store events (EventCreate/EventUpdate of Task — from a
    watch-API channel or an in-process store watch); it stamps each task
    id at CREATE and computes the latency at the FIRST observed RUNNING.
    The default clock is WALL time so a task whose CREATE event was
    missed (subscription race — the stream REQ and the service create
    ride separate connections) still measures from its store-stamped
    `meta.created_at`. Tasks with neither stamp are ignored (no
    partial-window bias). `allow()` restricts collection to the bench's
    own services — without it a busy cluster's foreign tasks would mix
    into the percentiles. Thread-safe: the pump thread feeds while the
    churn loop reads."""

    def __init__(self, clock=time.time, service_filter: bool = False):
        self._clock = clock
        from ..analysis.lockgraph import make_lock

        self._lock = make_lock('cmd.swarmbench.collector')
        self._created: dict[str, float] = {}
        self.latencies: dict[str, float] = {}    # task id -> seconds
        self.events = 0
        # set by pump_channel when the watch stream dies mid-run: the
        # report must not certify an SLO over silently-truncated data
        self.stream_error: str | None = None
        # None = collect everything; a set = only these service ids
        self._allowed: set | None = set() if service_filter else None

    def allow(self, service_id: str) -> None:
        """Admit one service's tasks (no-op without service_filter)."""
        with self._lock:
            if self._allowed is not None:
                self._allowed.add(service_id)

    def _admitted(self, obj) -> bool:
        return self._allowed is None or obj.service_id in self._allowed

    def feed(self, ev, now: float | None = None) -> None:
        from ..api.objects import EventCreate, EventDelete, Task
        from ..api.types import TaskState

        obj = getattr(ev, "obj", None)
        if not isinstance(obj, Task):
            return
        if now is None:
            now = self._clock()
        with self._lock:
            self.events += 1
            if not self._admitted(obj):
                return
            if isinstance(ev, EventDelete):
                self._created.pop(obj.id, None)
                return
            if isinstance(ev, EventCreate):
                self._created.setdefault(obj.id, now)
            if obj.status.state >= TaskState.RUNNING \
                    and obj.id not in self.latencies:
                # the first RUNNING-or-beyond sighting consumes the
                # CREATE stamp: a task observed straight to a terminal
                # state (FAILED/REJECTED) never yields a startup sample.
                # No local stamp (missed CREATE): fall back to the
                # store's wall-clock created_at — comparable because
                # the default collector clock is wall time too.
                t0 = self._created.pop(obj.id, None)
                if t0 is None:
                    t0 = getattr(obj.meta, "created_at", 0.0) or None
                if t0 is not None \
                        and obj.status.state == TaskState.RUNNING \
                        and now - t0 >= 0.0:
                    # a NEGATIVE delta means the fallback stamp came
                    # from a skewed manager clock — DISCARD it (a
                    # clamped 0.0 would dilute the percentiles and let
                    # a failing --slo gate pass)
                    self.latencies[obj.id] = now - t0

    def feed_poll(self, tasks, now: float | None = None) -> None:
        """Poll-mode fallback: absorb a list_tasks snapshot. CREATE
        stamps prefer the store's wall-clock `meta.created_at` (present
        on every scanned task) over first-sighting — a task created AND
        running between two polls would otherwise record ~0 latency and
        understate the percentiles. Negative deltas (skewed manager
        clock) are discarded like the watch path's."""
        from ..api.types import TaskState

        if now is None:
            now = self._clock()
        with self._lock:
            for t in tasks:
                if not self._admitted(t):
                    continue
                if t.id not in self._created:
                    self._created[t.id] = \
                        getattr(t.meta, "created_at", 0.0) or now
                if t.status.state == TaskState.RUNNING \
                        and t.id not in self.latencies \
                        and now - self._created[t.id] >= 0.0:
                    self.latencies[t.id] = now - self._created[t.id]

    def samples(self) -> list[float]:
        with self._lock:
            return list(self.latencies.values())

    def running(self) -> int:
        with self._lock:
            return len(self.latencies)


def pump_channel(ch, collector: StartupCollector,
                 stop: threading.Event) -> None:
    """Drain a watch channel into the collector until stopped. A stream
    death mid-run (closed channel, connection loss) is RECORDED on the
    collector — tasks starting after the drop contribute no sample, so
    the --slo gate must see the truncation, not a green report."""
    while not stop.is_set():
        try:
            ev = ch.get(timeout=0.2)
        except TimeoutError:
            continue
        except Exception as exc:
            if not stop.is_set():
                collector.stream_error = repr(exc)
            return
        collector.feed(ev)


def start_watch_collector(client, collector, stop,
                          service_ids=None) -> threading.Thread:
    """Subscribe to the cluster's task event stream and pump it on a
    thread. `client` is an RPCClient on a manager; selectors restrict
    server-side when service ids are known up front."""
    from ..watchapi.watch import WatchSelector

    if service_ids:
        selectors = [WatchSelector(kind="task", service_id=sid)
                     for sid in service_ids]
    else:
        selectors = [WatchSelector(kind="task")]
    ch = client.stream("watch.events", selectors=selectors)
    t = threading.Thread(target=pump_channel, args=(ch, collector, stop),
                         name="swarmbench-watch", daemon=True)
    t.start()
    return t


def start_poll_collector(ctl, svc_ids, collector, stop,
                         interval: float = 0.1) -> threading.Thread:
    """The legacy scan-poll fallback (`--poll`): one list_tasks scan per
    interval. `svc_ids=None` polls every task — churn mode creates its
    services mid-run, and the collector MUST already be sampling when
    they appear (a collector started after the churn would stamp
    created=now for already-running tasks and report ~0 latencies)."""
    from ..controlapi.control import ListFilters

    def run():
        while not stop.is_set():
            try:
                filters = (ListFilters(service_ids=list(svc_ids))
                           if svc_ids else None)
                collector.feed_poll(ctl.list_tasks(filters))
            except Exception:
                pass
            time.sleep(interval)

    t = threading.Thread(target=run, name="swarmbench-poll", daemon=True)
    t.start()
    return t


# ----------------------------------------------------------- session storm
class SessionStorm:
    """N simulated agent sessions against the manager's sharded
    dispatcher plane (ISSUE 13): register, subscribe a capped set of
    assignment streams, heartbeat round-robin until stopped — fan-out
    load riding alongside the churn, so the `--slo` gate certifies
    NEW→RUNNING percentiles UNDER a populated session plane.

    Registered simulacra are immediately DRAINED (spec.availability):
    the scheduler must never place real tasks on agents that will never
    run them — that would wedge the very startups the gate measures.
    The manager identity swarmbench already holds may drive any node's
    session (`_require_node` admits the MANAGER role), so no per-node
    certs are needed."""

    # registration batch size (ISSUE 16): one dispatcher.register_many
    # call per chunk — small enough that a raft-backed store commits it
    # in a handful of pipelined sub-transactions, large enough that 1M
    # simulacra register in ~1k RPCs instead of 1M
    REGISTER_CHUNK = 1024
    # per-session assignments-channel cap for simulacra whose streams
    # are never drained: shed at 64 queued messages instead of the
    # default 4096 (the OOM at 1M sessions was queued wire copies, not
    # the sessions themselves)
    CHANNEL_LIMIT = 64

    def __init__(self, client, ctl, n: int, prefix: str | None = None,
                 streams: int = 32, beat_interval: float = 1.0):
        self.client = client
        self.ctl = ctl
        self.n = n
        self.prefix = prefix or f"bench-sess-{int(time.time())}"
        self.streams = streams
        self.beat_interval = beat_interval
        self.metrics = {"registered": 0, "register_errors": 0,
                        "streams": 0, "stream_msgs": 0,
                        "beats": 0, "beat_errors": 0,
                        "drain_failures": 0, "register_s": 0.0,
                        "register_rpcs": 0}
        self._sessions: list[tuple[str, str]] = []
        self._chans: list = []
        self._thread: threading.Thread | None = None
        self._stop: threading.Event | None = None

    def _drain(self, nid: str, attempts: int = 3) -> bool:
        """Mark a simulated node DRAIN, re-reading the version per try
        (the cluster's own reconcilers bump node versions concurrently —
        one raced update must not leave a schedulable phantom)."""
        from ..api.types import NodeAvailability

        for _ in range(attempts):
            try:
                node = self.ctl.get_node(nid)
                if node.spec.availability == NodeAvailability.DRAIN:
                    return True
                node.spec.availability = NodeAvailability.DRAIN
                self.ctl.update_node(nid, node.meta.version, node.spec)
                return True
            except Exception:
                continue
        return False

    def start(self, stop: threading.Event):
        self._stop = stop
        t0 = time.monotonic()
        batched = True
        for off in range(0, self.n, self.REGISTER_CHUNK):
            if stop.is_set():
                break
            ids = [f"{self.prefix}-{i:07d}"
                   for i in range(off, min(off + self.REGISTER_CHUNK,
                                           self.n))]
            if batched:
                try:
                    # ISSUE 16 batched join: nodes are created
                    # pre-DRAINed (the scheduler never sees a
                    # schedulable phantom — no per-node control-API
                    # round trip) with capped assignment channels
                    granted = self.client.call(
                        "dispatcher.register_many", ids,
                        availability="drain",
                        channel_limit=self.CHANNEL_LIMIT)
                    self.metrics["register_rpcs"] += 1
                    self._sessions.extend(sorted(granted.items()))
                    self.metrics["registered"] += len(granted)
                    self.metrics["register_errors"] += \
                        len(ids) - len(granted)
                    continue
                except Exception:
                    # pre-16 manager (or a forwarding hiccup): fall
                    # back to the scalar register+drain path for this
                    # and all remaining chunks
                    batched = False
            for nid in ids:
                try:
                    sid = self.client.call("dispatcher.register", nid)
                    self.metrics["register_rpcs"] += 1
                except Exception:
                    self.metrics["register_errors"] += 1
                    continue
                if self._drain(nid):
                    self._sessions.append((nid, sid))
                    self.metrics["registered"] += 1
                else:
                    # a simulacrum that could NOT be drained must not
                    # stay a READY+ACTIVE phantom the scheduler places
                    # real tasks on (that would wedge the very startups
                    # the --slo gate measures): leave it so it goes DOWN
                    self.metrics["drain_failures"] += 1
                    try:
                        self.client.call("dispatcher.leave", nid, sid)
                    except Exception:
                        pass
        self.metrics["register_s"] = round(time.monotonic() - t0, 3)
        for nid, sid in self._sessions[:self.streams]:
            try:
                self._chans.append(
                    self.client.stream("dispatcher.assignments", nid, sid))
                self.metrics["streams"] += 1
            except Exception:
                pass
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="swarmbench-sessions")
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            for nid, sid in self._sessions:
                if self._stop.is_set():
                    return
                try:
                    self.client.call("dispatcher.heartbeat", nid, sid)
                    self.metrics["beats"] += 1
                except Exception:
                    self.metrics["beat_errors"] += 1
            for ch in self._chans:
                try:
                    while ch.try_get() is not None:
                        self.metrics["stream_msgs"] += 1
                except Exception:
                    pass
            self._stop.wait(self.beat_interval)

    def finish(self):
        """Best-effort graceful leave so the simulated nodes go DOWN
        cleanly instead of riding heartbeat-expiry timers."""
        if self._thread is not None:
            self._thread.join(timeout=5)
        for nid, sid in self._sessions:
            try:
                self.client.call("dispatcher.leave", nid, sid)
            except Exception:
                pass


# ----------------------------------------------------------------- log storm
class LogStorm:
    """--log-subscribers: N follow-mode log subscription streams against
    the manager's sharded log fan-out plane (ISSUE 20), held open for
    the whole run and drained at a bounded per-subscriber budget
    (--log-rate msgs/s; 0 = drain as fast as they arrive). A budget
    below the cluster's publish rate backs the stream up until the
    broker's bounded client channel SHEDS — the report shows every
    dropped window arriving as a counted, resumable LogShedRecord
    (shed_messages) instead of the stall/OOM the unbounded plane risked.

    The storm rides its own RPCClient (stream back-pressure must not
    stall the churn driver) and selects only the services THIS run
    created, so a busy cluster's foreign log traffic stays out of the
    counts. With `--command "sleep ..."` tasks emit nothing and the
    storm measures pure subscription fan-out (open/dispatch/complete);
    point --command at something chatty to drive real shed load."""

    PUMP_WORKERS = 4

    def __init__(self, client, n: int, rate: float = 0.0):
        self.client = client
        self.n = n
        self.rate = rate
        self.metrics = {"subscribers": 0, "subscribe_errors": 0,
                        "received": 0, "shed_records": 0,
                        "shed_messages": 0, "completed": 0,
                        "stream_deaths": 0, "subscribe_s": 0.0}
        self._chans: list = []
        self._threads: list[threading.Thread] = []
        self._stripe_counts: list[dict] = []
        self._stop: threading.Event | None = None

    def start(self, stop: threading.Event, service_ids):
        """Open the streams. Called AFTER the run's services exist — an
        empty LogSelector matches nothing, so the selector must carry
        the created ids (churn mode starts the storm post-churn and
        holds it through the settle window)."""
        from ..logbroker.broker import LogSelector

        self._stop = stop
        service_ids = list(service_ids)
        t0 = time.monotonic()
        for _ in range(self.n):
            if stop.is_set():
                break
            sel = LogSelector(service_ids=service_ids)
            try:
                # limit=-1 = the broker's default bounded client channel
                # (shed-don't-stall); the CLIENT side stays unbounded —
                # the server's ShedChannel is the accounting point
                ch = self.client.stream("logs.subscribe", sel,
                                        follow=True, limit=-1)
            except Exception:
                self.metrics["subscribe_errors"] += 1
                continue
            self._chans.append(ch)
        self.metrics["subscribers"] = len(self._chans)
        self.metrics["subscribe_s"] = round(time.monotonic() - t0, 3)
        workers = max(1, min(self.PUMP_WORKERS, len(self._chans)))
        for i in range(workers):
            stripe = self._chans[i::workers]
            counts = {"received": 0, "shed_records": 0,
                      "shed_messages": 0, "completed": 0,
                      "stream_deaths": 0}
            self._stripe_counts.append(counts)
            th = threading.Thread(target=self._pump,
                                  args=(stripe, counts, stop),
                                  name=f"swarmbench-logs-{i}", daemon=True)
            th.start()
            self._threads.append(th)

    def _pump(self, chans, counts, stop: threading.Event):
        from ..logbroker.broker import (LogMessage, LogShedRecord,
                                        SubscriptionComplete)

        # token bucket: the per-subscriber budget aggregates over the
        # stripe (rate * len); refilled from wall time, capped at one
        # second's worth so an idle stretch can't bank an unbounded burst
        budget = self.rate * len(chans)
        tokens, last = budget, time.monotonic()
        live = list(chans)
        while not stop.is_set() and live:
            if budget:
                now = time.monotonic()
                tokens = min(budget, tokens + (now - last) * budget)
                last = now
            drained = 0
            for ch in list(live):
                if budget and tokens < 1.0:
                    break
                try:
                    ev = ch.try_get()
                except Exception:
                    live.remove(ch)
                    counts["stream_deaths"] += 1
                    continue
                if ev is None:
                    if ch.closed:
                        live.remove(ch)
                    continue
                drained += 1
                if budget:
                    tokens -= 1.0
                if isinstance(ev, LogMessage):
                    counts["received"] += 1
                elif isinstance(ev, LogShedRecord):
                    counts["shed_records"] += 1
                    counts["shed_messages"] += ev.count
                elif isinstance(ev, SubscriptionComplete):
                    counts["completed"] += 1
            if not drained:
                stop.wait(0.05)
            elif budget and tokens < 1.0:
                stop.wait(max(0.01, (1.0 - tokens) / budget))

    def snapshot(self) -> dict:
        """Merged live counters. Each pump stripe owns its dict (one
        writer); a mid-run read is approximate but never torn."""
        out = dict(self.metrics)
        for counts in self._stripe_counts:
            for k, v in counts.items():
                out[k] += v
        return out

    def finish(self):
        for th in self._threads:
            th.join(timeout=5)
        for ch in self._chans:
            try:
                ch.close()
            except Exception:
                pass


# -------------------------------------------------------------- load shapes
def _service_spec(name: str, replicas: int, command: str,
                  auto_rollback: bool = False,
                  strategy: str | None = None):
    import shlex

    from ..api.specs import (Annotations, ContainerSpec, ServiceSpec,
                             TaskSpec, UpdateConfig)

    spec = ServiceSpec(
        annotations=Annotations(name=name),
        replicas=replicas,
        task=TaskSpec(runtime=ContainerSpec(
            command=shlex.split(command))),
    )
    if strategy == "binpack":
        # fullest-first scoring needs capacity to consume: one CPU
        # quantum per task makes the pile-up observable without
        # starving a real node (ISSUE 19)
        from ..scheduler.encode import CPU_QUANTUM

        spec.task.resources.reservations.nano_cpus = CPU_QUANTUM
    if auto_rollback:
        # fail-storm services must recover WITHOUT operator action: a
        # broken rollout trips max_failure_ratio and rolls back
        # (orchestrator wave planner; docs/orchestrator.md)
        from ..api.types import UpdateFailureAction

        spec.update = UpdateConfig(
            parallelism=2, monitor=2.0,
            failure_action=UpdateFailureAction.ROLLBACK,
            max_failure_ratio=0.0)
    return spec


def _retryable_update_error(exc: Exception) -> bool:
    """Version conflicts (the cluster's own orchestrators bump versions
    concurrently under churn) and transient RPC/leadership errors retry;
    a permanent error (validation, service removed) raises at once."""
    if isinstance(exc, (TimeoutError, ConnectionError, OSError)):
        return True
    msg = str(exc)
    return ("out of sequence" in msg or "NotLeader" in msg
            or "not found" not in msg and "conflict" in msg.lower())


def _update_with_retry(ctl, svc_id: str, mutate):
    """update_service under the repo's Backoff policy (CLAUDE.md: no
    ad-hoc sleep loops), refetching the current version per attempt."""
    from ..utils.backoff import Backoff, retry

    def attempt():
        svc = ctl.get_service(svc_id)
        spec = svc.spec
        mutate(spec)
        return ctl.update_service(svc.id, svc.meta.version, spec)

    return retry(attempt,
                 policy=Backoff(base=0.1, factor=2.0, max_delay=1.0,
                                max_attempts=8),
                 retryable=_retryable_update_error)


def run_churn(ctl, *, duration: float, replicas: int, rng: random.Random,
              services: int = 1, scale_step: int = 2,
              storm_every: int = 3, interval: float = 0.5,
              command: str = "sleep 3600",
              fail_storm_every: int = 0,
              name_prefix: str | None = None,
              strategy: str | None = None,
              progress=None, on_service=None) -> dict:
    """The continuous-churn load generator: every `interval` one service
    gets either a ROLLOUT STORM (env bump → every task replaced through
    the updater) or a scale up/down of `scale_step`. With
    `fail_storm_every` = M, every Mth storm pushes a BROKEN rollout (a
    command that exits immediately) against a service configured with
    failure_action=rollback — the orchestrator's wave planner must
    auto-rollback it, and the report counts observed rollbacks (the
    ISSUE 14 rolling-update-storm scenario against a live cluster).
    All randomness comes from `rng`, so a seeded run replays the same
    schedule. Returns {service_ids, rounds, storms, fail_storms,
    rollbacks_observed, scales}."""
    name_prefix = name_prefix or f"bench-{int(time.time())}"
    svcs = []
    try:
        for i in range(services):
            svc = ctl.create_service(
                _service_spec(f"{name_prefix}-{i}", replicas, command,
                              auto_rollback=bool(fail_storm_every),
                              strategy=strategy))
            if on_service is not None:
                on_service(svc)        # e.g. collector.allow(svc.id)
            svcs.append(svc)
    except Exception:
        # a mid-setup failure must not orphan the services already
        # created (the caller never learns their ids)
        for s in svcs:
            try:
                ctl.remove_service(s.id)
            except Exception:
                pass
        raise
    rounds = storms = scales = failed = fail_storms = 0
    deadline = time.monotonic() + duration
    while time.monotonic() < deadline:
        rounds += 1
        svc = svcs[rng.randrange(len(svcs))]
        # success-only counters: a report claiming N storms that all
        # failed would certify a load profile that never materialized
        try:
            if storm_every and rounds % storm_every == 0:
                broken = (fail_storm_every
                          and storms % fail_storm_every
                          == fail_storm_every - 1)

                def storm(spec, n=rounds, broken=broken):
                    spec.task.runtime.env = [f"BENCH_STORM={n}"]
                    if broken:
                        # a rollout that cannot start: every replacement
                        # exits at once, the monitor counts the deaths,
                        # and the rollback policy must recover the
                        # service without operator action
                        spec.task.runtime.command = ["false"]

                _update_with_retry(ctl, svc.id, storm)
                storms += 1
                if broken:
                    fail_storms += 1
            else:
                delta = rng.choice([-scale_step, scale_step])

                def scale(spec, d=delta):
                    spec.replicas = max(1, min(replicas * 2,
                                               spec.replicas + d))

                _update_with_retry(ctl, svc.id, scale)
                scales += 1
        except Exception:
            failed += 1                # churn must outlive a flaky round
        if progress is not None:
            progress(rounds)
        time.sleep(interval)
    rollbacks = 0
    if fail_storms:
        # census the recoveries: services whose status reached a
        # rollback_* family during the run (rollback_completed once
        # reconverged; the --slo settle window gives them time)
        for s in svcs:
            try:
                cur = ctl.get_service(s.id)
                state = (cur.update_status or {}).get("state", "")
                if state.startswith("rollback"):
                    rollbacks += 1
            except Exception:
                pass
    return {"service_ids": [s.id for s in svcs], "rounds": rounds,
            "storms": storms, "fail_storms": fail_storms,
            "rollbacks_observed": rollbacks, "scales": scales,
            "failed_rounds": failed}


# -------------------------------------------------------------------- report
def build_report(collector: StartupCollector, *, replicas=None,
                 slo_specs=None, churn_stats=None,
                 server_report=None) -> dict:
    from ..utils import slo as slo_mod

    lat = collector.samples()
    sorted_lat = sorted(lat)
    qs = slo_mod.quantiles_nearest_rank(sorted_lat, (50, 90, 99))
    report = {
        "running": len(lat),
        "time_to_first_s": (round(sorted_lat[0], 3) if sorted_lat
                            else None),
        "p50_s": _r3(qs[50]),
        "p90_s": _r3(qs[90]),
        "p99_s": _r3(qs[99]),
    }
    if replicas is not None:
        report["replicas"] = replicas
        report["time_to_all_s"] = (round(sorted_lat[-1], 3)
                                   if len(lat) >= replicas else None)
    if churn_stats:
        report["churn"] = churn_stats
    if collector.stream_error:
        report["stream_error"] = collector.stream_error
    if slo_specs:
        out = slo_mod.evaluate_samples(slo_specs, lat).as_dict()
        # a bench run with ZERO samples did not measure anything, and a
        # mid-run stream death truncated the data: the vacuous
        # min_samples pass is for monitoring windows, not for a load
        # generator certifying an objective — fail the gate loudly
        out["measured"] = len(lat) > 0
        out["ok"] = (out["ok"] and out["measured"]
                     and collector.stream_error is None)
        report["slo"] = out
    if server_report:
        report["server"] = server_report
    return report


def _r3(v):
    return None if v is None else round(v, 3)


# ---------------------------------------------------------------------- main
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="swarm-bench")
    ap.add_argument("--addr", required=True)
    ap.add_argument("--identity", required=True,
                    help="manager state dir (cert.pem/key.json/ca.pem)")
    ap.add_argument("--replicas", type=int, default=100)
    ap.add_argument("--command", default="sleep 3600")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--keep", action="store_true",
                    help="leave the service(s) running after the run")
    ap.add_argument("--poll", action="store_true",
                    help="legacy list_tasks scan-poll collector instead "
                         "of the watch-API stream")
    ap.add_argument("--churn", action="store_true",
                    help="continuous-churn mode: rollout storms + scale "
                         "up/down for --duration seconds")
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--services", type=int, default=1)
    ap.add_argument("--scale-step", type=int, default=2)
    ap.add_argument("--storm-every", type=int, default=3,
                    help="every Nth churn round is a rollout storm")
    ap.add_argument("--fail-storm-every", type=int, default=0,
                    metavar="M",
                    help="every Mth storm is a BROKEN rollout (exits "
                         "immediately) against auto-rollback services; "
                         "the report counts observed rollbacks "
                         "(rolling-update storm scenario)")
    ap.add_argument("--interval", type=float, default=0.5,
                    help="churn round interval seconds")
    ap.add_argument("--settle", type=float, default=15.0,
                    help="post-churn settle budget: wait (up to this "
                         "many seconds) for in-flight startups to land "
                         "before evaluating --slo")
    ap.add_argument("--seed", type=int, default=0,
                    help="churn schedule seed (replayable)")
    ap.add_argument("--telemetry", action="store_true",
                    help="embed the cluster telemetry rollup "
                         "(control.get_cluster_telemetry) in the JSON "
                         "report — one artifact for SLO gates AND the "
                         "rollup; the server arms via "
                         "SWARMKIT_TPU_TELEMETRY")
    ap.add_argument("--slo", default="",
                    help='startup objectives, e.g. "p50:1.0,p99:5.0" '
                         "(seconds); violated objectives fail the run")
    ap.add_argument("--sessions", type=int, default=0, metavar="N",
                    help="drive N simulated agent sessions (register + "
                         "heartbeats + assignment streams) against the "
                         "manager's sharded dispatcher plane during the "
                         "run; simulated nodes are drained so they "
                         "never receive real placements")
    ap.add_argument("--strategy", default=None,
                    choices=["spread", "binpack", "topology"],
                    help="scheduler strategy the target manager runs "
                         "(swarmd --scheduler-strategy); recorded in "
                         "the report for attribution, and binpack "
                         "gives created services a one-CPU-quantum "
                         "reservation so fullest-first scoring has "
                         "capacity to consume (ISSUE 19)")
    ap.add_argument("--shards", type=int, default=None, metavar="P",
                    help="dispatcher shard count the target manager was "
                         "started with (swarmd --dispatcher-shards); "
                         "recorded in the report so a storm run is "
                         "attributable to its plane configuration")
    ap.add_argument("--log-subscribers", type=int, default=0, metavar="N",
                    help="hold N follow-mode log subscription streams "
                         "on this run's services against the manager's "
                         "sharded log fan-out plane (ISSUE 20); the "
                         "report gains a log_plane block: client-side "
                         "received/shed/completed counts plus the "
                         "manager's logbroker telemetry")
    ap.add_argument("--log-rate", type=float, default=0.0, metavar="R",
                    help="per-subscriber drain budget in msgs/s for "
                         "--log-subscribers (0 = unbounded); a budget "
                         "below the publish rate backs streams up until "
                         "the broker's bounded channels SHED — a "
                         "counted, resumable window, never a stall")
    args = ap.parse_args(argv)

    from ..rpc.client import RPCClient
    from ..rpc.services import RemoteControl
    from ..utils.slo import parse_slo_arg
    from .swarmctl import _load_identity

    slo_specs = parse_slo_arg(args.slo) if args.slo else []
    sec = _load_identity(args.identity)
    ctl = RemoteControl(args.addr, sec)
    # service-filtered: only the services THIS run creates contribute
    # samples (a busy cluster's foreign tasks must not mix into the
    # percentiles); allow() admits them as they are created
    collector = StartupCollector(service_filter=True)
    stop = threading.Event()
    watch_client = None
    storm = storm_client = None
    log_storm = log_client = None
    created_ids: list[str] = []

    def start_log_storm(service_ids):
        # the log storm rides its OWN connection too: N held-open
        # subscription streams back up under a low --log-rate budget,
        # and that TCP back-pressure must not stall the driver's RPCs
        nonlocal log_storm, log_client
        if args.log_subscribers > 0 and log_storm is None:
            log_client = RPCClient(args.addr, security=sec)
            log_storm = LogStorm(log_client, args.log_subscribers,
                                 rate=args.log_rate)
            log_storm.start(stop, service_ids)
    try:
        if not args.poll:
            watch_client = RPCClient(args.addr, security=sec)
            start_watch_collector(watch_client, collector, stop)

        if args.sessions > 0:
            # the session storm rides its own connection: stream
            # back-pressure must not stall the churn driver's RPCs
            storm_client = RPCClient(args.addr, security=sec)
            storm = SessionStorm(storm_client, ctl, args.sessions)
            storm.start(stop)

        if args.churn:
            if args.poll:
                # the collector must be sampling BEFORE the churn
                # creates its services: a post-hoc snapshot would stamp
                # created=now for already-RUNNING tasks and report ~0
                # latencies, vacuously passing any --slo gate. No
                # service filter — the ids don't exist yet.
                start_poll_collector(ctl, None, collector, stop)
            churn_stats = run_churn(
                ctl, duration=args.duration, replicas=args.replicas,
                rng=random.Random(args.seed), services=args.services,
                scale_step=args.scale_step, storm_every=args.storm_every,
                interval=args.interval, command=args.command,
                fail_storm_every=args.fail_storm_every,
                strategy=args.strategy,
                on_service=lambda s: collector.allow(s.id))
            created_ids = churn_stats["service_ids"]
            # the log storm starts POST-churn (an empty LogSelector
            # matches nothing — the ids must exist) and rides the
            # settle window below
            start_log_storm(created_ids)
            # SETTLE before evaluating: the churn cutoff right-censors
            # in-flight startups — without this window, tasks still
            # starting (or stuck) at the end contribute no sample and
            # can never fail the gate. Wait until the sample count
            # stops growing (2s quiet) or the settle budget runs out.
            deadline = time.monotonic() + args.settle
            last_n, quiet_since = collector.running(), time.monotonic()
            while time.monotonic() < deadline:
                time.sleep(0.25)
                n = collector.running()
                if n != last_n:
                    last_n, quiet_since = n, time.monotonic()
                elif time.monotonic() - quiet_since >= 2.0:
                    break
            # census: tasks of OUR services that should be running but
            # are not by the settled cutoff are an SLO miss, not a
            # silently-dropped sample
            pending, census_error = None, None
            try:
                from ..api.types import TaskState
                from ..controlapi.control import ListFilters

                pending = sum(
                    1 for t in ctl.list_tasks(
                        ListFilters(service_ids=list(created_ids)))
                    if t.desired_state == TaskState.RUNNING
                    and t.status.state < TaskState.RUNNING)
            except Exception as exc:
                # a failed census is UNVERIFIED data, not a pass — the
                # gate below fails loudly, same as stream death
                census_error = repr(exc)
            server_report = None
            try:
                server_report = ctl.get_slo_report()
            except Exception:
                pass                   # pre-SLO manager / plane disarmed
            report = build_report(collector, slo_specs=slo_specs,
                                  churn_stats=churn_stats,
                                  server_report=server_report)
            report["not_running_at_cutoff"] = pending
            if census_error is not None:
                report["census_error"] = census_error
            if slo_specs and (pending or census_error is not None):
                report["slo"]["ok"] = False
        else:
            svc = ctl.create_service(_service_spec(
                f"bench-{int(time.time())}", args.replicas, args.command,
                strategy=args.strategy))
            collector.allow(svc.id)
            created_ids = [svc.id]
            start_log_storm(created_ids)
            if args.poll:
                start_poll_collector(ctl, created_ids, collector, stop)
            deadline = time.monotonic() + args.timeout
            while time.monotonic() < deadline \
                    and collector.running() < args.replicas:
                time.sleep(0.1)
            report = build_report(collector, replicas=args.replicas,
                                  slo_specs=slo_specs)
            report["service"] = svc.id

        if args.strategy is not None:
            report["strategy"] = args.strategy
        if storm is not None:
            report["session_storm"] = dict(storm.metrics)
            report["session_storm"]["sessions"] = args.sessions
            if args.shards is not None:
                report["session_storm"]["shards"] = args.shards
            # columnar diff-gate effectiveness (ISSUE 16): sessions/s
            # from the storm's own registration clock, skip ratio and
            # deltas/flush from the manager's dispatcher metrics (the
            # telemetry manager block carries them even disarmed)
            try:
                disp = ctl.get_cluster_telemetry().get(
                    "manager", {}).get("dispatcher", {})
                reg_s = storm.metrics.get("register_s") or 0
                skips = disp.get("zero_delta_skips", 0)
                dict_diffs = disp.get("dict_diffs", 0)
                flushes = disp.get("flushes", 0)
                report["diff_plane"] = {
                    "sessions_per_s": round(
                        storm.metrics["registered"] / reg_s, 1)
                    if reg_s else None,
                    "diff_rows_scanned": disp.get("diff_rows_scanned", 0),
                    "zero_delta_skips": skips,
                    "dict_diffs": dict_diffs,
                    "zero_delta_skip_ratio": round(
                        skips / (skips + dict_diffs), 4)
                    if (skips + dict_diffs) else None,
                    "deltas_per_flush": round(dict_diffs / flushes, 2)
                    if flushes else None,
                }
            except Exception as exc:     # pre-16 manager / no telemetry
                report["diff_plane"] = {"error": repr(exc)}
        if log_storm is not None:
            # log fan-out plane (ISSUE 20): client-side stream counts
            # plus the manager broker's own accounting — its
            # delivered + shed == published invariant is checkable
            # straight from the artifact
            lp = log_storm.snapshot()
            lp["rate"] = args.log_rate
            try:
                lb = ctl.get_cluster_telemetry().get(
                    "manager", {}).get("logbroker", {})
                lp["broker"] = {k: lb.get(k, 0) for k in (
                    "published", "delivered", "shed", "shed_windows",
                    "subscriptions_opened", "subscriptions_completed",
                    "dispatch_offers", "listeners")}
            except Exception as exc:     # pre-20 manager / no telemetry
                lp["broker"] = {"error": repr(exc)}
            report["log_plane"] = lp
        if args.telemetry:
            # embed the cluster rollup so the SLO gate and the
            # telemetry artifact come from ONE report (ISSUE 15);
            # armed-ness is the server's (SWARMKIT_TPU_TELEMETRY on
            # swarmd arms the plane cluster-wide)
            try:
                report["telemetry"] = ctl.get_cluster_telemetry()
            except Exception as exc:
                report["telemetry"] = {"armed": False,
                                       "error": repr(exc)}
            # recovery plane (ISSUE 18): lift the manager's snapshot
            # catch-up counters out of the rollup so a bench run shows
            # resume behavior (chunks resent vs sent, installs) at a
            # glance without digging through the telemetry artifact
            rec = (report.get("telemetry", {}).get("manager", {})
                   .get("raft", {}).get("recovery"))
            if rec:
                report["recovery_plane"] = rec
        print(json.dumps(report))
        ok = report.get("slo", {}).get("ok", True)
        if not args.churn:
            ok = ok and report["running"] >= args.replicas
        return 0 if ok else 1
    finally:
        stop.set()
        if storm is not None:
            storm.finish()
        if storm_client is not None:
            try:
                storm_client.close()
            except Exception:
                pass
        if log_storm is not None:
            log_storm.finish()
        if log_client is not None:
            try:
                log_client.close()
            except Exception:
                pass
        if not args.keep:
            for sid in created_ids:
                try:
                    ctl.remove_service(sid)
                except Exception:
                    pass
        if watch_client is not None:
            try:
                watch_client.close()
            except Exception:
                pass
        ctl.close()


if __name__ == "__main__":
    sys.exit(main())
