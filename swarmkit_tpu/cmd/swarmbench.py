"""swarm-bench: cluster load generator (reference cmd/swarm-bench).

Creates an N-replica service against a live cluster and measures
time-to-RUNNING per task, reporting percentiles — the reference has the
containers phone home over UDP; our tasks' observed RUNNING timestamps in
the replicated store carry the same signal without instrumenting payloads.

    python -m swarmkit_tpu.cmd.swarmbench --addr 127.0.0.1:4242 \
        --identity /tmp/m1 --replicas 100
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="swarm-bench")
    ap.add_argument("--addr", required=True)
    ap.add_argument("--identity", required=True,
                    help="manager state dir (cert.pem/key.json/ca.pem)")
    ap.add_argument("--replicas", type=int, default=100)
    ap.add_argument("--command", default="sleep 3600")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--keep", action="store_true",
                    help="leave the service running after the measurement")
    args = ap.parse_args(argv)

    from .swarmctl import _load_identity
    from ..api.specs import Annotations, ContainerSpec, ServiceSpec, TaskSpec
    from ..api.types import TaskState
    from ..controlapi.control import ListFilters
    from ..rpc.services import RemoteControl

    import shlex

    sec = _load_identity(args.identity)
    ctl = RemoteControl(args.addr, sec)

    name = f"bench-{int(time.time())}"
    t0 = time.monotonic()
    svc = ctl.create_service(ServiceSpec(
        annotations=Annotations(name=name),
        replicas=args.replicas,
        task=TaskSpec(runtime=ContainerSpec(
            command=shlex.split(args.command))),
    ))

    seen: dict[str, float] = {}  # task id -> time-to-RUNNING from t0
    deadline = time.monotonic() + args.timeout
    while time.monotonic() < deadline and len(seen) < args.replicas:
        now = time.monotonic()
        try:
            tasks = ctl.list_tasks(ListFilters(service_ids=[svc.id]))
        except Exception:
            time.sleep(0.3)
            continue
        for t in tasks:
            if t.id not in seen and t.status.state == TaskState.RUNNING:
                seen[t.id] = now - t0
        time.sleep(0.1)

    lat = sorted(seen.values())

    def pct(p):
        if not lat:
            return None
        return round(lat[min(len(lat) - 1, int(p / 100 * len(lat)))], 3)

    print(json.dumps({
        "service": svc.id,
        "replicas": args.replicas,
        "running": len(lat),
        "time_to_first_s": round(lat[0], 3) if lat else None,
        "time_to_all_s": round(lat[-1], 3) if len(lat) == args.replicas
        else None,
        "p50_s": pct(50), "p90_s": pct(90), "p99_s": pct(99),
    }))
    if not args.keep:
        try:
            ctl.remove_service(svc.id)
        except Exception:
            pass
    ctl.close()
    return 0 if len(lat) == args.replicas else 1


if __name__ == "__main__":
    sys.exit(main())
