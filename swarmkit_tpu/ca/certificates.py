"""Root CA and certificate issuance.

Re-derivation of the reference's CA core (ca/certificates.go): a self-signed
ECDSA root, CSR create/sign with the node's identity encoded in the subject
(CN = node ID, OU = role, O = cluster ID — ca/certificates.go:167-450), cert
chain validation, and expiry-window math used by the renewer.

The reference shells out to cloudflare/cfssl; we use `cryptography.x509`
directly. Certificates are real and usable for mTLS between processes; the
in-process transport carries the same identity objects without TLS.
"""
from __future__ import annotations

import datetime
import hashlib
from dataclasses import dataclass

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

from ..api.types import NodeRole

# Subject OU values by role (reference: ca/certificates.go:56-62).
MANAGER_ROLE = "swarm-manager"
WORKER_ROLE = "swarm-worker"
CA_ROLE = "swarm-ca"

# Expiry knobs (reference: ca/certificates.go:64-80): root 20y, node 90d
# default / 30min minimum, renewal begins inside the last half of validity.
ROOT_CA_EXPIRATION = 20 * 365 * 24 * 3600.0
DEFAULT_NODE_CERT_EXPIRATION = 90 * 24 * 3600.0
MIN_NODE_CERT_EXPIRATION = 30 * 60.0
CERT_BACKDATE = 300.0  # issue 5min in the past to tolerate clock skew


class CertificateError(Exception):
    pass


def role_to_ou(role: int) -> str:
    return MANAGER_ROLE if role == NodeRole.MANAGER else WORKER_ROLE


def ou_to_role(ou: str) -> int:
    if ou == MANAGER_ROLE:
        return NodeRole.MANAGER
    if ou == WORKER_ROLE:
        return NodeRole.WORKER
    raise CertificateError(f"unknown role OU {ou!r}")


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def generate_key() -> ec.EllipticCurvePrivateKey:
    """ECDSA P-256, matching the reference's default key type
    (ca/certificates.go RootCA uses ECDSA)."""
    return ec.generate_private_key(ec.SECP256R1())


def key_to_pem(key: ec.EllipticCurvePrivateKey) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def key_from_pem(pem: bytes) -> ec.EllipticCurvePrivateKey:
    return serialization.load_pem_private_key(pem, password=None)


def create_csr(node_id: str, role: int, org: str) -> tuple[bytes, bytes]:
    """Create a key + CSR for a node identity (reference:
    ca/certificates.go GenerateNewCSR + CreateCertificateSigningRequest).
    Returns (key_pem, csr_pem)."""
    key = generate_key()
    csr = (
        x509.CertificateSigningRequestBuilder()
        .subject_name(
            x509.Name(
                [
                    x509.NameAttribute(NameOID.COMMON_NAME, node_id),
                    x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, role_to_ou(role)),
                    x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
                ]
            )
        )
        .sign(key, hashes.SHA256())
    )
    return key_to_pem(key), csr.public_bytes(serialization.Encoding.PEM)


@dataclass
class CertIdentity:
    """Identity parsed out of a node certificate subject."""

    node_id: str
    role: int
    org: str


def parse_cert_identity(cert_pem: bytes) -> CertIdentity:
    cert = x509.load_pem_x509_certificate(cert_pem)
    subj = cert.subject

    def one(oid):
        attrs = subj.get_attributes_for_oid(oid)
        return attrs[0].value if attrs else ""

    ou = one(NameOID.ORGANIZATIONAL_UNIT_NAME)
    return CertIdentity(
        node_id=one(NameOID.COMMON_NAME),
        role=ou_to_role(ou),
        org=one(NameOID.ORGANIZATION_NAME),
    )


class RootCA:
    """A signing root: cert (possibly a multi-PEM trust BUNDLE during root
    rotation) + (optionally) key.

    Mirrors ca/certificates.go RootCA — a root without the signing key is a
    trust anchor only (worker-side); with the key it can sign CSRs. During a
    phased root rotation `intermediate_pem` carries the cross-signed new
    root (old key signs the new root's public key): every cert issued then
    ships `leaf + intermediate`, so nodes still pinned to the old anchor
    validate it through the cross-signature while nodes on the new anchor
    validate the leaf directly (ca/certificates.go CrossSignCACertificate).
    """

    def __init__(self, cert_pem: bytes, key_pem: bytes | None = None,
                 intermediate_pem: bytes | None = None):
        self.cert_pem = cert_pem
        self.key_pem = key_pem
        self.intermediate_pem = intermediate_pem
        self._certs = x509.load_pem_x509_certificates(cert_pem)
        self._cert = self._certs[0]
        self._key = key_from_pem(key_pem) if key_pem else None

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, org: str = "swarmkit-tpu") -> "RootCA":
        """Self-signed root (reference: ca/certificates.go CreateRootCA:768).

        The CN carries a unique suffix: during a phased root rotation two
        roots coexist and certs chain through a cross-signed intermediate —
        identical subjects would make OpenSSL's path building ambiguous
        (leaf → intermediate → wrong-keyed anchor of the same name)."""
        import secrets

        key = generate_key()
        now = _now()
        name = x509.Name(
            [
                x509.NameAttribute(
                    NameOID.COMMON_NAME,
                    f"{org} CA {secrets.token_hex(4)}"),
                x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, CA_ROLE),
                x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
            ]
        )
        cert = (
            x509.CertificateBuilder()
            .subject_name(name)
            .issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(seconds=CERT_BACKDATE))
            .not_valid_after(now + datetime.timedelta(seconds=ROOT_CA_EXPIRATION))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
            .add_extension(
                x509.KeyUsage(
                    digital_signature=True,
                    key_cert_sign=True,
                    crl_sign=True,
                    content_commitment=False,
                    key_encipherment=False,
                    data_encipherment=False,
                    key_agreement=False,
                    encipher_only=False,
                    decipher_only=False,
                ),
                critical=True,
            )
            .sign(key, hashes.SHA256())
        )
        return cls(cert.public_bytes(serialization.Encoding.PEM), key_to_pem(key))

    # -- properties --------------------------------------------------------

    @property
    def can_sign(self) -> bool:
        return self._key is not None

    def digest(self) -> str:
        """sha256 digest of the root cert, the token-embedded trust pin
        (reference: ca/config.go join-token digest)."""
        return hashlib.sha256(self.cert_pem).hexdigest()

    def without_key(self) -> "RootCA":
        return RootCA(self.cert_pem)

    def key_matches_cert(self) -> bool:
        """True iff the held private key is the one the certificate was
        issued for (reference ca_rotation.go validateCAConfig rejects a
        signing cert whose key doesn't match before starting a rotation)."""
        if self._key is None:
            return False
        ours = self._key.public_key().public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo)
        theirs = self._cert.public_key().public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo)
        return ours == theirs

    # -- signing -----------------------------------------------------------

    def sign_csr(
        self,
        csr_pem: bytes,
        expiry: float = DEFAULT_NODE_CERT_EXPIRATION,
        subject: tuple[str, int, str] | None = None,
    ) -> bytes:
        """Sign a node CSR. By default the CSR's subject is preserved; the CA
        server passes `subject=(node_id, role, org)` to force the identity it
        assigned, exactly as the reference overrides the cfssl subject when
        signing (ca/certificates.go RootCA.ParseValidateAndSignCSR — the CSR
        only contributes the public key)."""
        if not self.can_sign:
            raise CertificateError("root CA has no signing key")
        expiry = max(expiry, MIN_NODE_CERT_EXPIRATION)
        csr = x509.load_pem_x509_csr(csr_pem)
        if not csr.is_signature_valid:
            raise CertificateError("invalid CSR signature")
        if subject is not None:
            node_id, role, org = subject
            subject_name = x509.Name(
                [
                    x509.NameAttribute(NameOID.COMMON_NAME, node_id),
                    x509.NameAttribute(NameOID.ORGANIZATIONAL_UNIT_NAME, role_to_ou(role)),
                    x509.NameAttribute(NameOID.ORGANIZATION_NAME, org),
                ]
            )
        else:
            subject_name = csr.subject
        now = _now()
        cert = (
            x509.CertificateBuilder()
            .subject_name(subject_name)
            .issuer_name(self._cert.subject)
            .public_key(csr.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(seconds=CERT_BACKDATE))
            .not_valid_after(now + datetime.timedelta(seconds=expiry))
            .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
            .add_extension(
                x509.ExtendedKeyUsage(
                    [x509.ExtendedKeyUsageOID.SERVER_AUTH, x509.ExtendedKeyUsageOID.CLIENT_AUTH]
                ),
                critical=False,
            )
            .sign(self._key, hashes.SHA256())
        )
        leaf = cert.public_bytes(serialization.Encoding.PEM)
        if self.intermediate_pem:
            return leaf + self.intermediate_pem
        return leaf

    def cross_sign(self, new_root: "RootCA") -> bytes:
        """Sign the NEW root's public key + subject under THIS (old) root,
        producing the rotation intermediate (ca/certificates.go
        CrossSignCACertificate). Chains `new-leaf → intermediate → old
        anchor` keep old-pinned nodes trusting freshly issued certs."""
        if not self.can_sign:
            raise CertificateError("root CA has no signing key")
        target = new_root._cert
        now = _now()
        cert = (
            x509.CertificateBuilder()
            .subject_name(target.subject)
            .issuer_name(self._cert.subject)
            .public_key(target.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(seconds=CERT_BACKDATE))
            .not_valid_after(target.not_valid_after_utc)
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .add_extension(
                x509.KeyUsage(
                    digital_signature=True,
                    key_cert_sign=True,
                    crl_sign=True,
                    content_commitment=False,
                    key_encipherment=False,
                    data_encipherment=False,
                    key_agreement=False,
                    encipher_only=False,
                    decipher_only=False,
                ),
                critical=True,
            )
            .sign(self._key, hashes.SHA256())
        )
        return cert.public_bytes(serialization.Encoding.PEM)

    def issue_and_save_new_certificates(
        self, node_id: str, role: int, org: str
    ) -> tuple[bytes, bytes]:
        """Locally issue a cert without the CSR round-trip — used by the
        first manager bootstrapping itself (reference:
        ca/certificates.go IssueAndSaveNewCertificates:234).
        Returns (key_pem, cert_pem)."""
        key_pem, csr_pem = create_csr(node_id, role, org)
        return key_pem, self.sign_csr(csr_pem)

    # -- validation --------------------------------------------------------

    def verify_cert(self, cert_pem: bytes) -> CertIdentity:
        """Validate signature chain + validity window, return the identity
        (reference: ca/certificates.go ValidateCertChain).

        `cert_pem` may be `leaf` or `leaf + intermediates` (rotation
        chains); this root may hold several anchors (rotation bundle). The
        leaf is accepted if it chains to ANY anchor, directly or through
        the supplied intermediates."""
        chain = x509.load_pem_x509_certificates(cert_pem)
        leaf, intermediates = chain[0], chain[1:]
        now = _now()
        for cert in chain:
            if now < cert.not_valid_before_utc \
                    or now > cert.not_valid_after_utc:
                raise CertificateError(
                    "certificate outside validity window")

        def links_to_anchor(cert, depth=0) -> bool:
            for anchor in self._certs:
                try:
                    cert.verify_directly_issued_by(anchor)
                    return True
                except Exception:
                    continue
            if depth >= 2:   # node chains are at most leaf+one intermediate
                return False
            for inter in intermediates:
                try:
                    cert.verify_directly_issued_by(inter)
                except Exception:
                    continue
                if links_to_anchor(inter, depth + 1):
                    return True
            return False

        if not links_to_anchor(leaf):
            raise CertificateError("certificate not issued by this root")
        return parse_cert_identity(cert_pem)


def cert_expiry(cert_pem: bytes) -> tuple[float, float]:
    """(not_before, not_after) as unix seconds."""
    cert = x509.load_pem_x509_certificate(cert_pem)
    return (
        cert.not_valid_before_utc.timestamp(),
        cert.not_valid_after_utc.timestamp(),
    )


def renewal_due(cert_pem: bytes, now: float) -> bool:
    """True once inside the renewal window — the last half of validity,
    mirroring ca/config.go calculateRandomExpiry's midpoint heuristic."""
    nb, na = cert_expiry(cert_pem)
    return now >= nb + (na - nb) / 2
