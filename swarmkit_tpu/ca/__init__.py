"""Security substrate: root CA, node certificates, tokens, authz, renewal.

Re-derivation of the reference `ca/` package (SURVEY.md §2.10)."""
from .auth import Caller, PermissionDenied, authorize_forwarded, authorize_roles, caller_from_cert
from .certificates import (
    CertificateError,
    CertIdentity,
    RootCA,
    cert_expiry,
    create_csr,
    parse_cert_identity,
    renewal_due,
)
from .config import (
    InvalidToken,
    ParsedToken,
    SecurityConfig,
    generate_join_token,
    parse_join_token,
)
from .keyreadwriter import KeyReadWriter
from .renewer import TLSRenewer
from .server import CAServer

__all__ = [
    "Caller",
    "PermissionDenied",
    "authorize_forwarded",
    "authorize_roles",
    "caller_from_cert",
    "CertificateError",
    "CertIdentity",
    "RootCA",
    "cert_expiry",
    "create_csr",
    "parse_cert_identity",
    "renewal_due",
    "InvalidToken",
    "ParsedToken",
    "SecurityConfig",
    "generate_join_token",
    "parse_join_token",
    "KeyReadWriter",
    "TLSRenewer",
    "CAServer",
]
