"""Security substrate: root CA, node certificates, tokens, authz, renewal.

Re-derivation of the reference `ca/` package (SURVEY.md §2.10)."""
from .auth import Caller, PermissionDenied, authorize_forwarded, authorize_roles, caller_from_cert

__all__ = [
    "Caller",
    "PermissionDenied",
    "authorize_forwarded",
    "authorize_roles",
    "caller_from_cert",
]

# gate on the `cryptography` wheel SPECIFICALLY — a genuine import bug in
# the certificate modules must still fail loudly, not silently strip the
# CA surface from the package
try:
    import cryptography  # noqa: F401

    _HAVE_CRYPTO = True
except ImportError:
    # container without the optional wheel: authz (Caller, role gates)
    # and the unix-socket rpc substrate still work; anything touching
    # real certificates raises ImportError at its own import
    _HAVE_CRYPTO = False

if _HAVE_CRYPTO:
    from .certificates import (
        CertificateError,
        CertIdentity,
        RootCA,
        cert_expiry,
        create_csr,
        parse_cert_identity,
        renewal_due,
    )
    from .config import (
        InvalidToken,
        ParsedToken,
        SecurityConfig,
        generate_join_token,
        parse_join_token,
    )
    from .keyreadwriter import KeyReadWriter
    from .renewer import TLSRenewer
    from .server import CAServer

    __all__ += [
        "CertificateError",
        "CertIdentity",
        "RootCA",
        "cert_expiry",
        "create_csr",
        "parse_cert_identity",
        "renewal_due",
        "InvalidToken",
        "ParsedToken",
        "SecurityConfig",
        "generate_join_token",
        "parse_join_token",
        "KeyReadWriter",
        "TLSRenewer",
        "CAServer",
    ]
