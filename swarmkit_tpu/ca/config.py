"""SecurityConfig: a node's live TLS identity, plus join tokens.

Re-derivation of ca/config.go: SecurityConfig bundles the trust root and the
node's own cert/key, hot-swappable on renewal (watchers are notified so gRPC
servers can pick up the new cert); join tokens pin the root digest so joining
nodes can authenticate the cluster before trusting it.

Token format (ca/config.go GenerateJoinToken / ParseJoinToken):
    SWMTKN-1-<sha256 digest of root cert, hex>-<random secret>
(the reference encodes the digest crockford-base32; we keep hex — same pin,
different encoding, tokens are not wire-compatible with Docker Swarm's)
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..analysis.lockgraph import make_lock
from ..utils.identity import new_id
from .certificates import (
    CertIdentity,
    CertificateError,
    RootCA,
    parse_cert_identity,
    renewal_due,
)

TOKEN_PREFIX = "SWMTKN"
TOKEN_VERSION = "1"


class InvalidToken(Exception):
    pass


def generate_join_token(root: RootCA, fips: bool = False) -> str:
    prefix = "FIPS." + TOKEN_PREFIX if fips else TOKEN_PREFIX
    return f"{prefix}-{TOKEN_VERSION}-{root.digest()}-{new_id()}"


@dataclass
class ParsedToken:
    version: str
    root_digest: str
    secret: str
    fips: bool


def parse_join_token(token: str) -> ParsedToken:
    fips = False
    if token.startswith("FIPS."):
        fips = True
        token = token[len("FIPS.") :]
    parts = token.split("-")
    if len(parts) != 4 or parts[0] != TOKEN_PREFIX:
        raise InvalidToken("malformed join token")
    if parts[1] != TOKEN_VERSION:
        raise InvalidToken(f"unsupported token version {parts[1]}")
    return ParsedToken(version=parts[1], root_digest=parts[2], secret=parts[3], fips=fips)


# how long the PREVIOUS trust anchors stay verifiable after a root swap.
# A rotation finishes when every node's cert was re-ISSUED under the new
# root, but issuance and the node's local INSTALL are separate steps: a
# node whose status poll raced out under load still SERVES its old-root
# leaf for a few renewal retries. Without a grace, the moment peers trim
# trust to the new root that node can never authenticate again — not
# even to renew. The grace bounds the tail: the old root was fully
# trusted seconds earlier, and it expires on a timer (docker's own
# rotation has the same anchors coexisting during the phased window).
ROTATION_TRUST_GRACE = 300.0


class SecurityConfig:
    """Trust root + node identity, renewal-aware (ca/config.go:SecurityConfig)."""

    def __init__(self, root: RootCA, key_pem: bytes, cert_pem: bytes,
                 clock=None):
        from ..utils.clock import REAL_CLOCK

        self._lock = make_lock('ca.config.lock')
        self._clock = clock or REAL_CLOCK
        self._root = root
        self._key_pem = key_pem
        self._cert_pem = cert_pem
        self._identity = root.verify_cert(cert_pem)
        self._watchers: list = []  # callables fired on cert/root update
        self._prev_trust_pem: bytes = b""
        self._prev_trust_until: float = 0.0
        self._grace_timer = None

    # -- accessors ---------------------------------------------------------

    @property
    def root_ca(self) -> RootCA:
        with self._lock:
            return self._root

    @property
    def identity(self) -> CertIdentity:
        with self._lock:
            return self._identity

    def node_id(self) -> str:
        return self.identity.node_id

    def role(self) -> int:
        return self.identity.role

    def key_and_cert(self) -> tuple[bytes, bytes]:
        with self._lock:
            return self._key_pem, self._cert_pem

    # -- updates -----------------------------------------------------------

    def watch(self, cb):
        with self._lock:
            self._watchers.append(cb)

    def update_tls_credentials(self, key_pem: bytes, cert_pem: bytes):
        """Swap in a renewed cert (ca/config.go UpdateTLSCredentials).

        The cert's public key must match the private key: concurrent renewal
        submissions can otherwise pair a cert issued for an older CSR with a
        newer key, leaving the node with a broken TLS identity."""
        from cryptography.hazmat.primitives import serialization as _ser
        from cryptography import x509 as _x509

        from .certificates import key_from_pem

        def spki(pub):
            return pub.public_bytes(
                _ser.Encoding.DER, _ser.PublicFormat.SubjectPublicKeyInfo)

        cert_pub = spki(_x509.load_pem_x509_certificate(cert_pem).public_key())
        key_pub = spki(key_from_pem(key_pem).public_key())
        if cert_pub != key_pub:
            raise CertificateError(
                "certificate public key does not match the private key")
        with self._lock:
            identity = self._root.verify_cert(cert_pem)
            self._key_pem, self._cert_pem = key_pem, cert_pem
            self._identity = identity
            watchers = list(self._watchers)
        for cb in watchers:
            cb(self)

    def update_root_ca(self, root: RootCA):
        """Swap the trust root (root rotation — ca/config.go UpdateRootCA).
        The outgoing anchors stay verifiable for ROTATION_TRUST_GRACE via
        `trust_anchors_pem` (TLS contexts build from it) so a peer whose
        cert install raced the rotation finish can still authenticate its
        renewal."""
        old_timer = None
        with self._lock:
            old = self._root
            if old is not None and old.cert_pem != root.cert_pem:
                self._prev_trust_pem = old.cert_pem
                self._prev_trust_until = (self._clock.time()
                                          + ROTATION_TRUST_GRACE)
                # long-lived TLS contexts only rebuild on security
                # events; re-fire the watchers when the grace lapses so
                # server/client contexts actually DROP the old anchors
                # at the bound instead of trusting them until the next
                # renewal happens to rebuild a context
                old_timer = self._grace_timer
                self._grace_timer = self._clock.timer(
                    ROTATION_TRUST_GRACE + 1.0, self._on_grace_expired)
            self._root = root
            watchers = list(self._watchers)
        if old_timer is not None:
            old_timer.cancel()
        for cb in watchers:
            cb(self)

    def _on_grace_expired(self):
        with self._lock:
            watchers = list(self._watchers)
        for cb in watchers:
            try:
                cb(self)          # contexts rebuild from trimmed anchors
            except Exception:     # a failed reload must not kill the wheel
                pass

    def trust_anchors_pem(self) -> bytes:
        """PEM anchors TLS contexts should trust right now: the current
        root (bundle) plus the previous anchors while inside the
        post-swap grace window."""
        with self._lock:
            pem = self._root.cert_pem
            if self._prev_trust_pem \
                    and self._clock.time() < self._prev_trust_until:
                pem = pem + self._prev_trust_pem
            return pem

    def renewal_due(self, now: float | None = None) -> bool:
        with self._lock:
            return renewal_due(self._cert_pem, now if now is not None else time.time())

    @classmethod
    def load_from_dir(cls, state_dir: str,
                      kek: bytes | None = None) -> "SecurityConfig":
        """Load a node identity from a swarmd state dir (cert.pem /
        key.json / ca.pem — the layout node/daemon.py persists). The one
        place the on-disk layout is interpreted; swarmctl/rafttool/tests
        all go through here."""
        import os

        from .keyreadwriter import KeyReadWriter

        with open(os.path.join(state_dir, "ca.pem"), "rb") as f:
            root = RootCA(f.read())
        key_pem, _headers = KeyReadWriter(
            os.path.join(state_dir, "key.json"), kek).read()
        with open(os.path.join(state_dir, "cert.pem"), "rb") as f:
            cert_pem = f.read()
        return cls(root, key_pem, cert_pem)

    @classmethod
    def bootstrap_manager(
        cls, node_id: str | None = None, org: str = "swarmkit-tpu"
    ) -> "SecurityConfig":
        """First-manager self-bootstrap: create a root and self-issue a
        manager cert (node/node.go loadSecurityConfig init path)."""
        from ..api.types import NodeRole

        node_id = node_id or new_id()
        root = RootCA.create(org)
        key_pem, cert_pem = root.issue_and_save_new_certificates(
            node_id, NodeRole.MANAGER, org
        )
        return cls(root, key_pem, cert_pem)


def identity_from_cert(cert_pem: bytes) -> CertIdentity:
    return parse_cert_identity(cert_pem)
