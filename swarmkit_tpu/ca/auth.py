"""Per-RPC role authorization.

Re-derivation of ca/auth.go: each RPC is gated on the caller's certificate
OU (role) and O (cluster); leader-proxied calls carry the original caller as
forwarded metadata which only a manager may assert
(AuthorizeOrgAndRole / AuthorizeForwardedRoleAndOrg, ca/auth.go:88-196).

The in-process transport passes a `Caller` explicitly where gRPC would derive
it from the peer TLS state; the wire transport builds a Caller from the peer
certificate via `caller_from_cert`.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..api.types import NodeRole


class PermissionDenied(Exception):
    pass


@dataclass
class Caller:
    """The authenticated peer of an RPC."""

    node_id: str
    role: int
    org: str
    forwarded_by: "Caller | None" = None  # set when a manager proxies a call


def caller_from_cert(cert_pem: bytes) -> Caller:
    # imported lazily: authz logic (and the rpc substrate over unix
    # sockets) must work without the optional `cryptography` wheel —
    # only actual certificate parsing needs it
    from .certificates import parse_cert_identity

    ident = parse_cert_identity(cert_pem)
    return Caller(node_id=ident.node_id, role=ident.role, org=ident.org)


def authorize_roles(caller: Caller | None, roles: list[int], org: str | None = None) -> Caller:
    """Gate an RPC on caller role (+ org when pinned). Returns the effective
    caller for handlers that need the identity (e.g. dispatcher sessions)."""
    if caller is None:
        raise PermissionDenied("no peer identity")
    if org is not None and caller.org != org:
        raise PermissionDenied(f"certificate from wrong cluster {caller.org!r}")
    if caller.role not in roles:
        raise PermissionDenied(
            f"role {NodeRole(caller.role).name.lower()} not authorized"
        )
    return caller


def authorize_forwarded(
    caller: Caller | None, roles: list[int], org: str | None = None
) -> Caller:
    """Accept either a direct caller with an allowed role, or a manager
    forwarding an original caller with an allowed role."""
    if caller is None:
        raise PermissionDenied("no peer identity")
    if caller.forwarded_by is not None:
        # the direct peer must be a manager to assert forwarded identity
        authorize_roles(caller.forwarded_by, [NodeRole.MANAGER], org)
        return authorize_roles(caller, roles, org)
    return authorize_roles(caller, roles, org)
