"""CA server: the manager-side certificate issuance service.

Re-derivation of ca/server.go: `issue_node_certificate` validates the join
token against the cluster object, records a CSR on a Node object with status
PENDING (the CSR flow is *store-replicated*, so any manager can answer and
the signing decision survives failover); a signing loop watches for pending
certs and signs them (signNodeCert, ca/server.go:764-881);
`node_certificate_status` long-polls until ISSUED (ca/server.go:148-232).
Also hosts root rotation entry points (ca/reconciler.go).
"""
from __future__ import annotations

import logging
import threading

from ..api.objects import (
    EventCreate,
    EventUpdate,
    Node,
    NodeCertificate,
    RootCAObj,
)
from ..api.specs import NodeSpec
from ..api.types import IssuanceState, NodeRole
from ..analysis.lockgraph import make_rlock
from ..store import by
from ..utils.identity import new_id
from .auth import PermissionDenied
from .certificates import CertificateError, RootCA
from .config import InvalidToken, parse_join_token
from ..utils.leadership import leadership_lost

log = logging.getLogger("swarmkit_tpu.ca")


class CAServer:
    """Signs CSRs recorded on Node objects (reference ca/server.go Server)."""

    def __init__(self, store, root: RootCA, cluster_id: str,
                 org: str = "swarmkit-tpu", external_ca=None,
                 cert_expiry: float | None = None, clock=None):
        from ..utils.clock import REAL_CLOCK

        self.clock = clock or REAL_CLOCK
        self.store = store
        self.root = root
        self.cluster_id = cluster_id
        self.org = org
        # node certificate lifetime (swarmd --cert-expiry; reference
        # CAConfig.NodeCertExpiry); None == the compiled default
        self.cert_expiry = cert_expiry
        if cert_expiry and external_ca is not None:
            log.warning(
                "--cert-expiry has no effect with an external CA: the "
                "external service controls issued certificate lifetimes")
        # optional ca.external.ExternalCA: signing delegates to the
        # operator's CA service; the local root stays the trust anchor
        # (ca/external.go — the external CA signs under the same root)
        self.external_ca = external_ca
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        self._status_cond = threading.Condition(
            make_rlock("ca.server.status_cond"))

    # -- service lifecycle -------------------------------------------------

    def start(self):
        self._stop = threading.Event()  # restartable across leadership cycles
        self._thread = threading.Thread(target=self._run, name="ca-server", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self):
        """Snapshot-then-watch over nodes with pending certs
        (ca/server.go Run:356-476). A ProposeError/NotLeader escaping the
        signing or reconcile pass means this manager was demoted — exit
        cleanly; the manager's leadership handler stop()s us anyway."""
        queue = self.store.watch_queue()
        ch = queue.watch()
        try:
            from ..store.watch import ChannelClosed

            self._sign_pending()
            while not self._stop.is_set():
                try:
                    ev = ch.get(timeout=0.2)
                except TimeoutError:
                    if self._wake.is_set():
                        self._wake.clear()
                        self._sign_pending()
                    self._reconcile_rotation()
                    continue
                except ChannelClosed:
                    # slow-subscriber overflow: resubscribe and resync
                    queue.stop_watch(ch)
                    ch = queue.watch()
                    self._sign_pending()
                    continue
                if isinstance(ev, (EventCreate, EventUpdate)) and isinstance(ev.obj, Node):
                    cert = ev.obj.certificate
                    if cert is not None and cert.status_state in (
                        IssuanceState.PENDING,
                        IssuanceState.RENEW,
                        IssuanceState.ROTATE,
                    ):
                        self._sign_pending()
                        self._reconcile_rotation()
        except Exception as exc:
            if not leadership_lost(exc):
                raise
            log.info("ca-server: leadership lost; stopping signer loop")
        finally:
            queue.stop_watch(ch)

    # -- RPC surface -------------------------------------------------------

    def get_root_ca_certificate(self) -> bytes:
        """CA.GetRootCACertificate (api/ca.proto:13-17) — unauthenticated.
        During a rotation this is the two-anchor trust bundle."""
        return self.trust_bundle_pem()

    def get_unlock_key(self) -> bytes | None:
        """CA.GetUnlockKey — the current autolock KEK from the cluster object."""
        cluster = self.store.view(lambda tx: tx.get_cluster(self.cluster_id))
        if cluster is None or not cluster.unlock_keys:
            return None
        return cluster.unlock_keys[0]

    def issue_node_certificate(
        self,
        csr_pem: bytes,
        token: str | None = None,
        node_id: str | None = None,
        caller=None,
    ) -> str:
        """NodeCA.IssueNodeCertificate (ca/server.go:234-354).

        New nodes present a join token → role is derived from which cluster
        token matched. Known nodes (renewal) present their node_id with no
        token; the renewal must be authenticated: the caller's cert CN must
        match the node being renewed (ca/server.go:278-292 checks the TLS
        peer identity), or the caller must be a manager. `caller=None` with
        no token is rejected for existing nodes.
        """
        role = None
        if token is not None:
            role = self._role_from_token(token)
        if node_id is None:
            node_id = new_id()

        def txn(tx):
            # Existence and renewal authorization are evaluated inside the
            # same transaction as the write: a join-token request racing node
            # creation for the same node_id must not overwrite the existing
            # node's cert/role (ca/server.go:278-292 — the TLS peer CN must
            # match the renewed node, or the caller must be a manager).
            cluster = tx.get_cluster(self.cluster_id)
            epoch = (cluster.root_ca.last_forced_rotation
                     if cluster is not None and cluster.root_ca is not None
                     else 0)
            node = tx.get_node(node_id)
            if node is None:
                if role is None:
                    raise InvalidToken("unknown node and no join token")
                node = Node(
                    id=node_id,
                    spec=NodeSpec(desired_role=role),
                    role=role,
                    certificate=NodeCertificate(
                        role=role,
                        csr_pem=csr_pem,
                        status_state=IssuanceState.PENDING,
                        cn=node_id,
                        rotation_epoch=epoch,
                    ),
                )
                tx.create(node)
            else:
                if (role is not None and node.certificate is not None
                        and node.certificate.csr_pem == csr_pem):
                    # idempotent join retry (ca/server.go:236-247 issuance
                    # re-entrancy): the cert was requested — possibly even
                    # issued — but the joiner's status poll timed out on a
                    # loaded machine and it re-submits the SAME CSR with a
                    # valid token. Re-processing is a no-op for security
                    # (same public key, token re-verified by the caller),
                    # and denying it wedges the join forever.
                    if (node.certificate.status_state
                            != IssuanceState.ISSUED
                            and getattr(node.certificate,
                                        "rotation_epoch", 0) != epoch):
                        # a rotation started since the original submission:
                        # the signer skips stale-epoch CSRs (they could
                        # never complete the rotation), so this retry IS
                        # the post-rotation re-request — refresh the epoch
                        # so the same-key CSR becomes signable again.
                        node = node.copy()
                        node.certificate.rotation_epoch = epoch
                        tx.update(node)
                    return node_id
                if caller is None or (
                    caller.node_id != node_id and caller.role != NodeRole.MANAGER
                ):
                    raise PermissionDenied(
                        f"renewal for {node_id} requires the node's own identity"
                    )
                cert_role = role if role is not None else (
                    node.certificate.role if node.certificate else node.role
                )
                node = node.copy()
                node.certificate = NodeCertificate(
                    role=cert_role,
                    csr_pem=csr_pem,
                    status_state=IssuanceState.PENDING,
                    cn=node_id,
                    rotation_epoch=epoch,
                )
                tx.update(node)

        self.store.update(txn)
        self._wake.set()
        return node_id

    def node_certificate_status(
        self, node_id: str, timeout: float = 10.0
    ) -> NodeCertificate:
        """NodeCA.NodeCertificateStatus long-poll (ca/server.go:148-232)."""
        end = self.clock.monotonic() + timeout
        while True:
            node = self.store.view(lambda tx: tx.get_node(node_id))
            if node is None:
                raise KeyError(f"node {node_id} not found")
            cert = node.certificate
            if cert is not None and cert.status_state in (
                IssuanceState.ISSUED,
                IssuanceState.FAILED,
            ):
                return cert
            remaining = end - self.clock.monotonic()
            if remaining <= 0:
                return cert
            with self._status_cond:
                self._status_cond.wait(timeout=min(0.1, remaining))

    # -- internals ---------------------------------------------------------

    def _role_from_token(self, token: str) -> int:
        parsed = parse_join_token(token)
        if parsed.root_digest != self.root.digest():
            raise InvalidToken("join token pins a different root CA")
        cluster = self.store.view(lambda tx: tx.get_cluster(self.cluster_id))
        if cluster is None or cluster.root_ca is None:
            raise InvalidToken("cluster has no CA configured")
        rca: RootCAObj = cluster.root_ca
        if token == rca.join_token_manager:
            return NodeRole.MANAGER
        if token == rca.join_token_worker:
            return NodeRole.WORKER
        raise InvalidToken("join token does not match cluster tokens")

    def _sign_pending(self):
        """Sign every node whose certificate is awaiting issuance
        (ca/server.go signNodeCert:764-881)."""
        pending = self.store.view(
            lambda tx: [
                n
                for n in tx.find_nodes(by.All())
                if n.certificate is not None
                and n.certificate.status_state
                in (IssuanceState.PENDING, IssuanceState.RENEW, IssuanceState.ROTATE)
            ]
        )
        cluster0 = self.store.view(
            lambda tx: tx.get_cluster(self.cluster_id))
        rot0 = (cluster0.root_ca.root_rotation
                if cluster0 is not None and cluster0.root_ca is not None
                else None)
        epoch0 = (cluster0.root_ca.last_forced_rotation
                  if cluster0 is not None and cluster0.root_ca is not None
                  else 0)
        # during a phased rotation the signer is the NEW root with the
        # cross-signed intermediate appended (ca/reconciler.go); one
        # snapshot per pass — per-node store views + key parses would
        # repeat identical work N times
        pass_signing_root = (
            RootCA(rot0["new_ca_cert_pem"], rot0["new_ca_key_pem"],
                   intermediate_pem=rot0["cross_signed_pem"])
            if rot0 else self.root)
        # external signer for this pass, selected FOR the active signing
        # root (constructor-time one, or the matching
        # ClusterSpec.CAConfig.external_cas entry — the control-API
        # path). A key-less signing root (rotation to an operator cert
        # whose key an external CA holds) REQUIRES a matching entry;
        # entries for OTHER roots must not sign (their certs would never
        # chain to this anchor and the rotation could never finish).
        pass_external = self._external_signer(pass_signing_root.cert_pem)
        for node in pending:
            if rot0 and getattr(node.certificate, "rotation_epoch", 0) != epoch0:
                # The CSR was recorded BEFORE this rotation's epoch bump.
                # Signing it now — under the NEW root — would hand the node
                # a cert that satisfies its client-side chain check
                # (node/daemon.py _ensure_rotation_renewal verifies the leaf
                # against the bundle's new anchor) while the reconciler
                # keeps waiting on the stale epoch: the node never re-CSRs
                # and the rotation wedges (the round-4 load flake — the
                # window is a renewal CSR in flight when rotate_root_ca
                # lands, e.g. the bundle-shrink renewal kicked by a PRIOR
                # rotation finishing). Leave it unsigned: the submitter's
                # status poll times out and its straggler check submits a
                # fresh CSR carrying the current epoch; token-join retries
                # refresh the epoch via the idempotent path below.
                continue
            signing_root = pass_signing_root
            observed_state = node.certificate.status_state
            signed_csr = node.certificate.csr_pem
            try:
                if pass_external is not None:
                    from .certificates import parse_cert_identity
                    from .external import ExternalCAError

                    try:
                        cert_pem = pass_external.sign(signed_csr)
                    except ExternalCAError:
                        continue  # transient: stays PENDING, retried
                    # the external service signs the CSR's self-asserted
                    # subject — refuse to PUBLISH a cert whose identity
                    # differs from what this server assigned (a node
                    # could otherwise CSR itself into CN=<other node> or
                    # OU=manager; the local path forces the subject in
                    # sign_csr, so only this path needs the check)
                    ident = parse_cert_identity(cert_pem)
                    if ident.node_id != node.id \
                            or ident.role != node.certificate.role:
                        raise CertificateError(
                            "external CA returned a certificate for "
                            f"{ident.node_id!r} role {ident.role}, expected "
                            f"{node.id!r} role {node.certificate.role}")
                else:
                    kwargs = {}
                    if self.cert_expiry:
                        kwargs["expiry"] = self.cert_expiry
                    cert_pem = signing_root.sign_csr(
                        signed_csr,
                        subject=(node.id, node.certificate.role, self.org),
                        **kwargs,
                    )
                state, err = IssuanceState.ISSUED, ""
            except Exception as exc:
                cert_pem, state, err = b"", IssuanceState.FAILED, str(exc)

            def txn(
                tx,
                node_id=node.id,
                cert_pem=cert_pem,
                state=state,
                err=err,
                observed_state=observed_state,
                signed_csr=signed_csr,
                signing_root=signing_root,
            ):
                n = tx.get_node(node_id)
                if n is None or n.certificate is None:
                    return
                if n.certificate.status_state != observed_state:
                    return  # raced: state moved (another signer, or ROTATE marked)
                if n.certificate.csr_pem != signed_csr:
                    # raced: a newer CSR was submitted while we signed the old
                    # one — publishing this cert would pair it with a key the
                    # node no longer holds; the newer CSR is signed next pass
                    return
                cluster = tx.get_cluster(self.cluster_id)
                rot_now = (cluster.root_ca.root_rotation
                           if cluster is not None
                           and cluster.root_ca is not None else None)
                if (rot_now or None) != (rot0 or None):
                    return  # raced with rotation start/finish: next pass
                if rot0 is None and signing_root is not self.root:
                    return  # raced with a trust swap: re-signed next pass
                n = n.copy()
                n.certificate.certificate_pem = cert_pem
                n.certificate.status_state = state
                n.certificate.status_err = err
                n.role = n.certificate.role  # observed role follows the cert
                tx.update(n)

            try:
                self.store.update(txn)
            except Exception as exc:
                if leadership_lost(exc):
                    raise  # _run treats this as a clean-shutdown signal
                # transient propose failure: the cert stays PENDING and the
                # next signing pass retries this node
                log.warning("publishing cert for %s failed transiently: %s",
                            node.id, exc)
        if pending:
            with self._status_cond:
                self._status_cond.notify_all()

    # -- root rotation -----------------------------------------------------
    #
    # Phased, as in ca/reconciler.go: rotation STARTS by recording the new
    # root (cert+key) and its cross-signed intermediate on the cluster
    # object; the signing loop immediately issues under the NEW key with
    # the intermediate appended (old-pinned nodes validate through the
    # cross-signature), while the published trust bundle carries BOTH
    # anchors. Unlike the reference reconciler (which force-marks straggler
    # certs ROTATE server-side), completion here is CLIENT-driven: each node
    # observes the multi-anchor bundle and re-CSRs itself
    # (node/daemon.py _ensure_rotation_renewal) — the epoch check below
    # requires a post-rotation CSR, which a server-side re-sign of a stale
    # CSR could never satisfy. The reconciler FINISHES — swapping the trust
    # anchor, digest, and join tokens — only when every node certificate
    # chains to the new root under the current epoch; down nodes hold the
    # rotation open (surfaced via rate-limited warnings) until the operator
    # removes them, matching `docker swarm ca --rotate` semantics. No node
    # is ever wedged: at every instant each node trusts whichever root its
    # peers' certs carry.

    def _rotation(self):
        cluster = self.store.view(
            lambda tx: tx.get_cluster(self.cluster_id))
        if cluster is None or cluster.root_ca is None:
            return None
        return cluster.root_ca.root_rotation

    def _external_signer(self, signing_cert_pem: bytes | None = None):
        """The external CA to sign with, FOR A GIVEN signing root: the
        constructor-injected one (swarmd --external-ca) always wins;
        otherwise the ClusterSpec.CAConfig.external_cas entry whose
        ca_cert matches `signing_cert_pem` (an entry without a ca_cert
        means "the cluster's current root", reference api CAConfig
        semantics). Per-root selection is what lets a rotation COMPLETE:
        during a rotation to a locally-keyed new root, the old root's
        external CA must NOT keep signing (its certs never chain to the
        new anchor — code-review r04 wedge), and with multiple entries
        the one for the ACTIVE signing root is the only correct signer.
        Cached per (url, pinned cert) so steady passes don't rebuild TLS
        contexts."""
        if self.external_ca is not None:
            return self.external_ca
        cluster = self.store.view(
            lambda tx: tx.get_cluster(self.cluster_id))
        if cluster is None:
            return None
        entries = (cluster.spec.ca.external_cas or []
                   if cluster.spec is not None else [])
        current_root = (cluster.root_ca.ca_cert_pem
                        if cluster.root_ca is not None else b"")
        want = (signing_cert_pem if signing_cert_pem is not None
                else current_root) or b""

        def entry_cert(e):
            c = e.get("ca_cert") or b""
            if isinstance(c, str):
                c = c.encode()
            return c.strip() or current_root.strip()

        entry = next((e for e in entries
                      if isinstance(e, dict)
                      and (e.get("protocol") or "cfssl") == "cfssl"
                      and e.get("url")
                      and entry_cert(e) == want.strip()), None)
        if entry is None:
            self._spec_external = None
            return None
        ca_cert = entry.get("ca_cert") or None
        if isinstance(ca_cert, str):
            ca_cert = ca_cert.encode()
        key = (entry["url"], ca_cert)
        cached = getattr(self, "_spec_external", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        from .external import ExternalCA

        signer = ExternalCA(entry["url"], trust_root_pem=ca_cert)
        self._spec_external = (key, signer)
        return signer

    def _signing_root(self) -> RootCA:
        rot = self._rotation()
        if rot:
            return RootCA(rot["new_ca_cert_pem"], rot["new_ca_key_pem"],
                          intermediate_pem=rot["cross_signed_pem"])
        return self.root

    def trust_bundle_pem(self) -> bytes:
        """The PEM anchors nodes should trust right now: both roots plus the
        cross-signed intermediate while a rotation is in flight (the
        intermediate lets a joiner whose token pins the OLD root verify that
        the old root vouches for the new one), else the single current
        root."""
        rot = self._rotation()
        if rot:
            return (self.root.cert_pem + rot["new_ca_cert_pem"]
                    + rot["cross_signed_pem"])
        return self.root.cert_pem

    def rotate_root_ca(self) -> RootCA:
        """Begin a phased root rotation (ca/reconciler.go). Returns the new
        root. Completion is CLIENT-driven: nodes observe the new trust
        bundle (session plane / root download), renew with a fresh CSR, and
        the reconciler finishes only when every node's cert was re-issued
        from a post-rotation CSR — i.e. the node itself fetched and swapped
        it. Re-signing old CSRs server-side would let the anchor swap race
        ahead of what nodes actually present on the wire."""
        if self.external_ca is not None:
            # the OPERATOR-PINNED external service (swarmd --external-ca)
            # signs everything under the old root's key; certs it issues
            # can never chain to a locally minted new root, so the
            # reconciler could never finish — fail fast instead of
            # wedging. (Spec-configured external CAs are selected
            # per-root in _external_signer, so a locally-keyed rotation
            # simply stops using them once the signing root flips.)
            raise CertificateError(
                "root rotation with an external CA configured requires "
                "rotating the external CA out-of-band, then updating the "
                "cluster CA config")
        new_root = RootCA.create(self.org)
        cross = self.root.cross_sign(new_root)

        def txn(tx):
            cluster = tx.get_cluster(self.cluster_id)
            if cluster is not None and cluster.root_ca is not None:
                cluster = cluster.copy()
                cluster.root_ca.root_rotation = {
                    "new_ca_cert_pem": new_root.cert_pem,
                    "new_ca_key_pem": new_root.key_pem or b"",
                    "cross_signed_pem": cross,
                }
                cluster.root_ca.last_forced_rotation += 1
                tx.update(cluster)

        self.store.update(txn)
        self._wake.set()
        return new_root

    def _reconcile_rotation(self):
        """ca/reconciler.go: finish an in-flight rotation (anchor / digest /
        token swap) once every node certificate chains to the new root AND
        was issued for a CSR submitted under the current rotation epoch."""
        rot = self._rotation()
        if not rot:
            return
        new_root = RootCA(rot["new_ca_cert_pem"])
        cluster = self.store.view(
            lambda tx: tx.get_cluster(self.cluster_id))
        epoch = cluster.root_ca.last_forced_rotation
        nodes = self.store.view(lambda tx: tx.find_nodes(by.All()))
        waiting: list[str] = []
        for n in nodes:
            cert = n.certificate
            if cert is None or not cert.csr_pem:
                continue
            if cert.status_state != IssuanceState.ISSUED \
                    or getattr(cert, "rotation_epoch", 0) != epoch:
                waiting.append(n.id)
                continue
            try:
                new_root.verify_cert(cert.certificate_pem)
            except Exception:
                waiting.append(n.id)
        if waiting:
            # like the reference (and docker swarm ca --rotate), rotation
            # waits for EVERY node — down nodes must be removed by the
            # operator; surface who is holding it up instead of stalling
            # silently
            now = self.clock.monotonic()
            if now - getattr(self, "_last_rotation_log", 0) > 30:
                self._last_rotation_log = now
                log.warning(
                    "root rotation waiting on %d node(s): %s",
                    len(waiting), ", ".join(sorted(waiting)[:5]))
            return

        full_new_root = RootCA(rot["new_ca_cert_pem"],
                               rot["new_ca_key_pem"] or None)

        def finish(tx):
            cluster = tx.get_cluster(self.cluster_id)
            if cluster is None or cluster.root_ca is None \
                    or not cluster.root_ca.root_rotation:
                return
            from .config import generate_join_token

            cluster = cluster.copy()
            cluster.root_ca.ca_cert_pem = full_new_root.cert_pem
            cluster.root_ca.ca_key_pem = full_new_root.key_pem or b""
            cluster.root_ca.cert_digest = full_new_root.digest()
            cluster.root_ca.join_token_worker = \
                generate_join_token(full_new_root, fips=cluster.fips)
            cluster.root_ca.join_token_manager = \
                generate_join_token(full_new_root, fips=cluster.fips)
            cluster.root_ca.root_rotation = None
            tx.update(cluster)

        try:
            self.store.update(finish)
        except Exception as exc:
            if leadership_lost(exc):
                raise  # _run treats this as a clean-shutdown signal
            log.warning("rotation finish failed transiently: %s; "
                        "retried next pass", exc)
            return
        self.root = full_new_root
