"""Timer-driven certificate renewal.

Re-derivation of ca/renewer.go: a loop that waits until the cert enters its
renewal window (or is told to renew now), requests a fresh cert through the
CA flow, and hot-swaps it into the SecurityConfig so servers pick it up.
"""
from __future__ import annotations

import threading

from .certificates import create_csr
from .config import SecurityConfig


class TLSRenewer:
    """Renews a SecurityConfig's cert against a CAServer-like issuer
    (ca/renewer.go TLSRenewer; request path ca/certificates.go
    RequestAndSaveNewCertificates:234)."""

    def __init__(self, security: SecurityConfig, ca_server,
                 check_interval: float = 1.0, clock=None,
                 retry_policy=None):
        from ..utils.backoff import Backoff
        from ..utils.clock import REAL_CLOCK

        self.security = security
        self.ca_server = ca_server
        self.check_interval = check_interval
        # injectable time source (utils/clock.py — the reference's
        # ClockSource seam): tests drive the renewal window with FakeClock
        # instead of waiting out real certificate lifetimes
        self.clock = clock or REAL_CLOCK
        # unified retry policy (utils/backoff.py): a failed renewal
        # round-trip backs off exponentially with jitter instead of
        # hammering the CA every check_interval (the reference's
        # renewer backoff, ca/renewer.go expBackoff); each retry is a
        # FRESH CSR, so it picks up the current rotation_epoch
        self.retry_policy = retry_policy or Backoff(
            base=check_interval, factor=2.0,
            max_delay=30 * check_interval, max_attempts=1 << 30)
        self._failures = 0
        self._stop = threading.Event()
        self._renew_now = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        self._thread = threading.Thread(target=self._run, name="tls-renewer", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._renew_now.set()
        if self._thread:
            self._thread.join(timeout=5)

    def renew_now(self):
        self._renew_now.set()

    def renew_once(self) -> bool:
        """One renewal round-trip: refresh trust root → CSR → issue → poll →
        swap. Returns True on success.

        The root refresh mirrors the reference's download of the remote root
        CA cert before requesting certs (ca/certificates.go
        GetRemoteCA / RequestAndSaveNewCertificates) — without it a rotated
        root would make every renewed cert fail local verification."""
        from ..api.types import IssuanceState
        from .auth import Caller
        from .certificates import RootCA

        server_root_pem = self.ca_server.get_root_ca_certificate()
        if server_root_pem != self.security.root_ca.cert_pem:
            self.security.update_root_ca(RootCA(server_root_pem))

        ident = self.security.identity
        caller = Caller(node_id=ident.node_id, role=ident.role, org=ident.org)
        key_pem, csr_pem = create_csr(ident.node_id, ident.role, ident.org)
        self.ca_server.issue_node_certificate(csr_pem, node_id=ident.node_id, caller=caller)
        cert = self.ca_server.node_certificate_status(ident.node_id)

        if cert is None or cert.status_state != IssuanceState.ISSUED:
            return False
        self.security.update_tls_credentials(key_pem, cert.certificate_pem)
        return True

    def _run(self):
        while not self._stop.is_set():
            # after consecutive failures the wait stretches to the
            # policy's (jittered) delay; renew_now still short-circuits
            wait = self.check_interval
            if self._failures:
                wait = max(wait, self.retry_policy.delay(
                    self._failures - 1))
            triggered = self.clock.wait(self._renew_now, wait)
            if self._stop.is_set():
                return
            if triggered:
                self._renew_now.clear()
                self._failures = 0     # an explicit kick retries at once
            if triggered or self.security.renewal_due(self.clock.time()):
                try:
                    ok = self.renew_once()
                except Exception:
                    ok = False
                # renew_once()==False is retryable the same way (a cert
                # still pending under a mid-flight root rotation): the
                # next attempt issues a FRESH CSR under the current
                # rotation epoch
                self._failures = 0 if ok else self._failures + 1
