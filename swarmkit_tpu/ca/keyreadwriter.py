"""At-rest protection of the node's TLS key, with metadata headers.

Re-derivation of ca/keyreadwriter.go: the node's private key PEM lives on
disk, optionally sealed with a cluster KEK (autolock); PEM headers piggyback
small metadata — the reference stores the raft DEKs there (manager/deks.go).
Rotating the KEK re-seals in place via atomic rename (ioutils.AtomicWriteFile).
"""
from __future__ import annotations

import base64
import json
import os
import threading
from ..analysis.lockgraph import make_lock

from cryptography.fernet import Fernet


def _derive_fernet(kek: bytes) -> Fernet:
    # Fernet wants a 32-byte urlsafe-b64 key; stretch arbitrary KEK bytes.
    import hashlib

    return Fernet(base64.urlsafe_b64encode(hashlib.sha256(kek).digest()))


class KeyReadWriter:
    """Read/write `key.pem` (+ headers) under an optional KEK."""

    def __init__(self, path: str, kek: bytes | None = None):
        self.path = path
        self._kek = kek
        self._lock = make_lock('ca.keyreadwriter.lock')

    # file format: JSON {sealed: bool, headers: {..}, key: b64}
    # (the reference uses PEM headers; JSON keeps the same content model
    # without a PEM parser round-trip)

    def write(self, key_pem: bytes, headers: dict[str, str] | None = None):
        with self._lock:
            self._write_locked(key_pem, headers or self._read_headers())

    def _write_locked(self, key_pem: bytes, headers: dict[str, str]):
        if self._kek is not None:
            blob = _derive_fernet(self._kek).encrypt(key_pem)
            sealed = True
        else:
            blob = key_pem
            sealed = False
        rec = {
            "sealed": sealed,
            "headers": headers,
            "key": base64.b64encode(blob).decode(),
        }
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # unique temp name: the instance lock cannot serialize two WRITERS
        # holding separate KeyReadWriter objects for the same path (cert
        # renewal vs root-rotation update both re-save the identity); with
        # a shared ".tmp" name one replace steals the other's temp file →
        # FileNotFoundError mid-rotation. Unique temps make each replace
        # atomic and self-contained; last writer wins, both files valid.
        # 0600 from birth: the key must never be world-readable, even in the
        # temp window (ioutils AtomicWriteFile + keyreadwriter.go perms)
        tmp = f"{self.path}.{os.getpid()}.{threading.get_ident()}.tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(rec, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)  # atomic (ioutils AtomicWriteFile)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def read(self) -> tuple[bytes, dict[str, str]]:
        with self._lock:
            return self._read_unlocked()

    def _read_record(self) -> dict:
        with open(self.path) as f:
            return json.load(f)

    def _read_headers(self) -> dict[str, str]:
        try:
            return self._read_record().get("headers", {})
        except FileNotFoundError:
            return {}

    def update_headers(self, update: dict[str, str | None]):
        """Merge headers (None deletes), re-writing the file — the raft DEK
        rotation handshake path (manager/deks.go RaftDEKManager)."""
        with self._lock:
            key_pem, headers = self._read_unlocked()
            for k, v in update.items():
                if v is None:
                    headers.pop(k, None)
                else:
                    headers[k] = v
            self._write_locked(key_pem, headers)

    def _read_unlocked(self) -> tuple[bytes, dict[str, str]]:
        rec = self._read_record()
        blob = base64.b64decode(rec["key"])
        if rec["sealed"]:
            if self._kek is None:
                raise PermissionError("key is locked and no KEK supplied")
            blob = _derive_fernet(self._kek).decrypt(blob)
        return blob, rec.get("headers", {})

    def rotate_kek(self, new_kek: bytes | None):
        """Re-seal the key under a new KEK (ca/keyreadwriter.go ViewAndRotateKEK)."""
        with self._lock:
            key_pem, headers = self._read_unlocked()
            self._kek = new_kek
            self._write_locked(key_pem, headers)

    @property
    def kek(self) -> bytes | None:
        return self._kek
