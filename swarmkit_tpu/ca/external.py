"""External CA client: delegate node-certificate signing to an HTTPS
service speaking the cfssl sign protocol (reference ca/external.go:228).

    POST <url>  {"certificate_request": "<csr pem>"}
    → {"success": true, "result": {"certificate": "<cert pem>"}}

The connection authenticates the endpoint against a pinned trust root (the
operator configures the external CA's certificate, CAConfig.external_cas);
request bodies carry no cluster secrets beyond the CSR. Signing failures
raise — the CA server keeps certificates PENDING and retries, identical to
a transiently unavailable local signer.
"""
from __future__ import annotations

import json
import ssl
import urllib.error
import urllib.request


class ExternalCAError(Exception):
    pass


class ExternalCA:
    """ca/external.go ExternalCA: Sign(csr) via a cfssl-compatible URL."""

    def __init__(self, url: str, trust_root_pem: bytes | None = None,
                 timeout: float = 10.0):
        self.url = url
        self.timeout = timeout
        if trust_root_pem:
            # pinned trust root, but STANDARD hostname verification stays on
            # (ca/external.go keeps it too): any cert holder under a shared
            # CA could otherwise MITM the signing endpoint
            self._ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            self._ctx.check_hostname = True
            self._ctx.verify_mode = ssl.CERT_REQUIRED
            self._ctx.load_verify_locations(
                cadata=trust_root_pem.decode())
        elif url.startswith("https://"):
            self._ctx = ssl.create_default_context()
        else:
            self._ctx = None

    def sign(self, csr_pem: bytes) -> bytes:
        body = json.dumps(
            {"certificate_request": csr_pem.decode()}).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ctx) as resp:
                payload = json.loads(resp.read().decode())
        except (urllib.error.URLError, OSError, ValueError) as exc:
            raise ExternalCAError(f"external CA request failed: {exc}") \
                from exc
        if not payload.get("success"):
            raise ExternalCAError(
                f"external CA refused to sign: {payload.get('errors')}")
        cert = payload.get("result", {}).get("certificate", "")
        if not cert:
            raise ExternalCAError("external CA returned no certificate")
        return cert.encode()
