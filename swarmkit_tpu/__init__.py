"""swarmkit_tpu — a TPU-native cluster-orchestration framework.

A ground-up re-architecture of the capability surface of moby/swarmkit
(mirrored as thaJeztah/swarmkit): raft-replicated declarative cluster state,
services reconciled into tasks, constraint-based spread scheduling, a
dispatch protocol to workers, and an mTLS CA — with the manager-side hot
loops (constraint evaluation, resource filtering, spread scoring, raft
log-replay quorum tally) executed as batched JAX/XLA kernels on TPU.

Layering (see SURVEY.md §1):
    api/          typed object model (L0)
    store/        transactional in-memory state store + watch (L1)
    raft/         consensus & replication (L2)
    scheduler/    constraint/filter/spread scheduler, CPU + TPU backends (L3)
    orchestrator/ replicated/global/job orchestrators, updater, restart (L3)
    dispatcher/   manager<->worker assignment plane (L4)
    agent/        worker runtime + executor framework (L7)
    ca/           security substrate (X1)
    ops/          JAX/Pallas kernels (mask/score/water-fill, raft replay)
    parallel/     device-mesh sharding of the kernels (pjit/shard_map)
    models/       assembled jittable "models" (flagship scheduling step)
    utils/        ids, misc
"""

__version__ = "0.1.0"
