"""Manager-side CSI volume lifecycle.

Re-derivation of manager/csi/manager.go:31-465: an event loop over Volume
and Task events that (1) creates volumes via the controller plugin and
records VolumeInfo, (2) publishes volumes to nodes whose assigned tasks use
them (PENDING_PUBLISH → controller_publish → PUBLISHED), (3) unpublishes
once no tasks on a node need the volume (PENDING_NODE_UNPUBLISH, confirmed
by the agent → PENDING_UNPUBLISH → controller_unpublish → status removed),
and (4) deletes pending_delete volumes once fully unpublished. Failures are
retried through the volumequeue's exponential backoff (100ms → 10min).
"""
from __future__ import annotations

import threading

from ..api.objects import EventCreate, EventDelete, EventUpdate, Task, Volume
from ..api.types import TaskState
from ..store import by
from ..store.watch import ChannelClosed
from ..utils.volumequeue import VolumeQueue
from .plugin import (
    PENDING_NODE_UNPUBLISH,
    PENDING_PUBLISH,
    PENDING_UNPUBLISH,
    PUBLISHED,
    PluginGetter,
    VolumePublishStatus,
)


class VolumeManager:
    def __init__(self, store, plugins: PluginGetter):
        self.store = store
        self.plugins = plugins
        self.queue = VolumeQueue()
        self._attempts: dict[str, int] = {}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._stop = threading.Event()
        self.queue = VolumeQueue()
        for target, name in ((self._run_events, "csi-events"), (self._run_queue, "csi-queue")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        # initial pass over existing volumes (snapshot-then-watch)
        for v in self.store.view(lambda tx: tx.find_volumes(by.All())):
            self.queue.enqueue(v.id)

    def stop(self):
        self._stop.set()
        self.queue.stop()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    # -- event plumbing ----------------------------------------------------

    def _run_events(self):
        queue = self.store.watch_queue()
        ch = queue.watch()
        try:
            while not self._stop.is_set():
                try:
                    ev = ch.get(timeout=0.2)
                except TimeoutError:
                    continue
                except ChannelClosed:
                    queue.stop_watch(ch)
                    ch = queue.watch()
                    for v in self.store.view(lambda tx: tx.find_volumes(by.All())):
                        self.queue.enqueue(v.id)
                    continue
                obj = getattr(ev, "obj", None)
                if isinstance(obj, Volume) and isinstance(ev, (EventCreate, EventUpdate)):
                    self._attempts.pop(obj.id, None)
                    self.queue.outdated(obj.id)
                    self.queue.enqueue(obj.id)
                elif isinstance(obj, Task) and isinstance(
                    ev, (EventCreate, EventUpdate, EventDelete)
                ):
                    # task movement can free a node's last use of a volume
                    for vid in obj.volumes:
                        self.queue.enqueue(vid)
        finally:
            queue.stop_watch(ch)

    def _run_queue(self):
        while not self._stop.is_set():
            item = self.queue.wait(timeout=0.5)
            if item is None:
                continue
            vid, _attempt = item
            try:
                self._process_volume(vid)
                self._attempts.pop(vid, None)
            except Exception:
                attempt = self._attempts.get(vid, 0) + 1
                self._attempts[vid] = attempt
                self.queue.enqueue(vid, attempt=attempt)

    # -- reconciliation ----------------------------------------------------

    def _process_volume(self, volume_id: str):
        v = self.store.view(lambda tx: tx.get_volume(volume_id))
        if v is None:
            return
        plugin = self.plugins.get(v.spec.driver)

        # 1. creation (manager.go createVolume)
        if v.volume_info is None and not v.pending_delete:
            info = plugin.create_volume(v)

            def set_info(tx):
                cur = tx.get_volume(volume_id)
                if cur is not None and cur.volume_info is None:
                    cur = cur.copy()
                    cur.volume_info = info
                    tx.update(cur)

            self.store.update(set_info)
            return

        # 2/3. publish & unpublish reconciliation (manager.go handleVolume)
        def nodes_needing(tx) -> set[str]:
            need = set()
            for t in tx.find_tasks(by.All()):
                if (
                    volume_id in t.volumes
                    and t.node_id
                    and t.desired_state <= TaskState.RUNNING
                ):
                    need.add(t.node_id)
            return need

        needed = self.store.view(nodes_needing)
        statuses = {s.node_id: s for s in v.publish_status}

        # new nodes → PENDING_PUBLISH entries
        missing = needed - set(statuses)
        # nodes no longer needed → start node-unpublish handshake
        stale = [
            s for s in v.publish_status
            if s.node_id not in needed and s.state == PUBLISHED
        ]
        if (missing or stale) and not v.pending_delete:
            def mark(tx):
                cur = tx.get_volume(volume_id)
                if cur is None:
                    return
                cur = cur.copy()
                have = {s.node_id for s in cur.publish_status}
                for n in sorted(missing):
                    if n not in have:
                        cur.publish_status.append(VolumePublishStatus(node_id=n))
                for s in cur.publish_status:
                    if s.node_id not in needed and s.state == PUBLISHED:
                        s.state = PENDING_NODE_UNPUBLISH
                tx.update(cur)

            self.store.update(mark)
            v = self.store.view(lambda tx: tx.get_volume(volume_id))
            if v is None:
                return

        # drive controller calls for pending states
        changed = False
        results: dict[str, tuple[str, dict]] = {}
        errors: list[Exception] = []
        for s in v.publish_status:
            if s.state == PENDING_PUBLISH:
                try:
                    ctx = plugin.controller_publish(v, s.node_id)
                    results[s.node_id] = (PUBLISHED, ctx)
                    changed = True
                except Exception as exc:
                    errors.append(exc)
            elif s.state == PENDING_UNPUBLISH:
                try:
                    plugin.controller_unpublish(v, s.node_id)
                    results[s.node_id] = ("remove", {})
                    changed = True
                except Exception as exc:
                    errors.append(exc)

        if changed:
            def apply(tx):
                cur = tx.get_volume(volume_id)
                if cur is None:
                    return
                cur = cur.copy()
                keep = []
                for s in cur.publish_status:
                    res = results.get(s.node_id)
                    if res is None:
                        keep.append(s)
                        continue
                    state, ctx = res
                    if state == "remove" and s.state == PENDING_UNPUBLISH:
                        continue  # fully unpublished
                    if state == PUBLISHED and s.state == PENDING_PUBLISH:
                        s.state = PUBLISHED
                        s.publish_context = ctx
                    keep.append(s)
                cur.publish_status = keep
                tx.update(cur)

            self.store.update(apply)
            v = self.store.view(lambda tx: tx.get_volume(volume_id))
            if v is None:
                return

        # 4. deletion (manager.go handleVolume pending_delete path)
        if v.pending_delete:
            if any(s.state == PUBLISHED for s in v.publish_status):
                def drain(tx):
                    cur = tx.get_volume(volume_id)
                    if cur is None:
                        return
                    for s in cur.publish_status:
                        if s.state == PUBLISHED:
                            s.state = PENDING_NODE_UNPUBLISH
                    tx.update(cur)

                self.store.update(drain)
                raise RuntimeError("waiting for unpublish before delete")
            if v.publish_status:
                raise RuntimeError("waiting for unpublish before delete")
            if v.volume_info is not None:
                plugin.delete_volume(v)
            self.store.update(lambda tx: tx.delete(Volume, volume_id))
            return

        if errors:
            raise errors[0]

    # -- agent confirmation (dispatcher UpdateVolumeStatus path) -----------

    def confirm_node_unpublish(self, volume_id: str, node_id: str):
        """The agent finished node-side unpublish: advance to
        PENDING_UNPUBLISH so the controller can detach (manager.go
        UpdateVolumeStatus handling)."""
        advance_node_unpublish(self.store, node_id, [volume_id])
        self.queue.enqueue(volume_id)


def advance_node_unpublish(store, node_id: str, volume_ids: list[str]):
    """Shared PENDING_NODE_UNPUBLISH → PENDING_UNPUBLISH transition — the
    single implementation behind both Dispatcher.update_volume_status and
    VolumeManager.confirm_node_unpublish."""

    def txn(tx):
        for vid in volume_ids:
            v = tx.get_volume(vid)
            if v is None:
                continue
            changed = False
            for s in v.publish_status:
                if s.node_id == node_id and s.state == PENDING_NODE_UNPUBLISH:
                    s.state = PENDING_UNPUBLISH
                    changed = True
            if changed:
                tx.update(v)

    store.update(txn)
