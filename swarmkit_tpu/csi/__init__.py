"""CSI cluster volumes: manager lifecycle, scheduler feasibility, plugins
(reference: manager/csi/, manager/scheduler/volumes.go, SURVEY.md §2.8)."""
from .manager import VolumeManager
from .plugin import (
    PENDING_NODE_UNPUBLISH,
    PENDING_PUBLISH,
    PENDING_UNPUBLISH,
    PUBLISHED,
    CSIPlugin,
    CSIPluginError,
    FakeCSIPlugin,
    PluginGetter,
    VolumeInfo,
    VolumePublishStatus,
)
from .volumes import VolumeSet, task_csi_mounts

__all__ = [
    "VolumeManager",
    "CSIPlugin",
    "CSIPluginError",
    "FakeCSIPlugin",
    "PluginGetter",
    "VolumeInfo",
    "VolumePublishStatus",
    "VolumeSet",
    "task_csi_mounts",
    "PENDING_PUBLISH",
    "PUBLISHED",
    "PENDING_NODE_UNPUBLISH",
    "PENDING_UNPUBLISH",
]
