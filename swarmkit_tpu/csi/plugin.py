"""CSI plugin interface + publish-status model.

Re-derivation of manager/csi/plugin.go + api/objects.proto VolumePublishStatus:
the manager drives a controller plugin (create/delete/publish/unpublish);
agents drive the node side (stage/publish). Real deployments speak CSI gRPC
to plugin sockets; the interface below is that wire surface, and
`FakeCSIPlugin` is the test double (testutils/fake_plugingetter.go analogue).
"""
from __future__ import annotations

import threading
from ..analysis.lockgraph import make_lock
from dataclasses import dataclass, field


# Publish lifecycle (api/objects.proto VolumePublishStatus.State; the
# manager moves down, the agent confirms the node-unpublish step)
PENDING_PUBLISH = "pending_publish"
PUBLISHED = "published"
PENDING_NODE_UNPUBLISH = "pending_node_unpublish"
PENDING_UNPUBLISH = "pending_controller_unpublish"


@dataclass
class VolumePublishStatus:
    node_id: str
    state: str = PENDING_PUBLISH
    publish_context: dict[str, str] = field(default_factory=dict)
    message: str = ""


@dataclass
class VolumeInfo:
    """api/objects.proto VolumeInfo: what the plugin reports on creation."""

    volume_id: str = ""
    capacity_bytes: int = 0
    volume_context: dict[str, str] = field(default_factory=dict)
    accessible_topology: list[dict[str, str]] = field(default_factory=list)


class CSIPluginError(Exception):
    pass


class CSIPlugin:
    """Controller + node RPC surface (manager/csi/plugin.go Plugin;
    agent/csi/plugin/plugin.go NodePlugin)."""

    name = "csi-plugin"

    # controller side (manager)
    def create_volume(self, volume) -> VolumeInfo:
        raise NotImplementedError

    def delete_volume(self, volume) -> None:
        raise NotImplementedError

    def controller_publish(self, volume, node_id: str) -> dict[str, str]:
        """Returns the publish context for the node."""
        raise NotImplementedError

    def controller_unpublish(self, volume, node_id: str) -> None:
        raise NotImplementedError

    # node side (agent)
    def node_stage(self, volume_assignment) -> None:
        raise NotImplementedError

    def node_unstage(self, volume_assignment) -> None:
        raise NotImplementedError

    def node_publish(self, volume_assignment) -> None:
        raise NotImplementedError

    def node_unpublish(self, volume_assignment) -> None:
        raise NotImplementedError


class PluginGetter:
    """name -> plugin registry (manager/csi/manager.go newPluginManager)."""

    def __init__(self, plugins: dict[str, CSIPlugin] | None = None):
        self._plugins = dict(plugins or {})

    def add(self, plugin: CSIPlugin):
        self._plugins[plugin.name] = plugin

    def get(self, name: str) -> CSIPlugin:
        if name not in self._plugins:
            raise CSIPluginError(f"no CSI plugin {name!r}")
        return self._plugins[name]

    def names(self) -> list[str]:
        return sorted(self._plugins)


class FakeCSIPlugin(CSIPlugin):
    """Deterministic fake with failure injection and a call log."""

    def __init__(self, name: str = "fake-csi", topology: list[dict[str, str]] | None = None):
        self.name = name
        self.topology = topology or []
        self.calls: list[tuple] = []
        self.fail_next: set[str] = set()  # op names that fail once
        self._lock = make_lock('csi.plugin.lock')
        self._serial = 0

    def _record(self, op: str, *args):
        with self._lock:
            self.calls.append((op, *args))
            if op in self.fail_next:
                self.fail_next.discard(op)
                raise CSIPluginError(f"{op} failed (injected)")

    def create_volume(self, volume) -> VolumeInfo:
        self._record("create_volume", volume.id)
        with self._lock:
            self._serial += 1
            serial = self._serial
        return VolumeInfo(
            volume_id=f"{self.name}-vol-{serial}",
            capacity_bytes=1 << 30,
            accessible_topology=list(self.topology),
        )

    def delete_volume(self, volume) -> None:
        self._record("delete_volume", volume.id)

    def controller_publish(self, volume, node_id: str) -> dict[str, str]:
        self._record("controller_publish", volume.id, node_id)
        return {"device": f"/dev/{volume.id[:8]}"}

    def controller_unpublish(self, volume, node_id: str) -> None:
        self._record("controller_unpublish", volume.id, node_id)

    def node_stage(self, volume_assignment) -> None:
        self._record("node_stage", volume_assignment.volume_id)

    def node_unstage(self, volume_assignment) -> None:
        self._record("node_unstage", volume_assignment.volume_id)

    def node_publish(self, volume_assignment) -> None:
        self._record("node_publish", volume_assignment.volume_id)

    def node_unpublish(self, volume_assignment) -> None:
        self._record("node_unpublish", volume_assignment.volume_id)
