"""Scheduler-side cluster-volume feasibility and reservation.

Re-derivation of manager/scheduler/volumes.go:45-327 (`volumeSet`) and
topology.go: for each CSI mount of a task, pick a live volume matching the
mount source (name, or `group:<name>`), honoring availability, access-mode
scope/sharing, node topology, and single-scope in-use reservations;
`check_volumes_on_node` is the VolumesFilter predicate and
`choose_task_volumes` the commit-time selection (reservation recorded so
parallel groups in one tick don't oversubscribe single-scope volumes).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..analysis.lockgraph import make_lock
from ..api.objects import Volume
from ..csi.plugin import PENDING_NODE_UNPUBLISH, PENDING_UNPUBLISH


GROUP_PREFIX = "group:"


@dataclass
class _VolumeUsage:
    pub_nodes: set[str] = field(default_factory=set)  # store publish_status
    task_nodes: dict[str, str] = field(default_factory=dict)  # task -> node

    @property
    def nodes(self) -> set[str]:
        """Nodes currently tied to the volume: published there, or reserved
        by a task placed there. Derived, so unpublish/release actually frees
        single-scope volumes for other nodes."""
        return self.pub_nodes | set(self.task_nodes.values())

    @property
    def tasks(self) -> set[str]:
        return set(self.task_nodes)


def task_csi_mounts(task) -> list:
    runtime = task.spec.runtime
    if runtime is None:
        return []
    return [m for m in runtime.mounts if m.type == "csi"]


class VolumeSet:
    """volumes.go volumeSet: store-shadowed volume state + reservations."""

    def __init__(self):
        self._lock = make_lock('csi.volumes.lock')
        self.volumes: dict[str, Volume] = {}
        self.by_group: dict[str, set[str]] = {}
        self.by_name: dict[str, str] = {}
        self.usage: dict[str, _VolumeUsage] = {}

    # -- store shadowing ---------------------------------------------------

    def add_or_update_volume(self, v: Volume):
        with self._lock:
            old = self.volumes.get(v.id)
            if old is not None:
                self.by_name.pop(old.spec.annotations.name, None)
                if old.spec.group:
                    self.by_group.get(old.spec.group, set()).discard(v.id)
            self.volumes[v.id] = v
            self.by_name[v.spec.annotations.name] = v.id
            if v.spec.group:
                self.by_group.setdefault(v.spec.group, set()).add(v.id)
            usage = self.usage.setdefault(v.id, _VolumeUsage())
            # published/pending nodes count as usage (volumes.go restore
            # path); rebuilt each update so unpublished nodes are released
            usage.pub_nodes = {
                st.node_id
                for st in v.publish_status
                if st.state not in (PENDING_NODE_UNPUBLISH, PENDING_UNPUBLISH)
            }

    def remove_volume(self, volume_id: str):
        with self._lock:
            v = self.volumes.pop(volume_id, None)
            if v is None:
                return
            self.by_name.pop(v.spec.annotations.name, None)
            if v.spec.group:
                self.by_group.get(v.spec.group, set()).discard(volume_id)
            self.usage.pop(volume_id, None)

    def reserve_task(self, task):
        """Record a placed task's volumes (setup from store snapshot)."""
        with self._lock:
            for vid in task.volumes:
                u = self.usage.setdefault(vid, _VolumeUsage())
                u.task_nodes[task.id] = task.node_id or ""

    def release_task(self, task):
        """volumes.go freeVolumes: a task died — its reservations drop (the
        node publication itself is undone by the CSI manager)."""
        with self._lock:
            for vid in task.volumes:
                u = self.usage.get(vid)
                if u is not None:
                    u.task_nodes.pop(task.id, None)

    # -- feasibility -------------------------------------------------------

    def _candidates(self, source: str) -> list[Volume]:
        if source.startswith(GROUP_PREFIX):
            ids = self.by_group.get(source[len(GROUP_PREFIX) :], set())
            return [self.volumes[i] for i in ids]
        vid = self.by_name.get(source)
        return [self.volumes[vid]] if vid else []

    def _usable_on_node(self, v: Volume, node) -> bool:
        """volumes.go isVolumeAvailableOnNode: availability, scope/sharing,
        in-use nodes, topology."""
        if v.spec.availability != "active":
            return False
        if v.pending_delete:
            return False
        u = self.usage.get(v.id, _VolumeUsage())
        mode = v.spec.access_mode
        node_id = node.node.id if hasattr(node, "node") else node.id
        if mode.scope == "single" and u.nodes and node_id not in u.nodes:
            return False
        if mode.sharing == "none" and u.tasks:
            return False
        if mode.sharing == "onewriter" and u.tasks:
            # feasibility only — the writer check needs the mount's readonly
            # flag, applied in choose(); conservatively allow here
            pass
        # the node must run the volume's CSI driver (volumes.go
        # isVolumeAvailableOnNode: no NodeCSIInfo for the driver → no)
        desc = node.node.description if hasattr(node, "node") else node.description
        if desc is None:
            return False
        csi_info = desc.csi_info or {}
        ninfo = csi_info.get(v.spec.driver)
        if ninfo is None and v.spec.driver not in desc.csi_plugins:
            return False
        # topology: node's per-plugin accessible segments must cover one of
        # the volume's accessible topologies (topology.go IsInTopology)
        info = v.volume_info
        topos = info.accessible_topology if info is not None else []
        if topos:
            segments = ninfo.accessible_topology if ninfo is not None else {}
            if not any(
                all(segments.get(k) == val for k, val in topo.items())
                for topo in topos
            ):
                return False
        return True

    def check_volumes_on_node(self, node, task) -> bool:
        """VolumesFilter predicate (filter.go:388-447)."""
        with self._lock:
            for m in task_csi_mounts(task):
                cands = self._candidates(m.source)
                if not any(self._usable_on_node(v, node) for v in cands):
                    return False
        return True

    # -- selection ---------------------------------------------------------

    def choose_task_volumes(self, task, node) -> list[str] | None:
        """volumes.go chooseTaskVolumes: pick one volume per CSI mount for
        this node and reserve them; None if any mount is unsatisfiable
        (the scheduler retries the task next tick)."""
        chosen: list[str] = []
        with self._lock:
            node_id = node.node.id if hasattr(node, "node") else node.id
            for m in task_csi_mounts(task):
                pick = None
                for v in sorted(self._candidates(m.source), key=lambda v: v.id):
                    if not self._usable_on_node(v, node):
                        continue
                    u = self.usage.get(v.id, _VolumeUsage())
                    if (
                        v.spec.access_mode.sharing == "onewriter"
                        and not m.readonly
                        and any(u.tasks)
                    ):
                        continue
                    pick = v
                    break
                if pick is None:
                    # roll back reservations made for earlier mounts
                    for vid in chosen:
                        u = self.usage.get(vid)
                        if u is not None:
                            u.task_nodes.pop(task.id, None)
                    return None
                chosen.append(pick.id)
                u = self.usage.setdefault(pick.id, _VolumeUsage())
                u.task_nodes[task.id] = node_id
        return chosen
