"""CSI plugin wire protocol: out-of-process plugins over a unix socket.

The reference talks CSI gRPC to plugin sockets (manager/csi/plugin.go
Plugin.Client → csi.NewControllerClient; agent/csi/plugin/plugin.go
NodeClient), discovering capabilities via GetPluginCapabilities /
ControllerGetCapabilities / NodeGetCapabilities and skipping optional
stages the plugin doesn't implement (PUBLISH_UNPUBLISH_VOLUME,
STAGE_UNSTAGE_VOLUME). This module is that boundary re-built on this
framework's native RPC substrate (msgpack frames over a unix socket —
the same wire swarmd's control socket uses) instead of gRPC/protobuf:

  * `CSIPluginServer` wraps any CSIPlugin implementation and serves the
    controller+node method set plus the identity/capability handshake;
  * `RemoteCSIPlugin` is the in-process adapter: it connects, performs
    the handshake (plugin name, vendor version, capability flags), and
    then satisfies the `CSIPlugin` interface so `csi.manager.
    VolumeManager` and `agent.csi.NodeVolumeManager` drive a REAL
    external process exactly as they drive an in-process plugin.

Capability semantics mirror CSI: a plugin without `controller_publish`
skips the controller-publish round trip (the publish context is empty,
like CSI skipping ControllerPublishVolume); one without `stage_unstage`
makes node_stage/node_unstage no-ops. `cmd/csi_plugin_example.py` is a
runnable plugin (directory-backed volumes) for demos and tests.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..analysis.lockgraph import make_lock
from ..api.types import NodeRole
from .plugin import CSIPlugin, CSIPluginError, VolumeInfo

_ALL_ROLES = [NodeRole.MANAGER, NodeRole.WORKER]


@dataclass
class PluginCapabilities:
    """The handshake payload (GetPluginCapabilities +
    ControllerGetCapabilities + NodeGetCapabilities, collapsed)."""

    controller: bool = True        # serves the controller method set
    node: bool = True              # serves the node method set
    controller_publish: bool = True   # CSI PUBLISH_UNPUBLISH_VOLUME
    stage_unstage: bool = True        # CSI STAGE_UNSTAGE_VOLUME


@dataclass
class PluginInfo:
    """GetPluginInfo."""

    name: str = ""
    vendor_version: str = ""
    manifest: dict[str, str] = field(default_factory=dict)


class _PluginIdentity:
    """Minimal security shim for the unix RPC listener: the socket's
    filesystem permissions are the trust boundary (same model as swarmd's
    control socket)."""

    def __init__(self, name: str):
        from ..ca.auth import Caller

        self.identity = Caller(node_id=f"csi-plugin-{name}",
                               role=NodeRole.MANAGER, org="")


class CSIPluginServer:
    """Serve a CSIPlugin implementation on a unix socket."""

    def __init__(self, plugin: CSIPlugin, socket_path: str,
                 capabilities: PluginCapabilities | None = None,
                 vendor_version: str = "0.1"):
        from ..rpc.server import RPCServer, ServiceRegistry

        self.plugin = plugin
        self.socket_path = socket_path
        self.capabilities = capabilities or PluginCapabilities()
        info = PluginInfo(name=plugin.name, vendor_version=vendor_version)

        reg = ServiceRegistry()

        def add(name, fn):
            reg.add(f"csi.{name}", fn, roles=_ALL_ROLES)

        add("get_plugin_info", lambda caller: info)
        add("get_capabilities", lambda caller: self.capabilities)
        add("create_volume",
            lambda caller, v: plugin.create_volume(v))
        add("delete_volume",
            lambda caller, v: plugin.delete_volume(v))
        add("controller_publish",
            lambda caller, v, node_id: plugin.controller_publish(v, node_id))
        add("controller_unpublish",
            lambda caller, v, node_id:
            plugin.controller_unpublish(v, node_id))
        add("node_stage", lambda caller, va: plugin.node_stage(va))
        add("node_unstage", lambda caller, va: plugin.node_unstage(va))
        add("node_publish", lambda caller, va: plugin.node_publish(va))
        add("node_unpublish", lambda caller, va: plugin.node_unpublish(va))

        self._server = RPCServer("", _PluginIdentity(plugin.name), reg,
                                 unix_path=socket_path)

    def start(self):
        self._server.start()

    def stop(self):
        self._server.stop()


class RemoteCSIPlugin(CSIPlugin):
    """CSIPlugin backed by a plugin process's unix socket.

    `connect()` performs the identity + capability handshake; the adapter
    then honors the negotiated capabilities the way the reference's
    wrappers do (skip ControllerPublish / treat stage as no-op when the
    plugin doesn't advertise them)."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self.name = ""           # set by connect() from GetPluginInfo
        self.info: PluginInfo | None = None
        self.capabilities: PluginCapabilities | None = None
        self._client = None
        self._lock = make_lock('csi.wire.lock')

    # ------------------------------------------------------------ handshake
    def connect(self, timeout: float = 10.0) -> "RemoteCSIPlugin":
        client = self._conn(timeout)
        info = client.call("csi.get_plugin_info")
        caps = client.call("csi.get_capabilities")
        with self._lock:
            self.info = info
            self.capabilities = caps
            self.name = info.name
        return self

    def close(self):
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            client.close()

    def _conn(self, timeout: float = 10.0):
        from ..rpc.client import RPCClient

        with self._lock:
            if self._client is not None and self._client.alive:
                return self._client
            self._client = RPCClient(f"unix://{self.socket_path}",
                                     connect_timeout=timeout)
            return self._client

    def _call(self, method: str, *args):
        try:
            return self._conn().call(f"csi.{method}", *args)
        except CSIPluginError:
            raise
        except Exception as exc:
            # transport failures surface as plugin errors: the volume
            # queues' retry/backoff machinery owns recovery
            raise CSIPluginError(f"{self.name or self.socket_path}: "
                                 f"{method} failed: {exc}")

    def _caps(self) -> PluginCapabilities:
        if self.capabilities is None:
            try:
                self.connect()
            except CSIPluginError:
                raise
            except Exception as exc:
                # same contract as _call: transport failures belong to the
                # volume queues' retry machinery, as CSIPluginError
                raise CSIPluginError(
                    f"{self.name or self.socket_path}: handshake failed: "
                    f"{exc}")
        return self.capabilities

    def _require(self, flag: str):
        if not getattr(self._caps(), flag):
            raise CSIPluginError(
                f"plugin {self.name!r} does not advertise the "
                f"{flag} capability")

    # ----------------------------------------------------- controller side
    def create_volume(self, volume) -> VolumeInfo:
        self._require("controller")
        return self._call("create_volume", volume)

    def delete_volume(self, volume) -> None:
        self._require("controller")
        self._call("delete_volume", volume)

    def controller_publish(self, volume, node_id: str) -> dict[str, str]:
        if not self._caps().controller_publish:
            # CSI: no PUBLISH_UNPUBLISH_VOLUME capability → skip the round
            # trip; the node side mounts without a controller context
            return {}
        return self._call("controller_publish", volume, node_id)

    def controller_unpublish(self, volume, node_id: str) -> None:
        if not self._caps().controller_publish:
            return
        self._call("controller_unpublish", volume, node_id)

    # ----------------------------------------------------------- node side
    def node_stage(self, volume_assignment) -> None:
        if not self._caps().stage_unstage:
            return  # CSI: no STAGE_UNSTAGE_VOLUME capability
        self._call("node_stage", volume_assignment)

    def node_unstage(self, volume_assignment) -> None:
        if not self._caps().stage_unstage:
            return
        self._call("node_unstage", volume_assignment)

    def node_publish(self, volume_assignment) -> None:
        self._require("node")
        self._call("node_publish", volume_assignment)

    def node_unpublish(self, volume_assignment) -> None:
        self._require("node")
        self._call("node_unpublish", volume_assignment)
