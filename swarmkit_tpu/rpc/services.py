"""Service adapters: expose manager components over the RPC substrate.

The reference wires each gRPC service with an authenticatedWrapper (role
gate from the peer cert) and a raft proxy (non-leader managers transparently
forward to the leader) — manager/manager.go:480-561,
protobuf/plugin/{authenticatedwrapper,raftproxy}. Here:

  * `build_manager_registry` declares every method with its allowed roles;
  * write paths route through `_leader_forward`: served locally on the
    leader, proxied to the leader's RPC endpoint otherwise, with the
    original caller carried as forwarded identity (only managers may
    assert it — enforced in rpc/server.py);
  * client shims (RemoteDispatcher, RemoteControl, RemoteCA, RemoteLogs)
    present the same method surface as the in-process objects, so the
    agent and CLI run unchanged over the wire.
"""
from __future__ import annotations

import logging
import threading

from ..analysis.lockgraph import make_lock
from ..api.types import NodeRole
from ..ca.auth import Caller, PermissionDenied
from ..utils.backoff import DEFAULT_RPC, Backoff, retry
from ..utils.clock import REAL_CLOCK, Clock
from .client import RPCClient
from .server import ANON, ServiceRegistry

log = logging.getLogger("swarmkit_tpu.rpc.services")

MANAGER = NodeRole.MANAGER
WORKER = NodeRole.WORKER


class NotLeaderError(Exception):
    """Raised when a write reaches a non-leader manager that cannot locate
    (or reach) the current leader."""


class LeaderConns:
    """Cached client connection to the current raft leader
    (manager/raftselector + raft.go LeaderConn:1512-1541)."""

    def __init__(self, raft_node, security):
        self.raft = raft_node
        self.security = security
        self._lock = make_lock('rpc.services.leader_conns')
        self._client: RPCClient | None = None
        self._client_addr: str | None = None

    def leader_addr(self) -> str | None:
        node = self.raft
        if node is None:
            return None
        leader_id = node.leader_id
        if leader_id is None or leader_id == node.id:
            return None
        peer = node.members.get(leader_id)
        if peer is None or not peer.addr or peer.addr.startswith("mem://"):
            return None
        return peer.addr

    def client(self) -> RPCClient:
        addr = self.leader_addr()
        if addr is None:
            raise NotLeaderError("no reachable raft leader")
        with self._lock:
            if self._client is not None and self._client.alive \
                    and self._client_addr == addr:
                return self._client
            old, self._client = self._client, None
        if old is not None:
            old.close()
        client = RPCClient(addr, security=self.security)
        with self._lock:
            self._client = client
            self._client_addr = addr
        return client

    def close(self):
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            client.close()


def _strip_forward(caller: Caller | None) -> Caller | None:
    if caller is None:
        return None
    return Caller(node_id=caller.node_id, role=caller.role, org=caller.org)


def build_manager_registry(manager, raft_node=None,
                           leader_conns: LeaderConns | None = None,
                           registry: ServiceRegistry | None = None,
                           follower_reads=None,
                           ) -> ServiceRegistry:
    """Declare every plane on one registry (manager.go Run:441-641).

    Pass `registry` to fill a pre-existing (already-served) registry — the
    daemon binds its listener before the manager objects exist so the raft
    advertise address is known first.

    `follower_reads` (a dispatcher.follower.FollowerReadPlane, ISSUE 13)
    lets a NON-leader manager serve the read half of the worker protocol
    — Assignments/Tasks streams and watch-API reads — under the raft
    read lease; with no plane (or no live lease) those reads bounce with
    NotLeaderError and clients redirect to the leader as before. Writes
    (registration, status write-back) always leader-forward."""
    reg = registry if registry is not None else ServiceRegistry()
    is_leader = (lambda: True) if raft_node is None else \
        (lambda: raft_node.is_leader)

    def leader_forward(method_name, local_fn):
        """Serve locally on the leader; otherwise forward to the leader with
        the original caller as forwarded identity. A call that already
        carries a forwarded identity is never forwarded again (one hop)."""

        def wrapper(caller, *args, **kwargs):
            if is_leader() or (caller is not None
                               and caller.forwarded_by is not None):
                return local_fn(caller, *args, **kwargs)
            if leader_conns is None:
                raise NotLeaderError("not the leader and no forwarding path")
            client = leader_conns.client()
            return client.call(method_name, *args,
                               _forwarded_caller=_strip_forward(caller),
                               **kwargs)

        return wrapper

    # ---------------------------------------------------------------- raft
    if raft_node is not None:
        # membership changes must serialize: two concurrent joins would
        # both read max(members)+1 and claim the SAME raft id, leaving two
        # processes answering for one quorum seat (the reference guards
        # Join with the membership lock for exactly this)
        join_lock = make_lock('rpc.services.join_lock')

        def raft_step(caller, msg):
            frm = getattr(msg, "frm", None)
            if frm is not None and frm in raft_node.removed_ids:
                # reference membership.go ErrMemberRemoved: a removed
                # member's messages are answered with the TYPED marker so a
                # member demoted WHILE DOWN learns its fate on restart
                # (it never applied its own removal — the quorum stopped
                # replicating to it)
                from ..raft.messages import MemberRemovedError

                raise MemberRemovedError("raft: member removed")
            raft_node.step(msg)
            return None

        def raft_step_many(caller, msgs):
            """Batched transport path: a backlogged peer outbox coalesces
            into one RPC (raft/transport.py SEND_BATCH). The removed-member
            check runs once up front — every message in a batch carries the
            same sender, and stepping part of a removed member's batch
            before answering with the marker would be wrong either way."""
            for msg in msgs:
                frm = getattr(msg, "frm", None)
                if frm is not None and frm in raft_node.removed_ids:
                    from ..raft.messages import MemberRemovedError

                    raise MemberRemovedError("raft: member removed")
            for msg in msgs:
                raft_node.step(msg)
            return None

        def raft_resolve_address(caller, raft_id):
            peer = raft_node.members.get(raft_id)
            return peer.addr if peer is not None else None

        def raft_join(caller, node_id, addr):
            """RaftMembership.Join (api/raft.proto:39-44, raft.go Join:926):
            leader allocates a raft id, proposes the conf-change, returns
            the member list for the joiner's bootstrap. Serialized: the id
            allocation reads the membership it is about to extend."""
            with join_lock:
                return _raft_join_locked(caller, node_id, addr)

        def _raft_join_locked(caller, node_id, addr):
            from ..raft.messages import ConfChange
            from ..utils.identity import new_id

            if not raft_node.is_leader:
                raise NotLeaderError("join must be served by the leader")

            def propose(cc):
                done = threading.Event()
                outcome = {}

                def cb(ok, err=""):
                    outcome["ok"] = ok
                    outcome["err"] = err
                    done.set()

                raft_node.propose_conf_change(cc, new_id(), cb)
                if not done.wait(10) or not outcome.get("ok"):
                    raise NotLeaderError(
                        f"join failed: {outcome.get('err', 'timeout')}")

            existing = raft_node.member_by_node_id(node_id)
            if existing is not None:
                if existing.addr != addr:
                    # a member came back on a new address (restart with an
                    # ephemeral port): replicate the repair so EVERY member
                    # re-learns the dial address, not just this leader
                    # (transport.go UpdatePeerAddr + ResolveAddress)
                    raft_node.transport.update_peer_addr(existing.raft_id,
                                                         addr)
                    propose(ConfChange(action="add",
                                       raft_id=existing.raft_id,
                                       node_id=node_id, addr=addr))
                return (existing.raft_id, _member_list(raft_node))
            # never reuse a REMOVED member's id: peers answer removed ids
            # with the removed marker forever (raft_step above), which
            # would instantly eject the new member
            raft_id = max(max(raft_node.members, default=0),
                          max(raft_node.removed_ids, default=0)) + 1
            propose(ConfChange(action="add", raft_id=raft_id,
                               node_id=node_id, addr=addr))
            return (raft_id, _member_list(raft_node))

        def raft_leave(caller, node_id):
            if not raft_node.is_leader:
                raise NotLeaderError("leave must be served by the leader")
            if not raft_node.remove_member_by_node_id(node_id):
                raise NotLeaderError("leave failed (quorum check)")
            return None

        reg.add("raft.step", raft_step, roles=[MANAGER])
        reg.add("raft.step_many", raft_step_many, roles=[MANAGER])
        reg.add("raft.resolve_address", raft_resolve_address, roles=[MANAGER])
        # join/leave are leader-only operations, but a joiner only knows one
        # manager address — forward so any manager can serve them
        # (raftproxy wiring of RaftMembership, manager.go:480-561)
        reg.add("raft.join", leader_forward("raft.join", raft_join),
                roles=[MANAGER])
        reg.add("raft.leave", leader_forward("raft.leave", raft_leave),
                roles=[MANAGER])

    # --------------------------------------------------------------- cluster
    def cluster_announce_manager(caller, node_id, addr, raft_id):
        """A (re)started manager records its reachable RPC address + raft id
        on its Node object; the dispatcher's session plane serves this
        manager list to agents (node join flow, manager.go becomeLeader
        self-registration)."""
        if caller is not None and caller.node_id != node_id:
            raise PermissionDenied("managers may only announce themselves")

        def txn(tx):
            node = tx.get_node(node_id)
            if node is None:
                return
            node = node.copy()  # stored objects are live references
            if node.manager_status is None:
                from ..api.objects import ManagerStatus

                node.manager_status = ManagerStatus()
            node.manager_status.addr = addr
            node.manager_status.raft_id = raft_id
            node.manager_status.reachability = "reachable"
            tx.update(node)
            # reconcile every manager's leader flag from raft's view —
            # announces re-fire on leadership change, so this keeps
            # `node ls` pointing at the live leader, not the last bootstrap
            leader_raft_id = raft_node.leader_id if raft_node else None
            for other in tx.find_nodes():
                ms = other.manager_status
                if ms is None or not ms.raft_id:
                    continue
                should_lead = (leader_raft_id is not None
                               and ms.raft_id == leader_raft_id)
                if ms.leader != should_lead:
                    other = other.copy()
                    other.manager_status.leader = should_lead
                    tx.update(other)

        manager.store.update(txn)
        return None

    def cluster_managers(caller):
        """Reachable manager endpoints (the Session message's manager list,
        api/dispatcher.proto WeightedPeer)."""

        def view(tx):
            out = []
            for n in tx.find_nodes():
                ms = n.manager_status
                if ms is not None and ms.addr:
                    out.append((n.id, ms.addr))
            return out

        return manager.store.view(view)

    reg.add("cluster.announce_manager",
            leader_forward("cluster.announce_manager",
                           cluster_announce_manager), roles=[MANAGER])
    reg.add("cluster.managers", cluster_managers,
            roles=[NodeRole.WORKER, MANAGER])

    # ---------------------------------------------------------- dispatcher
    d = manager.dispatcher

    def _require_node(caller, node_id):
        # the authenticated CN is the node identity; a node may only drive
        # its own session (dispatcher.go register derives from TLS state)
        if caller is None or (caller.node_id != node_id
                              and caller.role != MANAGER):
            raise PermissionDenied("session node id must match certificate")

    def disp_register(caller, node_id, description=None):
        _require_node(caller, node_id)
        return d.register(node_id, description)

    def disp_register_many(caller, node_ids, description=None,
                           availability=None, channel_limit=None):
        # MANAGER-only (enforced again by roles below): a worker cert
        # names exactly one node and must not mint sessions for others;
        # batched joins are an operator/bench surface (ISSUE 16)
        if caller is None or caller.role != MANAGER:
            raise PermissionDenied("batched registration is manager-only")
        return d.register_many(node_ids, description,
                               availability=availability,
                               channel_limit=channel_limit)

    def disp_heartbeat(caller, node_id, session_id, metrics=None):
        _require_node(caller, node_id)
        return d.heartbeat(node_id, session_id, metrics=metrics)

    def _follower_read(serve):
        """Serve a read stream from the follower plane, translating a
        dead lease into the NotLeaderError clients already redirect
        on (RemoteDispatcher follows dispatcher.leader_addr)."""
        from ..dispatcher.follower import FollowerReadUnavailable

        try:
            return serve()
        except FollowerReadUnavailable as exc:
            raise NotLeaderError(str(exc)) from exc

    def disp_assignments(caller, node_id, session_id):
        _require_node(caller, node_id)
        if not is_leader() and follower_reads is not None:
            # lease-gated follower serving (ISSUE 13): the stream is a
            # READ — session ids name leader-side liveness state this
            # manager does not have, so identity is the cert-checked
            # node id alone. Status write-back stays leader-only.
            return _follower_read(
                lambda: follower_reads.assignments(node_id))
        return d.assignments(node_id, session_id)  # Channel -> stream

    def disp_update_task_status(caller, node_id, session_id, updates):
        _require_node(caller, node_id)
        return d.update_task_status(node_id, session_id, updates)

    def disp_update_volume_status(caller, node_id, session_id, unpublished):
        _require_node(caller, node_id)
        return d.update_volume_status(node_id, session_id, unpublished)

    def disp_leave(caller, node_id, session_id):
        _require_node(caller, node_id)
        return d.leave(node_id, session_id)

    both = [WORKER, MANAGER]
    reg.add("dispatcher.register",
            leader_forward("dispatcher.register", disp_register), roles=both)
    reg.add("dispatcher.register_many",
            leader_forward("dispatcher.register_many", disp_register_many),
            roles=[MANAGER])
    reg.add("dispatcher.heartbeat",
            leader_forward("dispatcher.heartbeat", disp_heartbeat), roles=both)
    def disp_session(caller, node_id, session_id):
        _require_node(caller, node_id)
        return d.session(node_id, session_id)

    def disp_tasks(caller, node_id, session_id):
        _require_node(caller, node_id)
        if not is_leader() and follower_reads is not None:
            return _follower_read(lambda: follower_reads.tasks(node_id))
        return d.tasks(node_id, session_id)

    reg.add("dispatcher.assignments", disp_assignments, roles=both,
            streaming=True)  # streams cannot hop; agents follow the leader
    reg.add("dispatcher.session", disp_session, roles=both, streaming=True)
    # legacy Tasks fallback stream (api/dispatcher.proto:40-47) — wire
    # parity for agents that predate Assignments
    reg.add("dispatcher.tasks", disp_tasks, roles=both, streaming=True)
    reg.add("dispatcher.update_task_status",
            leader_forward("dispatcher.update_task_status",
                           disp_update_task_status), roles=both)
    reg.add("dispatcher.update_volume_status",
            leader_forward("dispatcher.update_volume_status",
                           disp_update_volume_status), roles=both)
    reg.add("dispatcher.leave",
            leader_forward("dispatcher.leave", disp_leave), roles=both)

    def disp_leader_addr(caller):
        """Where the assignment stream lives (agents redirect here)."""
        if is_leader():
            return None  # you are talking to the leader
        if leader_conns is None:
            raise NotLeaderError("no leader known")
        addr = leader_conns.leader_addr()
        if addr is None:
            raise NotLeaderError("no leader known")
        return addr

    reg.add("dispatcher.leader_addr", disp_leader_addr, roles=both)

    # ------------------------------------------------------------------ ca
    ca = manager.ca_server

    def ca_issue(caller, csr_pem, token=None, node_id=None):
        return ca.issue_node_certificate(csr_pem, token=token,
                                         node_id=node_id, caller=caller)

    def ca_status(caller, node_id, timeout=10.0):
        return ca.node_certificate_status(node_id, timeout=min(timeout, 30.0))

    def ca_root(caller):
        return ca.get_root_ca_certificate()

    reg.add("ca.issue_node_certificate",
            leader_forward("ca.issue_node_certificate", ca_issue),
            roles=[ANON])
    reg.add("ca.node_certificate_status", ca_status, roles=[ANON])
    reg.add("ca.get_root_ca_certificate", ca_root, roles=[ANON])

    # -------------------------------------------------------------- control
    control = manager.control_api
    for name in dir(control):
        if name.startswith("_"):
            continue
        fn = getattr(control, name)
        if not callable(fn):
            continue

        def local(caller, *args, _fn=fn, **kwargs):
            return _fn(*args, **kwargs)

        # the control surface is manager-role only (the CLI authenticates
        # with the node's manager certificate; workers have no business
        # mutating cluster state — reference authorizes Control as manager)
        reg.add(f"control.{name}",
                leader_forward(f"control.{name}", local), roles=[MANAGER])

    # ---------------------------------------------------------------- logs
    broker = manager.log_broker

    def logs_subscribe(caller, selector, follow=True, limit=-1):
        # limit=-1 takes the broker's default client bound (sharded
        # plane: CLIENT_CHANNEL_LIMIT with shed-don't-stall overflow);
        # None keeps the unbounded oracle stream
        _sub_id, ch = broker.subscribe_logs(selector, follow=follow,
                                            limit=limit)
        return ch

    def logs_listen_subscriptions(caller, node_id):
        _require_node(caller, node_id)
        return broker.listen_subscriptions(node_id)

    def logs_publish(caller, sub_id, messages, node_id="", close=False,
                     error=""):
        # the node identity is the CALLER's, not self-asserted: a
        # publisher can only close its own accounting slot (the reference
        # derives it from the TLS peer, broker.go:385)
        return broker.publish_logs(sub_id, messages,
                                   node_id=caller.node_id if close else "",
                                   close=close, error=error)

    reg.add("logs.subscribe", logs_subscribe, roles=[MANAGER], streaming=True)
    reg.add("logs.listen_subscriptions", logs_listen_subscriptions,
            roles=both, streaming=True)
    reg.add("logs.publish", logs_publish, roles=both)

    # --------------------------------------------------------------- watch
    watch_api = manager.watch_api

    def watch_events(caller, selectors=None, since_version=None):
        # lease-gated on non-leaders (ISSUE 13): a follower with a live
        # read lease serves its replicated store (bounded staleness); a
        # partitioned/lagging one bounces instead of silently serving
        # arbitrarily stale events. Managers without the plane keep the
        # historical serve-anything behavior.
        if not is_leader() and follower_reads is not None \
                and not follower_reads.read_ok():
            raise NotLeaderError(
                "watch reads need the leader or a live read lease")
        return watch_api.watch(selectors, since_version)

    reg.add("watch.events", watch_events, roles=[MANAGER], streaming=True)

    # -------------------------------------------------------------- health
    def health_check(caller, service=""):
        return manager.health.check(service)

    reg.add("health.check", health_check, roles=[ANON])

    return reg


def _member_list(raft_node):
    return [(p.raft_id, p.node_id, p.addr)
            for p in raft_node.members.values()]


# --------------------------------------------------------------------------
# Client shims: in-process method surface over the wire.
# --------------------------------------------------------------------------


class RemoteDispatcher:
    """Drop-in for the Dispatcher object held by an Agent; reconnection is
    the agent's session loop's job (it already retries register).

    `addr` may be a single manager or a comma-separated seed list; the shim
    follows the leader (assignment streams cannot hop) and falls back to the
    next seed when the manager it was pinned to dies — the wire analogue of
    remotes.Remotes weighted re-selection (agent/session.go:90-118)."""

    def __init__(self, addr: str, security, connect_timeout: float = 10.0):
        self.seeds = [a.strip() for a in addr.split(",") if a.strip()]
        self.addr = self.seeds[0]
        self.security = security
        self._connect_timeout = connect_timeout
        self._lock = make_lock('rpc.services.remote_dispatcher')
        self._client: RPCClient | None = None

    def update_managers(self, addrs: list[str]):
        """Merge freshly-learned manager endpoints into the seed list (the
        Session message manager-list plane)."""
        with self._lock:
            for a in addrs:
                if a and a not in self.seeds:
                    self.seeds.append(a)

    def _conn(self) -> RPCClient:
        with self._lock:
            if self._client is not None and self._client.alive:
                return self._client
            self._client = None
            candidates = [self.addr] + [s for s in self.seeds
                                        if s != self.addr]
        last_exc: Exception | None = None
        for addr in candidates:
            try:
                client = RPCClient(addr, security=self.security,
                                   connect_timeout=self._connect_timeout)
            except OSError as exc:
                last_exc = exc
                continue
            with self._lock:
                self._client = client
                self.addr = addr
            return client
        raise ConnectionError(
            f"no reachable manager among {candidates}: {last_exc}")

    def register(self, node_id, description=None):
        # follow the leader: the assignments stream cannot be proxied, so
        # sessions are opened against the leader's endpoint directly
        addr = self._conn().call("dispatcher.leader_addr")
        if addr is not None and addr != self.addr:
            self.close()
            with self._lock:
                self.addr = addr
        return self._conn().call("dispatcher.register", node_id, description)

    def heartbeat(self, node_id, session_id, metrics=None):
        if metrics is None:
            # keep the wire frame of a plain beat unchanged (and old
            # servers compatible) when no snapshot rides along
            return self._conn().call("dispatcher.heartbeat", node_id,
                                     session_id)
        return self._conn().call("dispatcher.heartbeat", node_id,
                                 session_id, metrics=metrics)

    def assignments(self, node_id, session_id):
        return self._conn().stream("dispatcher.assignments", node_id,
                                   session_id)

    def session(self, node_id, session_id):
        return self._conn().stream("dispatcher.session", node_id, session_id)

    def tasks(self, node_id, session_id):
        """Legacy Dispatcher.Tasks stream (full task lists per change);
        superseded by assignments() — served for wire parity."""
        return self._conn().stream("dispatcher.tasks", node_id, session_id)

    def update_task_status(self, node_id, session_id, updates):
        return self._conn().call("dispatcher.update_task_status", node_id,
                                 session_id, updates)

    def update_volume_status(self, node_id, session_id, unpublished):
        return self._conn().call("dispatcher.update_volume_status", node_id,
                                 session_id, unpublished)

    def leave(self, node_id, session_id):
        return self._conn().call("dispatcher.leave", node_id, session_id)

    def close(self):
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            client.close()


class RemoteCA:
    """ca_server surface for node bootstrap + renewal (the TLSRenewer and
    Node.run use exactly these four methods).

    `addr` may be a comma-separated seed list; `seeds_fn` (optional) supplies
    a live manager list (e.g. the dispatcher shim's refreshed seeds) so
    renewal keeps working after the original join endpoint dies."""

    def __init__(self, addr: str, security=None,
                 root_cert_pem: bytes | None = None,
                 seeds_fn=None):
        self.seeds = [a.strip() for a in addr.split(",") if a.strip()]
        self.addr = self.seeds[0]
        self.security = security
        self.root_cert_pem = root_cert_pem
        self.seeds_fn = seeds_fn
        self._lock = make_lock('rpc.services.remote_ca')
        self._client: RPCClient | None = None

    def _conn(self) -> RPCClient:
        with self._lock:
            if self._client is not None and self._client.alive:
                return self._client
            self._client = None
            candidates = list(dict.fromkeys(
                [self.addr] + self.seeds
                + (list(self.seeds_fn()) if self.seeds_fn else [])))
        last: Exception | None = None
        for addr in candidates:
            try:
                client = RPCClient(addr, security=self.security,
                                   root_cert_pem=self.root_cert_pem)
            except OSError as exc:
                last = exc
                continue
            with self._lock:
                self._client = client
                self.addr = addr
            return client
        raise ConnectionError(
            f"no reachable manager among {candidates}: {last}")

    # all four CA methods are idempotent (CSR joins are retried with
    # idempotent semantics server-side — round-3 invariant), so
    # maybe-executed transients may retry under the unified policy too
    def issue_node_certificate(self, csr_pem, token=None, node_id=None,
                               caller=None):
        # `caller` is derived server-side from the TLS peer; accepted here
        # for in-process signature compatibility and ignored
        return self._conn().call("ca.issue_node_certificate", csr_pem,
                                 token=token, node_id=node_id,
                                 retry_policy=DEFAULT_RPC,
                                 idempotent=True)

    def node_certificate_status(self, node_id, timeout: float = 10.0):
        # the long-poll happens server-side; give the RPC a little
        # headroom. NO retry policy: a timeout here must fail fast so
        # _conn()'s multi-candidate failover rotates to the next manager
        # instead of re-polling a dead one for attempts × deadline
        return self._conn().call("ca.node_certificate_status", node_id,
                                 timeout, timeout=timeout + 10.0)

    def get_root_ca_certificate(self):
        return self._conn().call("ca.get_root_ca_certificate",
                                 retry_policy=DEFAULT_RPC,
                                 idempotent=True)

    def close(self):
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            client.close()


class RemoteLogBroker:
    """LogBroker surface over the wire: the agent side (listen/publish) and
    the client side (subscribe) of api/logbroker.proto."""

    def __init__(self, addr: str, security):
        self.addr = addr
        self.security = security
        self._lock = make_lock('rpc.services.remote_logbroker')
        self._client: RPCClient | None = None

    def _conn(self) -> RPCClient:
        with self._lock:
            if self._client is not None and self._client.alive:
                return self._client
            self._client = RPCClient(self.addr, security=self.security)
            return self._client

    def listen_subscriptions(self, node_id):
        return self._conn().stream("logs.listen_subscriptions", node_id)

    def publish_logs(self, sub_id, messages, node_id="", close=False,
                     error=""):
        # node_id rides the TLS identity server-side; passed here only
        # for signature parity with the in-process broker
        return self._conn().call("logs.publish", sub_id, messages,
                                 close=close, error=error)

    def subscribe_logs(self, selector, follow=True, limit=-1):
        ch = self._conn().stream("logs.subscribe", selector, follow=follow,
                                 limit=limit)
        return None, ch  # (sub_id, channel) — matches LogBroker surface

    def close(self):
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            client.close()


class RemoteControl:
    """controlapi.ControlAPI surface over the wire (for swarmctl).

    A call landing on a manager that knows no leader (election in flight)
    is retried briefly — the reference's connection broker re-selects a
    manager instead of surfacing transient NotLeader errors to the CLI.

    Retries are an explicit `utils/backoff.py` policy (the PR 3
    contract: no ad-hoc sleep loops), clock-injectable so tests drive
    them under FakeClock."""

    # jitter=False: the old loop GUARANTEED a 30 s window (fixed 0.5 s
    # pauses to a deadline) — a jittered policy's window is a random
    # sum whose lower tail would surface transients the old client
    # always rode out. Deterministic delays 0.5+1+2x17 = 35.5 s keep
    # that guarantee (the old fixed cadence was lockstep too, and CLI
    # clients are few). The attempt count bounds FAST failures; the
    # RETRY_WINDOW deadline below bounds SLOW ones (a starved server
    # eating a full call timeout per read-only attempt must not stretch
    # 20 attempts to minutes — the old loop's wall-clock bound, kept).
    RETRY_POLICY = Backoff(base=0.5, factor=2.0, max_delay=2.0,
                           max_attempts=20, jitter=False)
    RETRY_WINDOW = 30.0

    def __init__(self, addr: str, security, clock: Clock | None = None):
        self.addr = addr
        self.security = security
        self._clock = clock or REAL_CLOCK
        self._lock = make_lock('rpc.services.remote_control')
        self._client: RPCClient | None = None

    def _conn(self) -> RPCClient:
        with self._lock:
            if self._client is not None and self._client.alive:
                return self._client
            self._client = RPCClient(self.addr, security=self.security)
            return self._client

    @staticmethod
    def _transient(exc: Exception) -> bool:
        import ssl as _ssl

        from .wire import RPCError

        if isinstance(exc, RPCError) and exc.name == "NotLeaderError":
            return True
        from .wire import ConnectionClosed

        if isinstance(exc, ConnectionClosed) \
                and getattr(exc, "unsent", False):
            # the request never reached the server as a complete frame
            # (connection died between _conn()'s aliveness check and the
            # send — e.g. a server reloading its TLS trust right after a
            # root-rotation finish kills just-opened connections): safe
            # to retry on a fresh connection even for writes
            return True
        # mid-rotation credential swap: for a moment the server's listener
        # cert and this client's trust bundle come from different epochs.
        # The reference rides this out via gRPC's transparent reconnect
        # backoff; a wrong identity still fails — just after the window.
        # A handshake EOF (server dropped the connection before the
        # session established) can't have executed anything either.
        return isinstance(exc, (_ssl.SSLCertVerificationError,
                                _ssl.SSLEOFError))

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        # read-only methods are idempotent: a starved server that
        # answers after the client's call timeout is a retry, not an
        # error (writes are NOT retried on timeout — the first attempt
        # may have committed)
        read_only = name.startswith(("get_", "list_"))

        def call(*args, **kwargs):
            deadline = self._clock.monotonic() + self.RETRY_WINDOW

            def retryable(exc: Exception) -> bool:
                if self._clock.monotonic() >= deadline:
                    return False
                return self._transient(exc) or (
                    read_only and isinstance(exc, TimeoutError))

            return retry(
                lambda: self._conn().call(f"control.{name}", *args,
                                          **kwargs),
                policy=self.RETRY_POLICY, retryable=retryable,
                clock=self._clock)

        return call

    def close(self):
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            client.close()
