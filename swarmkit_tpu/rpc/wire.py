"""Stream-RPC wire protocol: length-prefixed msgpack frames over (m)TLS.

The reference's three communication planes all ride gRPC+mTLS
(manager/state/raft/transport/, api/dispatcher.proto, api/control.proto).
Our equivalent is a small multiplexed stream protocol over one TLS
connection per peer pair:

    frame    := uint32_be length ++ msgpack body
    body     := [type, stream_id, head, payload]
    type     := REQ | RESP | ERR | STREAM_ITEM | STREAM_END | CANCEL
    head     := method name (REQ), error name (ERR), "" otherwise
    payload  := codec-encoded args / result / error message

A REQ opens a stream id chosen by the client (monotonically increasing
ints). Unary calls answer with one RESP or ERR. Streaming calls answer
with STREAM_ITEMs terminated by STREAM_END or ERR; the client may abort
early with CANCEL.

TLS identity: certificates minted by the cluster CA carry CN=node-id,
OU=role, O=org (ca/certificates.py); both ends verify the peer chain
against the cluster root, and servers derive the authenticated Caller from
the client certificate — the analogue of the reference's
ca/auth.go:88-196 per-RPC authorization.
"""
from __future__ import annotations

import os
import socket
import ssl
import struct
import tempfile
import threading

from ..ca.auth import Caller
from ..utils import failpoints
from . import codec

REQ, RESP, ERR, STREAM_ITEM, STREAM_END, CANCEL = 1, 2, 3, 4, 5, 6

MAX_FRAME = 64 * 1024 * 1024  # large snapshots must fit; DoS-bounded
_LEN = struct.Struct(">I")


class RPCError(Exception):
    """Server-reported error with no registered local exception type."""

    def __init__(self, name: str, message: str):
        super().__init__(f"{name}: {message}")
        self.name = name
        self.message = message


class ConnectionClosed(Exception):
    pass


def safe_close(sock, wlock: threading.Lock | None = None) -> None:
    """Close a socket other threads may still be WRITING to.

    Closing an fd while a sibling thread sits inside `sendall` frees the
    fd NUMBER with the write still in flight; the kernel recycles it
    instantly (an mkstemp, another socket) and the bytes land in the new
    object — observed in round 4 as a TLS record spliced in front of a
    daemon's freshly-written state.json. `shutdown()` first: it kills
    both directions without freeing the fd (the in-flight sendall/recv
    fail with EPIPE/ECONNRESET), then the fd is released under the
    connection's write lock so no writer can still be inside sendall."""
    import socket as _socket

    try:
        sock.shutdown(_socket.SHUT_RDWR)
    except (OSError, ValueError):
        pass
    if wlock is not None:
        with wlock:
            try:
                sock.close()
            except OSError:
                pass
    else:
        try:
            sock.close()
        except OSError:
            pass


def shutdown_only(sock) -> None:
    """Wake a connection's owning thread (its recv fails) without freeing
    the fd — the owner's close path (which holds the write lock) runs the
    actual close. For closing from OUTSIDE the serving thread."""
    import socket as _socket

    try:
        sock.shutdown(_socket.SHUT_RDWR)
    except (OSError, ValueError):
        pass


def send_frame(sock, lock: threading.Lock, body: list) -> None:
    # failpoint `rpc.wire.send`: error = connection reset before any byte
    # leaves (provably unsent); delay = latency spike under the write lock
    failpoints.fp("rpc.wire.send")
    data = codec.dumps(body)
    if len(data) > MAX_FRAME:
        raise ValueError(f"frame of {len(data)} bytes exceeds MAX_FRAME")
    frame = _LEN.pack(len(data)) + data
    with lock:
        # failpoint `rpc.wire.send.torn` (value = fraction in (0,1)):
        # ship a PARTIAL frame then die — the peer sees a reset mid-frame
        # and must treat the stream as unparseable from here on
        torn = failpoints.fp_value("rpc.wire.send.torn")
        if torn is not None:
            cut = max(1, min(len(frame) - 1, int(len(frame) * float(torn))))
            try:
                sock.sendall(frame[:cut])
            finally:
                shutdown_only(sock)
            raise OSError("injected reset mid-frame")
        sock.sendall(frame)


def recv_frame(sock) -> list:
    # failpoint `rpc.wire.recv`: error = reset while waiting for a frame;
    # delay = a stalled peer
    failpoints.fp("rpc.wire.recv")
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ConnectionClosed(f"oversized frame ({length} bytes)")
    return codec.loads(_recv_exact(sock, length))


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionClosed("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


# --------------------------------------------------------------------- TLS


class _PemFiles:
    """ssl.SSLContext only loads key material from files; stage the PEMs in
    a private temp dir for the duration of context construction."""

    def __init__(self, *pems: bytes):
        self.dir = tempfile.mkdtemp(prefix="skt-tls-")
        os.chmod(self.dir, 0o700)
        self.paths = []
        for i, pem in enumerate(pems):
            p = os.path.join(self.dir, f"{i}.pem")
            fd = os.open(p, os.O_WRONLY | os.O_CREAT, 0o600)
            with os.fdopen(fd, "wb") as f:
                f.write(pem)
            self.paths.append(p)

    def __enter__(self):
        return self.paths

    def __exit__(self, *exc):
        for p in self.paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        try:
            os.rmdir(self.dir)
        except OSError:
            pass


def server_ssl_context(security, require_client_cert: bool = False) -> ssl.SSLContext:
    """mTLS server context from a SecurityConfig. Client certs are
    *requested*; when `require_client_cert` is False an anonymous client is
    admitted but authenticates as no one (Caller None) — this is how a
    joining node with only a join token reaches the CA service, mirroring
    the reference's unauthenticated NodeCA.IssueNodeCertificate."""
    key_pem, cert_pem = security.key_and_cert()
    # current anchors + the bounded post-rotation grace tail
    # (ca/config.py trust_anchors_pem): a peer whose cert install raced
    # a rotation finish must still be able to authenticate its renewal
    ca_pem = security.trust_anchors_pem()
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    with _PemFiles(cert_pem, key_pem, ca_pem) as (cert_f, key_f, ca_f):
        ctx.load_cert_chain(cert_f, key_f)
        ctx.load_verify_locations(ca_f)
    ctx.verify_mode = (ssl.CERT_REQUIRED if require_client_cert
                       else ssl.CERT_OPTIONAL)
    return ctx


def client_ssl_context(security=None, root_cert_pem: bytes | None = None) -> ssl.SSLContext:
    """mTLS client context. With a SecurityConfig the client presents its
    node certificate; with only `root_cert_pem` (join-token bootstrap,
    before any cert exists) the client authenticates the server but not
    itself."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    # cluster certs carry identity in the subject (CN=node id), not
    # hostnames; the chain check against the cluster root is the trust
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_REQUIRED
    if security is not None:
        key_pem, cert_pem = security.key_and_cert()
        with _PemFiles(cert_pem, key_pem,
                       security.trust_anchors_pem()) as (
                cert_f, key_f, ca_f):
            ctx.load_cert_chain(cert_f, key_f)
            ctx.load_verify_locations(ca_f)
    elif root_cert_pem is not None:
        with _PemFiles(root_cert_pem) as (ca_f,):
            ctx.load_verify_locations(ca_f)
    else:
        raise ValueError("need a SecurityConfig or a root cert to trust")
    return ctx


def caller_from_socket(ssl_sock) -> Caller | None:
    """Authenticated identity from the peer certificate (subject CN/OU/O),
    None for anonymous (no client cert presented)."""
    # lazy: only the TLS path needs certificate parsing; unix-socket RPC
    # must work without the optional `cryptography` wheel
    from ..ca.certificates import CertificateError, ou_to_role

    cert = ssl_sock.getpeercert()
    if not cert:
        return None
    subject = {}
    for rdn in cert.get("subject", ()):
        for key, value in rdn:
            subject[key] = value
    cn = subject.get("commonName", "")
    ou = subject.get("organizationalUnitName", "")
    org = subject.get("organizationName", "")
    if not cn or not ou:
        return None
    try:
        role = ou_to_role(ou)
    except CertificateError:
        return None
    return Caller(node_id=cn, role=role, org=org)


def connect_tls(addr: str, ctx: ssl.SSLContext, timeout: float = 10.0):
    host, port = parse_addr(addr)
    raw = socket.create_connection((host, port), timeout=timeout)
    raw.settimeout(None)
    return ctx.wrap_socket(raw, server_hostname=host)


def parse_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    if not host:
        raise ValueError(f"address {addr!r} must be host:port")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    return host, int(port)
