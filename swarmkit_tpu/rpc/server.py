"""RPC server: serves registered service methods over mTLS.

One listener carries every plane (raft, dispatcher, CA, control, logs,
health), mirroring manager.go:441-641 where all gRPC services share the
remote listener. Each method declares the roles allowed to call it; the
authenticated Caller is derived from the peer certificate and passed as the
first handler argument (the reference's authenticatedWrapper +
ca/auth.go AuthorizeOrgAndRole, generated per service by
protobuf/plugin/authenticatedwrapper).

Streaming: a handler returning a generator or a watch Channel has its items
pumped to the client as STREAM_ITEM frames until exhaustion, client CANCEL,
or connection loss.
"""
from __future__ import annotations

import logging
import socket
import ssl
import threading
import time
from dataclasses import dataclass
from typing import Callable

from ..analysis.lockgraph import make_lock, make_rlock
from ..ca.auth import Caller, PermissionDenied
from ..store.watch import Channel, ChannelClosed
from ..utils import failpoints, trace
from .wire import (
    CANCEL,
    ERR,
    REQ,
    RESP,
    STREAM_END,
    STREAM_ITEM,
    ConnectionClosed,
    caller_from_socket,
    recv_frame,
    safe_close,
    send_frame,
    server_ssl_context,
    shutdown_only,
)

log = logging.getLogger("swarmkit_tpu.rpc.server")

ANON = "anon"  # marker role: method callable without a client certificate

# per-RPC server metrics, the grpc_prometheus.Register surface the
# reference installs on both gRPC servers (manager/manager.go:551,562):
# every method gets started/handled counters (handled carries the
# result code) and a handling-latency histogram, all surfaced through
# /metrics (node/debugserver.py -> utils.metrics exposition)
from ..utils.metrics import counter_family, histogram_family  # noqa: E402

RPC_STARTED = counter_family(
    "swarm_rpc_server_started_total",
    "RPCs begun on the server, per method", ("method",))
RPC_HANDLED = counter_family(
    "swarm_rpc_server_handled_total",
    "RPCs completed on the server, per method and code",
    ("method", "code"))
RPC_LATENCY = histogram_family(
    "swarm_rpc_server_handling_seconds",
    "Server-side RPC handling latency, per method", ("method",))


@dataclass
class MethodDef:
    func: Callable
    roles: list  # NodeRole ints, or [ANON] for tokenless bootstrap methods
    streaming: bool = False


class ServiceRegistry:
    """Method table shared by the server and the leader proxy."""

    def __init__(self):
        self.methods: dict[str, MethodDef] = {}

    def add(self, name: str, func: Callable, roles: list,
            streaming: bool = False):
        self.methods[name] = MethodDef(func, roles, streaming)

    def lookup(self, name: str) -> MethodDef | None:
        return self.methods.get(name)


class RPCServer:
    def __init__(self, listen_addr: str, security, registry: ServiceRegistry,
                 org: str | None = None, unix_path: str | None = None):
        """TCP+mTLS by default; with `unix_path` a LOCAL control listener
        (the reference's xnet unix socket): no TLS — filesystem permissions
        are the trust boundary, and every caller authenticates as this
        node's own identity, exactly like swarmd's control socket serving
        the local engine."""
        self.security = security
        self.registry = registry
        self.org = org if org is not None else security.identity.org
        self.unix_path = unix_path
        if unix_path is None:
            host, _, port = listen_addr.rpartition(":")
            self._bind = (host or "127.0.0.1", int(port))
        else:
            self._bind = None
        self._sock: socket.socket | None = None
        self._ctx_lock = make_lock('rpc.server.ctx_lock')
        self._ctx = server_ssl_context(security) if unix_path is None else None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set = set()
        self._conns_lock = make_lock('rpc.server.conns_lock')
        # in-flight request handlers: stop() drains these behind a
        # deadline BEFORE shutting connections, so a reply that is
        # already being computed still reaches the caller instead of
        # dying on a reset mid-frame (the race the reset-mid-frame
        # failpoint exposes)
        self._inflight = 0
        self._inflight_cond = threading.Condition(
            make_rlock("rpc.server.inflight_cond"))
        # set by stop() once the drain window has passed: a serve loop
        # that exits because _stop was set (it re-checks between frames,
        # so it can exit BEFORE blocking in recv) must wait for this
        # before closing its connection, or it yanks the fd out from
        # under a handler the drain is still waiting for — the reply
        # dies on EBADF and the caller sees a reset the drain contract
        # promises it will not see (found by the stop-drain test's rare
        # between-frames interleaving)
        self._drained = threading.Event()
        self.addr: str | None = None  # actual host:port after bind
        # renewed certs / rotated roots apply to new connections
        if unix_path is None:
            security.watch(self._reload_tls)

    def _reload_tls(self, _security):
        if self.unix_path is not None:
            return
        try:
            ctx = server_ssl_context(self.security)
        except Exception:
            log.exception("rpc-server: TLS reload failed")
            return
        with self._ctx_lock:
            self._ctx = ctx

    # -- lifecycle ---------------------------------------------------------
    def bind(self) -> str:
        """Bind the listening socket without serving yet; returns the actual
        host:port. Lets the assembly learn its advertise address (port 0 →
        kernel-assigned) before the raft node / registry that reference it
        are constructed; accepted connections queue in the backlog until
        start()."""
        if self._sock is not None:
            return self.addr
        if self.unix_path is not None:
            import os

            try:
                os.unlink(self.unix_path)
            except FileNotFoundError:
                pass
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.bind(self.unix_path)
            os.chmod(self.unix_path, 0o600)
            sock.listen(128)
            self._sock = sock
            self.addr = f"unix://{self.unix_path}"
            return self.addr
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(self._bind)
        sock.listen(128)
        self._sock = sock
        host, port = sock.getsockname()[:2]
        self.addr = f"{host}:{port}"
        return self.addr

    def start(self):
        self.bind()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"rpc-accept-{self.addr}")
        t.start()
        self._threads.append(t)

    def stop(self, drain_timeout: float = 2.0):
        """Shut down: listener first (no new connections), then DRAIN
        in-flight handlers behind `drain_timeout` so computed replies
        reach their callers, then shut the connections. Streaming pumps
        observe _stop and wind down on their own within the drain."""
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self.unix_path is not None:
            import os

            try:
                os.unlink(self.unix_path)
            except OSError:
                pass
        deadline = time.monotonic() + max(0.0, drain_timeout)
        with self._inflight_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    log.warning("rpc-server %s: %d handler(s) still "
                                "in flight past the drain deadline",
                                self.addr, self._inflight)
                    break
                self._inflight_cond.wait(remaining)
        # drain window over (clean or deadline): serve loops parked on
        # this event may now close their connections
        self._drained.set()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            # wake each conn's serving thread; ITS close path (under the
            # per-conn write lock) frees the fd — closing from here races
            # in-flight reply sendalls onto a recycled fd (wire.safe_close)
            shutdown_only(c)
        for t in self._threads:
            t.join(timeout=2)

    # -- accept/serve ------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                raw, _peer = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(raw,),
                                 daemon=True, name="rpc-conn")
            t.start()

    def _serve_conn(self, raw: socket.socket):
        if self.unix_path is not None:
            # local control socket: the caller IS this node (xnet semantics)
            conn = raw
            ident = self.security.identity
            caller = Caller(ident.node_id, ident.role, ident.org)
        else:
            try:
                with self._ctx_lock:
                    ctx = self._ctx
                conn = ctx.wrap_socket(raw, server_side=True)
            except (ssl.SSLError, OSError) as exc:
                log.debug("rpc-server: TLS handshake failed: %s", exc)
                try:
                    raw.close()
                except OSError:
                    pass
                return
            caller = caller_from_socket(conn)
        if caller is not None and self.org and caller.org != self.org:
            conn.close()
            return
        with self._conns_lock:
            self._conns.add(conn)
        wlock = make_lock('rpc.server.wlock')
        cancels: dict[int, threading.Event] = {}
        try:
            while not self._stop.is_set():
                frame = recv_frame(conn)
                ftype, stream_id, head, payload = frame
                if ftype == REQ:
                    # counted BEFORE the thread starts so stop()'s drain
                    # cannot observe zero while a handler is being born
                    with self._inflight_cond:
                        self._inflight += 1
                    t = threading.Thread(
                        target=self._handle_tracked,
                        args=(conn, wlock, caller, stream_id, head, payload,
                              cancels),
                        daemon=True, name=f"rpc-call-{head}")
                    t.start()
                elif ftype == CANCEL:
                    ev = cancels.get(stream_id)
                    if ev is not None:
                        ev.set()
        except (ConnectionClosed, OSError, ssl.SSLError):
            pass
        finally:
            for ev in cancels.values():
                ev.set()
            with self._conns_lock:
                self._conns.discard(conn)
            if self._stop.is_set():
                # stopping: honor the drain contract. The loop above
                # re-checks _stop between frames, so it can get here
                # BEFORE stop()'s drain has let in-flight handlers send
                # their replies — closing now would reset them. Bounded:
                # stop() always sets _drained after its drain window.
                self._drained.wait(timeout=30)
            # reply threads may still be inside send_frame on this conn:
            # shutdown, then close under their write lock (wire.safe_close)
            safe_close(conn, wlock)

    # -- dispatch ----------------------------------------------------------
    def _handle_tracked(self, *args):
        try:
            self._handle_request(*args)
        finally:
            with self._inflight_cond:
                self._inflight -= 1
                self._inflight_cond.notify_all()

    def _handle_request(self, conn, wlock, caller: Caller | None,
                        stream_id: int, method: str, payload, cancels):
        import time as _time

        t_start = _time.perf_counter()
        # The method string is client-controlled until the registry lookup
        # succeeds; recording it verbatim would let any connected peer (the
        # CA listener accepts peers without a client cert) grow the metric
        # series without bound. Registry methods are a finite set — unknown
        # names collapse into one "<unknown>" series.
        mdef = self.registry.lookup(method)
        mlabel = method if mdef is not None else "<unknown>"
        RPC_STARTED.inc((mlabel,))

        def finish(code: str):
            RPC_HANDLED.inc((mlabel, code))
            RPC_LATENCY.observe((mlabel,), _time.perf_counter() - t_start)

        def reply_err(exc: Exception):
            from .wire import RPCError

            if isinstance(exc, RPCError):
                # forwarded-hop error: preserve the ORIGINAL name so the
                # caller's retry/translation logic sees e.g. NotLeaderError,
                # not a double-wrapped "RPCError"
                name, msg = exc.name, exc.message
            else:
                name, msg = type(exc).__name__, str(exc)
            finish(name)
            try:
                send_frame(conn, wlock, [ERR, stream_id, name, msg])
            except (OSError, ValueError):
                pass

        if mdef is None:
            reply_err(PermissionDenied(f"unknown method {method!r}"))
            return
        if ANON not in mdef.roles:
            if caller is None:
                reply_err(PermissionDenied(
                    f"{method} requires an authenticated peer"))
                return
            if caller.role not in mdef.roles:
                reply_err(PermissionDenied(
                    f"{method}: role not authorized"))
                return
        args, kwargs = payload if payload else ((), {})
        # reserved trace-context key: stripped UNCONDITIONALLY (a traced
        # client may call an untraced server — the handler must never see
        # it); parents the server span below when this end is armed too
        tctx = kwargs.pop("_trace_ctx", None)
        forwarded = kwargs.pop("_forwarded_caller", None)
        if forwarded is not None:
            # Only a manager may assert a forwarded identity (the leader
            # proxy path — ca/auth.go AuthorizeForwardedRoleAndOrg); the
            # effective caller becomes the original, with the proxying
            # manager recorded.
            from ..api.types import NodeRole

            if caller is None or caller.role != NodeRole.MANAGER:
                reply_err(PermissionDenied(
                    "forwarded identity requires a manager peer"))
                return
            forwarded.forwarded_by = caller
            caller = forwarded
            if ANON not in mdef.roles and caller.role not in mdef.roles:
                reply_err(PermissionDenied(f"{method}: role not authorized"))
                return
        try:
            # failpoint `rpc.server.handle`: delay = a slow handler (the
            # stop-drain path); error = a handler crash, surfaced to the
            # caller as a wire error like any handler exception
            failpoints.fp("rpc.server.handle")
            if trace.enabled():
                with trace.span("rpc.server", parent=tctx, method=mlabel):
                    result = mdef.func(caller, *args, **kwargs)
            else:
                result = mdef.func(caller, *args, **kwargs)
        except Exception as exc:  # handler error -> wire error
            reply_err(exc)
            return
        if not mdef.streaming:
            try:
                send_frame(conn, wlock, [RESP, stream_id, "", result])
                finish("OK")
            except ValueError as exc:  # encode failure
                reply_err(exc)
            except OSError:
                finish("OK")           # handler succeeded; conn died
            return
        # streaming: pump a Channel or generator until done/cancel/dead conn
        cancel = threading.Event()
        cancels[stream_id] = cancel
        stream_code = "OK"
        try:
            if isinstance(result, Channel):
                while not cancel.is_set() and not self._stop.is_set():
                    try:
                        item = result.get(timeout=0.2)
                    except TimeoutError:
                        continue
                    except ChannelClosed:
                        break
                    send_frame(conn, wlock,
                               [STREAM_ITEM, stream_id, "", item])
            else:
                for item in result:
                    if cancel.is_set() or self._stop.is_set():
                        break
                    send_frame(conn, wlock,
                               [STREAM_ITEM, stream_id, "", item])
            send_frame(conn, wlock, [STREAM_END, stream_id, "", None])
        except (OSError, ValueError, ConnectionClosed):
            pass
        except Exception as exc:
            stream_code = None          # reply_err records the error code
            reply_err(exc)
        finally:
            if stream_code is not None:
                finish(stream_code)
            cancels.pop(stream_id, None)
            if isinstance(result, Channel):
                result.close()
            close = getattr(result, "close", None)
            if close is not None and not isinstance(result, Channel):
                try:
                    close()
                except Exception:
                    pass
