"""RPC client: one multiplexed mTLS connection, unary calls + streams.

The demux thread routes frames by stream id: unary calls block on an event;
streams feed a watch Channel the caller consumes exactly like an in-process
subscription (the agent's assignment stream and the raft peer stream both
ride this). Connection loss fails every pending call and closes every
stream — reconnect policy belongs to the caller (agent session backoff,
raft peer retry), as in the reference (agent/session.go:90-118,
manager/state/raft/transport/peer.go).
"""
from __future__ import annotations

import logging
import ssl
import threading

from ..store.watch import Channel
from .wire import (
    CANCEL,
    ERR,
    REQ,
    RESP,
    STREAM_END,
    STREAM_ITEM,
    ConnectionClosed,
    RPCError,
    client_ssl_context,
    connect_tls,
    recv_frame,
    safe_close,
    send_frame,
    shutdown_only,
)

log = logging.getLogger("swarmkit_tpu.rpc.client")

DEFAULT_CALL_TIMEOUT = 30.0

# Exceptions a server may raise that the client re-raises as the local type
# (everything else surfaces as RPCError). Data-only: name -> constructor
# taking one message argument.
_KNOWN_ERRORS: dict[str, type] = {}


def _register_errors():
    if _KNOWN_ERRORS:
        return
    from ..ca.auth import PermissionDenied
    from ..ca.config import InvalidToken
    from ..ca.certificates import CertificateError
    from ..controlapi import errors as control_errors
    from ..dispatcher.dispatcher import DispatcherError, SessionInvalid
    from ..csi.plugin import CSIPluginError
    from ..raft.messages import MemberRemovedError
    from ..raft.proposer import ProposeError
    from ..store.memory import ExistError, NotExistError, SequenceConflict

    for name in dir(control_errors):
        obj = getattr(control_errors, name)
        if isinstance(obj, type) and issubclass(obj, Exception):
            _KNOWN_ERRORS[obj.__name__] = obj
    # registered after control errors: ca.auth.PermissionDenied wins the
    # name collision (the authz edge is what the server raises)
    for cls in (PermissionDenied, InvalidToken, CertificateError,
                DispatcherError, SessionInvalid, ProposeError,
                MemberRemovedError, CSIPluginError,
                ExistError, NotExistError, SequenceConflict,
                KeyError, ValueError, TimeoutError):
        _KNOWN_ERRORS[cls.__name__] = cls


def _make_error(name: str, message: str) -> Exception:
    _register_errors()
    cls = _KNOWN_ERRORS.get(name)
    if cls is None:
        return RPCError(name, message)
    try:
        return cls(message)
    except Exception:
        return RPCError(name, message)


class _PendingCall:
    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None


class RPCClient:
    """One connection to one server; thread-safe for concurrent calls."""

    def __init__(self, addr: str, security=None,
                 root_cert_pem: bytes | None = None,
                 connect_timeout: float = 10.0):
        self.addr = addr
        if addr.startswith("unix://"):
            # local control socket: plain stream, filesystem perms are the
            # trust boundary (xnet) — no TLS, no identity needed
            import socket as _socket

            sock = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            sock.settimeout(connect_timeout)
            sock.connect(addr[len("unix://"):])
            sock.settimeout(None)
            self._sock = sock
        else:
            ctx = client_ssl_context(security, root_cert_pem)
            self._sock = connect_tls(addr, ctx, timeout=connect_timeout)
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._next_id = 1
        self._calls: dict[int, _PendingCall] = {}
        self._streams: dict[int, Channel] = {}
        self._closed = threading.Event()
        self._demux = threading.Thread(target=self._demux_loop, daemon=True,
                                       name=f"rpc-demux-{addr}")
        self._demux.start()

    # -- public ------------------------------------------------------------
    def call(self, method: str, *args,
             timeout: float = DEFAULT_CALL_TIMEOUT, **kwargs):
        if self._closed.is_set():
            # the request was never sent: callers may retry it on a fresh
            # connection even for writes (nothing reached the server) —
            # the post-rotation window where a server reloading its TLS
            # trust kills a just-opened connection surfaces exactly here
            exc = ConnectionClosed(
                f"connection to {self.addr} is closed")
            exc.unsent = True
            raise exc
        pending = _PendingCall()
        stream_id = self._register(calls=pending)
        try:
            send_frame(self._sock, self._wlock,
                       [REQ, stream_id, method, ((args), kwargs)])
        except OSError as exc:
            self._unregister(stream_id)
            self._fail_all(ConnectionClosed(str(exc)))
            # a partial frame is unparseable — the server cannot have
            # executed this request; safe to retry on a new connection
            closed = ConnectionClosed(str(exc))
            closed.unsent = True
            raise closed from exc
        if not pending.event.wait(timeout):
            self._unregister(stream_id)
            raise TimeoutError(f"{method} timed out after {timeout}s")
        if pending.error is not None:
            raise pending.error
        return pending.result

    def stream(self, method: str, *args, limit: int | None = None,
               **kwargs) -> Channel:
        """Open a server stream; returns a Channel of items. The channel
        closes on stream end, server error, or connection loss."""
        if self._closed.is_set():
            raise ConnectionClosed(f"connection to {self.addr} is closed")
        ch = Channel(matcher=None, limit=limit)
        stream_id = self._register(stream=ch)
        try:
            send_frame(self._sock, self._wlock,
                       [REQ, stream_id, method, ((args), kwargs)])
        except OSError as exc:
            self._unregister(stream_id)
            self._fail_all(ConnectionClosed(str(exc)))
            raise ConnectionClosed(str(exc)) from exc
        return ch

    def cancel_stream(self, ch: Channel):
        with self._lock:
            sid = next((k for k, v in self._streams.items() if v is ch), None)
        if sid is not None:
            try:
                send_frame(self._sock, self._wlock, [CANCEL, sid, "", None])
            except OSError:
                pass
            self._unregister(sid)
        ch.close()

    @property
    def alive(self) -> bool:
        return not self._closed.is_set()

    def close(self):
        self._closed.set()
        # wake the demux thread only; the fd is closed by ITS finally
        # (safe_close under the write lock) once it is out of recv. An
        # SSL recv can itself WRITE — TLS 1.3 encrypts alerts and
        # KeyUpdate replies as application-data records — so freeing the
        # fd from any other thread races that hidden write onto a
        # recycled fd (observed: close_notify-sized records spliced into
        # freshly-written state files)
        shutdown_only(self._sock)
        self._fail_all(ConnectionClosed("client closed"))

    # -- internals ---------------------------------------------------------
    def _register(self, calls: _PendingCall | None = None,
                  stream: Channel | None = None) -> int:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            if calls is not None:
                self._calls[sid] = calls
            if stream is not None:
                self._streams[sid] = stream
            return sid

    def _unregister(self, sid: int):
        with self._lock:
            self._calls.pop(sid, None)
            self._streams.pop(sid, None)

    def _fail_all(self, exc: Exception):
        with self._lock:
            calls = list(self._calls.values())
            streams = list(self._streams.values())
            self._calls.clear()
            self._streams.clear()
        for p in calls:
            p.error = exc
            p.event.set()
        for ch in streams:
            ch.close()

    def _demux_loop(self):
        try:
            while not self._closed.is_set():
                ftype, sid, head, payload = recv_frame(self._sock)
                if ftype == RESP:
                    with self._lock:
                        pending = self._calls.pop(sid, None)
                    if pending is not None:
                        pending.result = payload
                        pending.event.set()
                elif ftype == ERR:
                    exc = _make_error(head, payload)
                    with self._lock:
                        pending = self._calls.pop(sid, None)
                        stream = self._streams.pop(sid, None)
                    if pending is not None:
                        pending.error = exc
                        pending.event.set()
                    if stream is not None:
                        stream.close(error=exc)
                elif ftype == STREAM_ITEM:
                    with self._lock:
                        stream = self._streams.get(sid)
                    if stream is not None:
                        stream._offer(payload)
                elif ftype == STREAM_END:
                    with self._lock:
                        stream = self._streams.pop(sid, None)
                    if stream is not None:
                        stream.close()
        except (ConnectionClosed, OSError, ssl.SSLError) as exc:
            self._closed.set()
            self._fail_all(ConnectionClosed(str(exc)))
        finally:
            self._closed.set()
            safe_close(self._sock, self._wlock)
