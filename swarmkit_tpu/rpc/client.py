"""RPC client: one multiplexed mTLS connection, unary calls + streams.

The demux thread routes frames by stream id: unary calls block on an event;
streams feed a watch Channel the caller consumes exactly like an in-process
subscription (the agent's assignment stream and the raft peer stream both
ride this). Connection loss fails every pending call and closes every
stream — reconnect policy belongs to the caller (agent session backoff,
raft peer retry), as in the reference (agent/session.go:90-118,
manager/state/raft/transport/peer.go).
"""
from __future__ import annotations

import logging
import ssl
import threading

from ..analysis.lockgraph import make_lock
from ..store.watch import Channel
from ..utils import backoff as _backoff
from ..utils import trace
from .wire import (
    CANCEL,
    ERR,
    REQ,
    RESP,
    STREAM_END,
    STREAM_ITEM,
    ConnectionClosed,
    RPCError,
    client_ssl_context,
    connect_tls,
    recv_frame,
    safe_close,
    send_frame,
    shutdown_only,
)

log = logging.getLogger("swarmkit_tpu.rpc.client")

DEFAULT_CALL_TIMEOUT = 30.0

# Exceptions a server may raise that the client re-raises as the local type
# (everything else surfaces as RPCError). Data-only: name -> constructor
# taking one message argument.
_KNOWN_ERRORS: dict[str, type] = {}


def _register_errors():
    if _KNOWN_ERRORS:
        return
    from ..ca.auth import PermissionDenied
    from ..controlapi import errors as control_errors
    from ..dispatcher.dispatcher import DispatcherError, SessionInvalid
    from ..csi.plugin import CSIPluginError
    from ..raft.messages import MemberRemovedError
    from ..raft.proposer import ProposeError
    from ..store.memory import ExistError, NotExistError, SequenceConflict

    for name in dir(control_errors):
        obj = getattr(control_errors, name)
        if isinstance(obj, type) and issubclass(obj, Exception):
            _KNOWN_ERRORS[obj.__name__] = obj
    # registered after control errors: ca.auth.PermissionDenied wins the
    # name collision (the authz edge is what the server raises)
    for cls in (PermissionDenied, DispatcherError, SessionInvalid,
                ProposeError, MemberRemovedError, CSIPluginError,
                ExistError, NotExistError, SequenceConflict,
                KeyError, ValueError, TimeoutError):
        _KNOWN_ERRORS[cls.__name__] = cls
    try:
        # certificate-flow errors need the optional `cryptography` wheel;
        # without it they just surface as generic RPCError by name
        from ..ca.certificates import CertificateError
        from ..ca.config import InvalidToken

        _KNOWN_ERRORS[CertificateError.__name__] = CertificateError
        _KNOWN_ERRORS[InvalidToken.__name__] = InvalidToken
    except ImportError:
        pass


def _make_error(name: str, message: str) -> Exception:
    _register_errors()
    cls = _KNOWN_ERRORS.get(name)
    if cls is None:
        return RPCError(name, message)
    try:
        return cls(message)
    except Exception:
        return RPCError(name, message)


class _PendingCall:
    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None


class RPCClient:
    """One connection to one server; thread-safe for concurrent calls."""

    def __init__(self, addr: str, security=None,
                 root_cert_pem: bytes | None = None,
                 connect_timeout: float = 10.0):
        self.addr = addr
        self._security = security
        self._root_cert_pem = root_cert_pem
        self._connect_timeout = connect_timeout
        self._wlock = make_lock('rpc.client.wlock')
        self._lock = make_lock('rpc.client.lock')
        self._dial_lock = make_lock('rpc.client.dial_lock')
        self._next_id = 1
        self._calls: dict[int, _PendingCall] = {}
        self._streams: dict[int, Channel] = {}
        self._user_closed = False
        self._sock = self._connect()
        self._closed = threading.Event()
        self._demux = threading.Thread(target=self._demux_loop,
                                       args=(self._sock, self._closed),
                                       daemon=True,
                                       name=f"rpc-demux-{addr}")
        self._demux.start()

    def _connect(self):
        addr = self.addr
        if addr.startswith("unix://"):
            # local control socket: plain stream, filesystem perms are the
            # trust boundary (xnet) — no TLS, no identity needed
            import socket as _socket

            sock = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            sock.settimeout(self._connect_timeout)
            sock.connect(addr[len("unix://"):])
            sock.settimeout(None)
            return sock
        ctx = client_ssl_context(self._security, self._root_cert_pem)
        return connect_tls(addr, ctx, timeout=self._connect_timeout)

    def _redial(self):
        """Replace a dead connection in place (retry_policy path only).
        The old demux thread closed its own socket and set its own
        closed-event; pending work on the old connection was already
        failed, so a fresh socket + demux generation is safe to swap in."""
        with self._dial_lock:
            if not self._closed.is_set():
                return
            if self._user_closed:
                raise ConnectionClosed(
                    f"connection to {self.addr} is closed")
            # fail anything still pending on the dying generation BEFORE
            # the swap: the old demux's generation-guarded _fail_all may
            # lose the race and skip, which would strand those calls for
            # their full timeout
            self._fail_all(ConnectionClosed(
                f"connection to {self.addr} lost (redialing)"))
            sock = self._connect()
            closed = threading.Event()
            self._sock = sock
            self._closed = closed
            self._demux = threading.Thread(
                target=self._demux_loop, args=(sock, closed), daemon=True,
                name=f"rpc-demux-{self.addr}")
            self._demux.start()

    # -- public ------------------------------------------------------------
    @staticmethod
    def _retry_safe(exc: Exception, idempotent: bool) -> bool:
        """True when retrying `exc` cannot double-execute the request:
        either the request provably never reached the server (unsent
        ConnectionClosed, a failed dial), or the caller declared the
        method idempotent (then maybe-executed transients retry too)."""
        if getattr(exc, "unsent", False):
            return True
        if isinstance(exc, OSError) and not isinstance(
                exc, (ConnectionClosed, TimeoutError)):
            # dial failure from _redial: nothing was ever sent. Builtin
            # TimeoutError IS an OSError subclass and means the request
            # was sent and may have executed — excluded here, it only
            # retries under the idempotent opt-in below
            return True
        if idempotent:
            return isinstance(exc, (ConnectionClosed, TimeoutError, OSError))
        return False

    def call(self, method: str, *args,
             timeout: float = DEFAULT_CALL_TIMEOUT,
             retry_policy: "_backoff.Backoff | None" = None,
             idempotent: bool = False,
             retry_clock=None, retry_rng=None, **kwargs):
        """Unary call. With `retry_policy` (utils/backoff.Backoff) the
        client retries — redialing a dead connection — but ONLY the
        provably-unsent failures unless `idempotent=True` opts
        maybe-executed transients (timeouts, mid-call connection loss)
        in as well. Sleeps ride `retry_clock` (FakeClock-able) and the
        jitter `retry_rng` for deterministic tests."""
        if retry_policy is None:
            return self._call_once(method, args, kwargs, timeout)
        attempt = 0
        while True:
            try:
                if self._closed.is_set():
                    self._redial()
                return self._call_once(method, args, kwargs, timeout)
            except Exception as exc:
                if attempt + 1 >= retry_policy.max_attempts \
                        or not self._retry_safe(exc, idempotent):
                    raise
                log.debug("rpc-client %s: retrying %s after %s",
                          self.addr, method, exc)
                _backoff.sleep(retry_clock or _backoff.REAL_CLOCK,
                               retry_policy.delay(attempt, retry_rng))
                attempt += 1

    def _call_once(self, method: str, args, kwargs, timeout: float):
        # trace plane: a client span per unary call; its ctx rides the
        # frame payload as the reserved `_trace_ctx` kwarg (the server
        # strips it unconditionally and parents its handler span to it).
        # Disarmed: one truthiness test, the kwargs dict untouched.
        sp = trace.start("rpc.client", method=method)
        if sp is None:
            return self._call_once_inner(method, args, kwargs, timeout)
        kwargs = dict(kwargs)          # never mutate the caller's dict
        kwargs["_trace_ctx"] = sp.ctx()
        try:
            result = self._call_once_inner(method, args, kwargs, timeout)
        except Exception as exc:
            sp.end(error=type(exc).__name__)
            raise
        sp.end(ok=True)
        return result

    def _call_once_inner(self, method: str, args, kwargs, timeout: float):
        # generation snapshot: a concurrent _redial may swap sock/closed
        # mid-call; failures observed on THIS generation must not kill
        # calls pending on a newer one
        closed, sock = self._closed, self._sock
        if closed.is_set():
            # the request was never sent: callers may retry it on a fresh
            # connection even for writes (nothing reached the server) —
            # the post-rotation window where a server reloading its TLS
            # trust kills a just-opened connection surfaces exactly here
            exc = ConnectionClosed(
                f"connection to {self.addr} is closed")
            exc.unsent = True
            raise exc
        pending = _PendingCall()
        stream_id = self._register(calls=pending)
        try:
            send_frame(sock, self._wlock,
                       [REQ, stream_id, method, ((args), kwargs)])
        except OSError as exc:
            self._unregister(stream_id)
            if self._closed is closed:
                self._fail_all(ConnectionClosed(str(exc)))
            # a partial frame is unparseable — the server cannot have
            # executed this request; safe to retry on a new connection
            unsent = ConnectionClosed(str(exc))
            unsent.unsent = True
            raise unsent from exc
        if not pending.event.wait(timeout):
            self._unregister(stream_id)
            raise TimeoutError(f"{method} timed out after {timeout}s")
        if pending.error is not None:
            raise pending.error
        return pending.result

    def stream(self, method: str, *args, limit: int | None = None,
               **kwargs) -> Channel:
        """Open a server stream; returns a Channel of items. The channel
        closes on stream end, server error, or connection loss."""
        closed, sock = self._closed, self._sock
        if closed.is_set():
            raise ConnectionClosed(f"connection to {self.addr} is closed")
        ch = Channel(matcher=None, limit=limit)
        stream_id = self._register(stream=ch)
        try:
            send_frame(sock, self._wlock,
                       [REQ, stream_id, method, ((args), kwargs)])
        except OSError as exc:
            self._unregister(stream_id)
            if self._closed is closed:
                self._fail_all(ConnectionClosed(str(exc)))
            raise ConnectionClosed(str(exc)) from exc
        return ch

    def cancel_stream(self, ch: Channel):
        with self._lock:
            sid = next((k for k, v in self._streams.items() if v is ch), None)
        if sid is not None:
            try:
                send_frame(self._sock, self._wlock, [CANCEL, sid, "", None])
            except OSError:
                pass
            self._unregister(sid)
        ch.close()

    @property
    def alive(self) -> bool:
        return not self._closed.is_set()

    def close(self):
        self._user_closed = True   # a retry_policy call must not redial
        self._closed.set()
        # wake the demux thread only; the fd is closed by ITS finally
        # (safe_close under the write lock) once it is out of recv. An
        # SSL recv can itself WRITE — TLS 1.3 encrypts alerts and
        # KeyUpdate replies as application-data records — so freeing the
        # fd from any other thread races that hidden write onto a
        # recycled fd (observed: close_notify-sized records spliced into
        # freshly-written state files)
        shutdown_only(self._sock)
        self._fail_all(ConnectionClosed("client closed"))

    # -- internals ---------------------------------------------------------
    def _register(self, calls: _PendingCall | None = None,
                  stream: Channel | None = None) -> int:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            if calls is not None:
                self._calls[sid] = calls
            if stream is not None:
                self._streams[sid] = stream
            return sid

    def _unregister(self, sid: int):
        with self._lock:
            self._calls.pop(sid, None)
            self._streams.pop(sid, None)

    def _fail_all(self, exc: Exception):
        with self._lock:
            calls = list(self._calls.values())
            streams = list(self._streams.values())
            self._calls.clear()
            self._streams.clear()
        for p in calls:
            p.error = exc
            p.event.set()
        for ch in streams:
            ch.close()

    def _demux_loop(self, sock, closed):
        # sock/closed are THIS generation's: after a _redial swaps in a
        # fresh connection, the old demux's teardown must only touch its
        # own socket and must not fail calls pending on the new one
        try:
            while not closed.is_set():
                ftype, sid, head, payload = recv_frame(sock)
                if ftype == RESP:
                    with self._lock:
                        pending = self._calls.pop(sid, None)
                    if pending is not None:
                        pending.result = payload
                        pending.event.set()
                elif ftype == ERR:
                    exc = _make_error(head, payload)
                    with self._lock:
                        pending = self._calls.pop(sid, None)
                        stream = self._streams.pop(sid, None)
                    if pending is not None:
                        pending.error = exc
                        pending.event.set()
                    if stream is not None:
                        stream.close(error=exc)
                elif ftype == STREAM_ITEM:
                    with self._lock:
                        stream = self._streams.get(sid)
                    if stream is not None:
                        stream._offer(payload)
                elif ftype == STREAM_END:
                    with self._lock:
                        stream = self._streams.pop(sid, None)
                    if stream is not None:
                        stream.close()
        except (ConnectionClosed, OSError, ssl.SSLError) as exc:
            closed.set()
            if self._closed is closed:
                self._fail_all(ConnectionClosed(str(exc)))
        finally:
            closed.set()
            safe_close(sock, self._wlock)
