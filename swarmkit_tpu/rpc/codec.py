"""Wire codec: schema-registered msgpack encoding of the API dataclasses.

The reference serializes with protobuf — a closed, data-only schema. Our
equivalent is msgpack plus an explicit type registry: only classes that are
registered here (the API dataclasses, enums, raft messages, dispatcher
messages) can cross the wire, and they are reconstructed field-by-field
through their constructors, never by executing embedded callables. A payload
referencing an unregistered type fails with WireDecodeError.

This replaces the earlier restricted-pickle codec, whose allowlist was
bypassable (dotted-name traversal through allowlisted modules reaching
os.system, getattr gadget chains); pickle is not used anywhere in the
framework any more.
"""
from __future__ import annotations

import dataclasses
import enum
import threading
from ..analysis.lockgraph import make_lock

import msgpack

# Marker keys. "\x00" cannot appear in our field names, so plain dicts whose
# keys are ordinary strings can never collide with an encoded object.
_T = "\x00t"   # registered class: {_T: name, _F: {field: value}}
_F = "\x00f"
_E = "\x00e"   # enum: {_E: name, _V: raw value}
_V = "\x00v"
_TUP = "\x00u"   # tuple: {_TUP: [items]}
_SET = "\x00s"   # set
_FSET = "\x00z"  # frozenset
_DICT = "\x00d"  # dict with non-primitive keys: {_DICT: [[k, v], ...]}

_PRIM_KEY = (str, int, float, bool, bytes)


class WireEncodeError(Exception):
    pass


class WireDecodeError(Exception):
    pass


class _Registry:
    def __init__(self):
        self.by_name: dict[str, type] = {}
        self.by_type: dict[type, str] = {}
        self.fields: dict[str, tuple[str, ...]] = {}
        self._lock = make_lock('rpc.codec.lock')
        self._populated = False

    def add(self, cls: type, fields: tuple[str, ...] | None = None):
        name = cls.__name__
        existing = self.by_name.get(name)
        if existing is not None and existing is not cls:
            # disambiguate by module tail (e.g. two `Node` classes)
            name = cls.__module__.rsplit(".", 1)[-1] + ":" + cls.__name__
        if fields is None:
            if dataclasses.is_dataclass(cls):
                fields = tuple(f.name for f in dataclasses.fields(cls))
            elif issubclass(cls, enum.Enum):
                fields = ()
            else:
                raise WireEncodeError(
                    f"{cls} is neither a dataclass nor an Enum; pass fields=")
        self.by_name[name] = cls
        self.by_type[cls] = name
        self.fields[name] = fields

    def add_module(self, mod):
        for obj in vars(mod).values():
            if isinstance(obj, type) and obj.__module__ == mod.__name__:
                if dataclasses.is_dataclass(obj) or (
                        issubclass(obj, enum.Enum) and obj is not enum.Enum):
                    self.add(obj)

    def populate(self):
        """Import and register every module whose types may cross the wire
        (or land in the encrypted WAL / snapshot files)."""
        with self._lock:
            if self._populated:
                return
            from ..api import genericresource, objects, specs, types
            from ..raft import messages as raft_messages

            for mod in (types, specs, objects, genericresource, raft_messages):
                self.add_module(mod)

            from ..store.memory import StoreAction

            self.add(StoreAction, fields=("kind", "obj"))

            from ..raft.node import Peer

            self.add(Peer)

            # modules below import the store; registered lazily but before
            # any encode/decode happens, so ordering is safe
            from ..agent import csi as agent_csi
            from ..csi import plugin as csi_plugin
            from ..dispatcher import dispatcher as dispatcher_mod
            from ..logbroker import broker as broker_mod

            for cls in (agent_csi.VolumeAssignment,):
                self.add(cls)
            for cls in (csi_plugin.VolumePublishStatus, csi_plugin.VolumeInfo):
                self.add(cls)

            from ..csi import wire as csi_wire

            for cls in (csi_wire.PluginCapabilities, csi_wire.PluginInfo):
                self.add(cls)
            for cls in (dispatcher_mod.Assignment,
                        dispatcher_mod.AssignmentsMessage,
                        dispatcher_mod.SessionMessage):
                self.add(cls)
            for cls in (broker_mod.LogSelector, broker_mod.LogContext,
                        broker_mod.LogMessage, broker_mod.SubscriptionMessage,
                        broker_mod.SubscriptionComplete,
                        broker_mod.LogShedRecord):
                self.add(cls)

            try:
                from ..ca.auth import Caller
                from ..ca.certificates import CertIdentity
            except ImportError:
                # environment without the optional `cryptography` wheel:
                # the CA tier is unusable there anyway, and gating it here
                # keeps the rest of the wire (raft WAL records, dispatcher
                # messages, ...) working
                Caller = CertIdentity = None
            if Caller is not None:
                self.add(CertIdentity)
                self.add(Caller)

            # dataclasses that live inside store objects (and therefore in
            # raft entries / WAL records / snapshots)
            try:
                from ..manager.keymanager import EncryptionKey
            except ImportError:
                EncryptionKey = None   # crypto-less env (see CA gate above)
            from ..orchestrator.restart import (
                InstanceRestartInfo,
                RestartedInstance,
            )

            for cls in (EncryptionKey, InstanceRestartInfo,
                        RestartedInstance):
                if cls is not None:
                    self.add(cls)

            # control/watch request types that cross the client wire
            from ..controlapi.control import ListFilters
            from ..watchapi.watch import WatchSelector

            for cls in (ListFilters, WatchSelector):
                self.add(cls)
            self._populated = True


_registry = _Registry()
register = _registry.add
register_module = _registry.add_module


def _to_wire(obj):
    # exact type checks: IntEnum/StrEnum instances pass isinstance(int/str)
    # but must take the enum branch below or they decode as bare scalars
    t = type(obj)
    if obj is None or t in (bool, int, float, str, bytes):
        return obj
    if t is list:
        return [_to_wire(x) for x in obj]
    if t is dict:
        # A user-data key that looks like one of our markers ("\x00"-prefixed)
        # must not be emitted in the plain form, or decode would misread the
        # dict as an encoded object (type confusion); the pair-list form
        # round-trips such keys literally.
        if all(type(k) in _PRIM_KEY for k in obj) and not any(
                isinstance(k, str) and k.startswith("\x00") for k in obj):
            return {k: _to_wire(v) for k, v in obj.items()}
        return {_DICT: [[_to_wire(k), _to_wire(v)] for k, v in obj.items()]}
    if t is tuple:
        return {_TUP: [_to_wire(x) for x in obj]}
    if t is set:
        return {_SET: [_to_wire(x) for x in obj]}
    if t is frozenset:
        return {_FSET: [_to_wire(x) for x in obj]}
    if isinstance(obj, enum.Enum):
        name = _registry.by_type.get(t)
        if name is None:
            raise WireEncodeError(f"unregistered enum {t}")
        return {_E: name, _V: obj.value}
    name = _registry.by_type.get(t)
    if name is not None:
        fields = _registry.fields[name]
        return {_T: name,
                _F: {f: _to_wire(getattr(obj, f)) for f in fields}}
    raise WireEncodeError(f"cannot encode {t} on the wire (unregistered)")


def _from_wire(obj):
    if isinstance(obj, dict):
        if _T in obj:
            name = obj[_T]
            cls = _registry.by_name.get(name)
            if cls is None:
                raise WireDecodeError(f"wire payload references unknown type {name!r}")
            raw = obj.get(_F) or {}
            known = set(_registry.fields.get(name, ()))
            kwargs = {k: _from_wire(v) for k, v in raw.items() if k in known}
            try:
                return cls(**kwargs)
            except TypeError as exc:
                raise WireDecodeError(f"cannot construct {name}: {exc}") from exc
        if _E in obj:
            cls = _registry.by_name.get(obj[_E])
            if cls is None or not (isinstance(cls, type)
                                   and issubclass(cls, enum.Enum)):
                raise WireDecodeError(f"unknown enum {obj.get(_E)!r}")
            try:
                return cls(obj.get(_V))
            except ValueError as exc:
                raise WireDecodeError(str(exc)) from exc
        if _TUP in obj:
            return tuple(_from_wire(x) for x in obj[_TUP])
        if _SET in obj:
            return {_from_wire(x) for x in obj[_SET]}
        if _FSET in obj:
            return frozenset(_from_wire(x) for x in obj[_FSET])
        if _DICT in obj:
            return {_from_wire(k): _from_wire(v) for k, v in obj[_DICT]}
        return {k: _from_wire(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_wire(x) for x in obj]
    return obj


def dumps(obj) -> bytes:
    _registry.populate()
    return msgpack.packb(_to_wire(obj), use_bin_type=True)


def loads(data: bytes):
    _registry.populate()
    try:
        raw = msgpack.unpackb(data, raw=False, strict_map_key=False)
    except Exception as exc:
        raise WireDecodeError(f"malformed wire payload: {exc}") from exc
    return _from_wire(raw)
