"""Wire codec: restricted pickling of the API dataclasses.

The reference serializes with protobuf; our objects are plain dataclasses,
so the wire format is pickle restricted to an allowlist — only
`swarmkit_tpu.*` types, stdlib value types, and builtins can deserialize.
Combined with mutual TLS (only cluster members reach the port), this closes
the arbitrary-object-construction hole while keeping one schema source.
"""
from __future__ import annotations

import io
import pickle

_ALLOWED_PREFIXES = ("swarmkit_tpu.",)
_ALLOWED_MODULES = {
    "builtins": {
        "dict", "list", "set", "frozenset", "tuple", "bytes", "str", "int",
        "float", "bool", "complex", "bytearray", "NoneType", "getattr",
    },
    "collections": {"OrderedDict", "defaultdict", "deque", "Counter"},
    "datetime": {"datetime", "date", "time", "timedelta", "timezone"},
    "enum": {"EnumType", "EnumMeta"},
    "copyreg": {"_reconstructor"},
}


class WireDecodeError(Exception):
    pass


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if any(module.startswith(p) for p in _ALLOWED_PREFIXES):
            return super().find_class(module, name)
        allowed = _ALLOWED_MODULES.get(module)
        if allowed is not None and name in allowed:
            return super().find_class(module, name)
        raise WireDecodeError(f"wire payload references forbidden {module}.{name}")


def dumps(obj) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def loads(data: bytes):
    return _RestrictedUnpickler(io.BytesIO(data)).load()
