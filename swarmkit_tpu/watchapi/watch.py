"""Watch API: filtered store-event streaming to clients.

Behavioral re-derivation of manager/watchapi/watch.go + api/watch.proto:
clients subscribe with per-object-kind selectors (kind, id/id-prefix,
name/name-prefix, labels) and an action mask (create/update/delete) and
receive matching events, optionally including the previous object state on
updates, with resume-from-version replay via the store's WatchFrom plane.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..api.objects import (
    ALL_TABLES,
    EventCreate,
    EventDelete,
    EventUpdate,
)
from ..store.memory import MemoryStore
from ..store.watch import Channel

ACTION_CREATE = 1
ACTION_UPDATE = 2
ACTION_DELETE = 4
ACTION_ALL = ACTION_CREATE | ACTION_UPDATE | ACTION_DELETE


@dataclass
class WatchSelector:
    """One watch entry (reference: api/watch.proto WatchRequest.WatchEntry,
    field menu per object from api/objects.proto watch_selectors — e.g.
    Task exposes service_id/node_id/slot/desired_state, Node exposes
    role/membership, and every annotated object exposes custom-index
    selectors). Kind-specific fields require `kind` to be set to the one
    object kind that supports them (validated by WatchAPI.watch, mirroring
    api/watch.go ConvertWatchArgs rejecting unsupported checks)."""

    kind: str = ""  # store table name, e.g. "task"; "" = all kinds
    action: int = ACTION_ALL
    id: str = ""
    id_prefix: str = ""
    name: str = ""
    name_prefix: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    # custom indexes (Annotations.indices); val "" = key presence only
    custom: dict[str, str] = field(default_factory=dict)
    custom_prefix: dict[str, str] = field(default_factory=dict)
    # kind="task" only
    service_id: str = ""
    node_id: str = ""
    slot: int | None = None
    desired_state: int | None = None
    # kind="node" only
    role: int | None = None
    membership: int | None = None

    # fields legal only for one kind (objects.proto watch_selectors)
    KIND_FIELDS = {
        "service_id": "task", "node_id": "task", "slot": "task",
        "desired_state": "task", "role": "node", "membership": "node",
    }

    def validate(self) -> None:
        for fname, kind in self.KIND_FIELDS.items():
            v = getattr(self, fname)
            if (v is not None and v != "") and self.kind != kind:
                raise ValueError(
                    f"selector field {fname!r} requires kind={kind!r}"
                    f" (got kind={self.kind!r})")

    def matches(self, event) -> bool:
        obj = getattr(event, "obj", None)
        if obj is None:
            return False
        if self.kind and obj.TABLE != self.kind:
            return False
        if isinstance(event, EventCreate):
            if not self.action & ACTION_CREATE:
                return False
        elif isinstance(event, EventUpdate):
            if not self.action & ACTION_UPDATE:
                return False
        elif isinstance(event, EventDelete):
            if not self.action & ACTION_DELETE:
                return False
        else:
            return False
        if self.id and obj.id != self.id:
            return False
        if self.id_prefix and not obj.id.startswith(self.id_prefix):
            return False
        if self.service_id and obj.service_id != self.service_id:
            return False
        if self.node_id and obj.node_id != self.node_id:
            return False
        if self.slot is not None and obj.slot != self.slot:
            return False
        if self.desired_state is not None \
                and obj.desired_state != self.desired_state:
            return False
        if self.role is not None and obj.spec.desired_role != self.role:
            return False
        if self.membership is not None \
                and obj.spec.membership != self.membership:
            return False
        if self.name or self.name_prefix or self.labels or self.custom \
                or self.custom_prefix:
            ann = getattr(getattr(obj, "spec", obj), "annotations", None)
            if ann is None:
                ann = getattr(obj, "annotations", None)
            if ann is None:
                return False
            if self.name and ann.name != self.name:
                return False
            if self.name_prefix and not ann.name.startswith(self.name_prefix):
                return False
            for k, v in self.labels.items():
                if k not in ann.labels:
                    return False
                if v and ann.labels[k] != v:
                    return False
            indices = getattr(ann, "indices", None) or {}
            for k, v in self.custom.items():
                if k not in indices:
                    return False
                if v and indices[k] != v:
                    return False
            for k, v in self.custom_prefix.items():
                if k not in indices or not indices[k].startswith(v):
                    return False
        return True


class WatchAPI:
    """reference: manager/watchapi/watch.go Server.Watch."""

    def __init__(self, store: MemoryStore):
        self.store = store

    def watch(self, selectors: list[WatchSelector] | None = None,
              resume_from: int | None = None,
              limit: int | None = -1) -> Channel:
        """Subscribe to matching events. `resume_from` replays committed
        changes after that store version first (reference WatchFrom)."""
        selectors = selectors or [WatchSelector()]
        for sel in selectors:
            if sel.kind and sel.kind not in ALL_TABLES:
                raise ValueError(f"unknown object kind {sel.kind!r}")
            sel.validate()

        def matcher(event) -> bool:
            return any(sel.matches(event) for sel in selectors)

        if resume_from is not None:
            return self.store.watch_from(resume_from, matcher, limit=limit)
        return self.store.watch_queue().watch(matcher, limit=limit)
