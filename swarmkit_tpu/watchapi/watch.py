"""Watch API: filtered store-event streaming to clients.

Behavioral re-derivation of manager/watchapi/watch.go + api/watch.proto:
clients subscribe with per-object-kind selectors (kind, id/id-prefix,
name/name-prefix, labels) and an action mask (create/update/delete) and
receive matching events, optionally including the previous object state on
updates, with resume-from-version replay via the store's WatchFrom plane.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..api.objects import (
    ALL_TABLES,
    EventCreate,
    EventDelete,
    EventUpdate,
)
from ..store.memory import MemoryStore
from ..store.watch import Channel

ACTION_CREATE = 1
ACTION_UPDATE = 2
ACTION_DELETE = 4
ACTION_ALL = ACTION_CREATE | ACTION_UPDATE | ACTION_DELETE


@dataclass
class WatchSelector:
    """One watch entry (reference: api/watch.proto WatchRequest.WatchEntry)."""

    kind: str = ""  # store table name, e.g. "task"; "" = all kinds
    action: int = ACTION_ALL
    id: str = ""
    id_prefix: str = ""
    name: str = ""
    name_prefix: str = ""
    labels: dict[str, str] = field(default_factory=dict)

    def matches(self, event) -> bool:
        obj = getattr(event, "obj", None)
        if obj is None:
            return False
        if self.kind and obj.TABLE != self.kind:
            return False
        if isinstance(event, EventCreate):
            if not self.action & ACTION_CREATE:
                return False
        elif isinstance(event, EventUpdate):
            if not self.action & ACTION_UPDATE:
                return False
        elif isinstance(event, EventDelete):
            if not self.action & ACTION_DELETE:
                return False
        else:
            return False
        if self.id and obj.id != self.id:
            return False
        if self.id_prefix and not obj.id.startswith(self.id_prefix):
            return False
        if self.name or self.name_prefix or self.labels:
            ann = getattr(getattr(obj, "spec", obj), "annotations", None)
            if ann is None:
                ann = getattr(obj, "annotations", None)
            if ann is None:
                return False
            if self.name and ann.name != self.name:
                return False
            if self.name_prefix and not ann.name.startswith(self.name_prefix):
                return False
            for k, v in self.labels.items():
                if k not in ann.labels:
                    return False
                if v and ann.labels[k] != v:
                    return False
        return True


class WatchAPI:
    """reference: manager/watchapi/watch.go Server.Watch."""

    def __init__(self, store: MemoryStore):
        self.store = store

    def watch(self, selectors: list[WatchSelector] | None = None,
              resume_from: int | None = None,
              limit: int | None = -1) -> Channel:
        """Subscribe to matching events. `resume_from` replays committed
        changes after that store version first (reference WatchFrom)."""
        selectors = selectors or [WatchSelector()]
        for sel in selectors:
            if sel.kind and sel.kind not in ALL_TABLES:
                raise ValueError(f"unknown object kind {sel.kind!r}")

        def matcher(event) -> bool:
            return any(sel.matches(event) for sel in selectors)

        if resume_from is not None:
            return self.store.watch_from(resume_from, matcher, limit=limit)
        return self.store.watch_queue().watch(matcher, limit=limit)
