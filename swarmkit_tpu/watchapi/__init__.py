from .watch import WatchAPI, WatchSelector  # noqa: F401
