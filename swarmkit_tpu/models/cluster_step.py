"""The flagship assembled model: one jitted cluster step.

This is the TPU program the whole framework funnels into — the analogue of
a model's forward pass. One `jit` fuses the two batched control-plane
kernels (SURVEY.md §2.12 / BASELINE.json north star):

  * placement — the scheduler hot loop (manager/scheduler/scheduler.go
    tick) as the masked canonical water-fill over every pending task group
    (`ops.placement.schedule_groups`);
  * consensus replay — the raft quorum tally + commit-frontier advance
    over the simulated manager mesh (`ops.raft_replay`).

`example_inputs()` builds a self-contained synthetic cluster (no test
fixtures), so the compile surface is reproducible anywhere the package is
importable. `__graft_entry__.entry()` exposes exactly this step.
"""
from __future__ import annotations

import random


def cluster_step(acks, quorum, *placement_args):
    """One cluster step: place all pending groups, advance the commit
    frontier. Jittable as a whole; inputs follow
    `scheduler.encode.KERNEL_ARG_FIELDS` for the placement side."""
    from ..ops.placement import schedule_groups
    from ..ops.raft_replay import replay_commit

    counts, totals, svc_counts = schedule_groups(*placement_args)
    commit_index, _committed = replay_commit(acks, quorum)
    return counts, totals, commit_index


def example_cluster(n_nodes: int = 256, n_groups: int = 4,
                    tasks_per_group: int = 64, seed: int = 0):
    """A synthetic (node_infos, task_groups) pair: labeled, resourced READY
    nodes and constrained task groups — the shapes the encoder feeds the
    flagship step."""
    from ..api.objects import Node, Task
    from ..api.specs import (
        Annotations,
        NodeDescription,
        Placement,
        Platform,
        Resources,
    )
    from ..api.types import NodeAvailability, NodeStatusState, TaskState
    from ..scheduler.encode import CPU_QUANTUM, MEM_QUANTUM, TaskGroup
    from ..scheduler.nodeinfo import NodeInfo

    rng = random.Random(seed)
    infos = []
    for i in range(n_nodes):
        n = Node(id=f"node-{i:05d}")
        n.status.state = NodeStatusState.READY
        n.status.addr = f"10.1.{i % 250}.{(i * 7) % 250}"
        n.spec.availability = NodeAvailability.ACTIVE
        n.spec.annotations = Annotations(
            name=f"node-{i}",
            labels={"zone": "abc"[i % 3], "disk": ("ssd", "hdd")[i % 2],
                    "rack": f"r{i % 17}"})
        n.description = NodeDescription(
            hostname=f"host-{i}",
            platform=Platform(os="linux", architecture="amd64"),
            resources=Resources(
                nano_cpus=rng.randint(4, 16) * CPU_QUANTUM * 1000,
                memory_bytes=rng.randint(8, 64) * MEM_QUANTUM * 1024,
                # discrete generic pool on a quarter of the fleet so the
                # generic-resource columns are part of the flagship surface
                generic={"gpu": 2} if i % 4 == 0 else {},
            ),
        )
        infos.append(NodeInfo.new(n, {}, n.description.resources.copy()))

    groups = []
    for gi in range(n_groups):
        svc = f"svc-{gi:03d}"
        tasks = []
        spec = None
        for ti in range(tasks_per_group):
            t = Task(id=f"task-{gi:03d}-{ti:05d}", service_id=svc,
                     slot=ti + 1)
            t.desired_state = TaskState.RUNNING
            t.status.state = TaskState.PENDING
            if spec is None:
                from ..api.specs import PlacementPreference

                spec = t.spec
                spec.resources.reservations.nano_cpus = \
                    (gi % 3) * CPU_QUANTUM
                spec.resources.reservations.memory_bytes = \
                    (gi % 4) * MEM_QUANTUM
                if gi % 2 == 0:
                    spec.placement = Placement(
                        constraints=[f"node.labels.zone == {'abc'[gi % 3]}"])
                if gi % 3 == 1:
                    # spread-tree groups (LMAX>0): one-, two- and
                    # THREE-level preference trees so the segmented pour
                    # path is part of the flagship compile surface at the
                    # depth real topologies use (zone > disk > rack)
                    prefs = [PlacementPreference(
                        spread_descriptor="node.labels.zone")]
                    if gi % 2 == 1:
                        prefs.append(PlacementPreference(
                            spread_descriptor="node.labels.disk"))
                    if gi % 6 == 1:
                        prefs.append(PlacementPreference(
                            spread_descriptor="node.labels.rack"))
                    spec.placement.preferences = prefs
                if gi % 7 == 3:
                    # generic-resource consumers (gpu pool nodes only)
                    spec.resources.reservations.generic = {"gpu": 1}
            else:
                t.spec = spec
            if gi % 5 == 2:
                # host-published ports: within-tick port conflicts between
                # groups publishing the same port ride the kernel's
                # port_used ORs
                from ..api.specs import EndpointSpec, PortConfig

                t.endpoint = EndpointSpec(ports=[PortConfig(
                    protocol="tcp", target_port=80,
                    published_port=8000 + (gi % 10),
                    publish_mode="host")])
            tasks.append(t)
        groups.append(TaskGroup(service_id=svc, spec_version=1, tasks=tasks,
                                ids=[t.id for t in tasks]))
    return infos, groups


def example_inputs(n_nodes: int = 256, n_groups: int = 4,
                   tasks_per_group: int = 64, n_managers: int = 5,
                   log_len: int = 1024, seed: int = 0):
    """(acks, quorum, *placement_args) ready for `cluster_step`."""
    import jax.numpy as jnp
    import numpy as np

    from ..scheduler.encode import encode, kernel_args

    infos, groups = example_cluster(n_nodes, n_groups, tasks_per_group, seed)
    p = encode(infos, groups)
    placement_args = tuple(jnp.asarray(a) for a in kernel_args(p))

    rng = np.random.RandomState(seed)
    acks = jnp.asarray(rng.rand(n_managers, log_len) < 0.8)
    quorum = jnp.asarray(np.int32(n_managers // 2 + 1))
    return (acks, quorum) + placement_args
