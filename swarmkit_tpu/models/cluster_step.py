"""The flagship assembled model: one jitted cluster step.

This is the TPU program the whole framework funnels into — the analogue of
a model's forward pass. One `jit` fuses the two batched control-plane
kernels (SURVEY.md §2.12 / BASELINE.json north star):

  * placement — the scheduler hot loop (manager/scheduler/scheduler.go
    tick) as the masked canonical water-fill over every pending task group
    (`ops.placement.schedule_groups`);
  * consensus replay — the raft quorum tally + commit-frontier advance
    over the simulated manager mesh (`ops.raft_replay`).

`example_inputs()` builds a self-contained synthetic cluster (no test
fixtures), so the compile surface is reproducible anywhere the package is
importable. `__graft_entry__.entry()` exposes exactly this step.
"""
from __future__ import annotations

import random


def cluster_step(acks, quorum, *placement_args, strategy: int = 0):
    """One cluster step: place all pending groups, advance the commit
    frontier. Jittable as a whole (strategy is static: 0 = spread/
    topology tree fill, 1 = binpack); inputs follow
    `scheduler.encode.KERNEL_ARG_FIELDS` for the placement side."""
    from ..ops.placement import schedule_groups
    from ..ops.raft_replay import replay_commit

    counts, totals, svc_counts = schedule_groups(*placement_args,
                                                 strategy=strategy)
    commit_index, _committed = replay_commit(acks, quorum)
    return counts, totals, commit_index


def example_cluster(n_nodes: int = 256, n_groups: int = 4,
                    tasks_per_group: int = 64, seed: int = 0):
    """A synthetic (node_infos, task_groups) pair: labeled, resourced READY
    nodes and constrained task groups — the shapes the encoder feeds the
    flagship step."""
    from ..api.objects import Node, Task
    from ..api.specs import (
        Annotations,
        NodeDescription,
        Placement,
        Platform,
        Resources,
    )
    from ..api.types import NodeAvailability, NodeStatusState, TaskState
    from ..scheduler.encode import CPU_QUANTUM, MEM_QUANTUM, TaskGroup
    from ..scheduler.nodeinfo import NodeInfo

    rng = random.Random(seed)
    infos = []
    for i in range(n_nodes):
        n = Node(id=f"node-{i:05d}")
        n.status.state = NodeStatusState.READY
        n.status.addr = f"10.1.{i % 250}.{(i * 7) % 250}"
        n.spec.availability = NodeAvailability.ACTIVE
        n.spec.annotations = Annotations(
            name=f"node-{i}",
            labels={"zone": "abc"[i % 3], "disk": ("ssd", "hdd")[i % 2],
                    "rack": f"r{i % 17}"})
        n.description = NodeDescription(
            hostname=f"host-{i}",
            platform=Platform(os="linux", architecture="amd64"),
            resources=Resources(
                nano_cpus=rng.randint(4, 16) * CPU_QUANTUM * 1000,
                memory_bytes=rng.randint(8, 64) * MEM_QUANTUM * 1024,
                # discrete generic pool on a quarter of the fleet so the
                # generic-resource columns are part of the flagship surface
                generic={"gpu": 2} if i % 4 == 0 else {},
            ),
        )
        infos.append(NodeInfo.new(n, {}, n.description.resources.copy()))

    groups = []
    for gi in range(n_groups):
        svc = f"svc-{gi:03d}"
        tasks = []
        spec = None
        for ti in range(tasks_per_group):
            t = Task(id=f"task-{gi:03d}-{ti:05d}", service_id=svc,
                     slot=ti + 1)
            t.desired_state = TaskState.RUNNING
            t.status.state = TaskState.PENDING
            if spec is None:
                from ..api.specs import PlacementPreference

                spec = t.spec
                spec.resources.reservations.nano_cpus = \
                    (gi % 3) * CPU_QUANTUM
                spec.resources.reservations.memory_bytes = \
                    (gi % 4) * MEM_QUANTUM
                if gi % 2 == 0:
                    spec.placement = Placement(
                        constraints=[f"node.labels.zone == {'abc'[gi % 3]}"])
                if gi % 3 == 1:
                    # spread-tree groups (LMAX>0): one-, two- and
                    # THREE-level preference trees so the segmented pour
                    # path is part of the flagship compile surface at the
                    # depth real topologies use (zone > disk > rack)
                    prefs = [PlacementPreference(
                        spread_descriptor="node.labels.zone")]
                    if gi % 2 == 1:
                        prefs.append(PlacementPreference(
                            spread_descriptor="node.labels.disk"))
                    if gi % 6 == 1:
                        prefs.append(PlacementPreference(
                            spread_descriptor="node.labels.rack"))
                    spec.placement.preferences = prefs
                if gi % 7 == 3:
                    # generic-resource consumers (gpu pool nodes only)
                    spec.resources.reservations.generic = {"gpu": 1}
            else:
                t.spec = spec
            if gi % 5 == 2:
                # host-published ports: within-tick port conflicts between
                # groups publishing the same port ride the kernel's
                # port_used ORs
                from ..api.specs import EndpointSpec, PortConfig

                t.endpoint = EndpointSpec(ports=[PortConfig(
                    protocol="tcp", target_port=80,
                    published_port=8000 + (gi % 10),
                    publish_mode="host")])
            tasks.append(t)
        groups.append(TaskGroup(service_id=svc, spec_version=1, tasks=tasks,
                                ids=[t.id for t in tasks]))
    return infos, groups


def synth_shard_cluster(n_nodes: int, n_shards: int,
                        groups_per_shard: int = 4,
                        tasks_per_group: int = 31_250,
                        seed: int = 0, lmax: int = 2,
                        with_ports: bool = True,
                        with_voltopo: bool = True,
                        strategy: str = "spread"):
    """Array-native synthetic cluster at oracle-infeasible scale.

    Builds an EncodedProblem DIRECTLY as numpy arrays — no Node/Task/
    NodeInfo objects, no encoder pass — so the 100k–1M-node grid costs
    O(N) vectorized numpy instead of a million Python objects (the
    memory-bounded construction the mesh flagship needs; the 10k-node
    `example_cluster` path stays the object-built, encoder-validated
    shape).

    The problem is built SHARD-PARTITIONED for the sampled-shard parity
    methodology (docs/mesh.md): nodes split into `n_shards` contiguous
    slices, every group is eligible on exactly one slice via an interned
    constraint, the spread label tree nests within slices (level-0 branch
    ids encode the shard), warm service counts stay within the owning
    slice, and port ids are reused only within a slice. Under those
    rules the global sequential-group fill RESTRICTED to one slice is
    bit-identical to the greedy CPU oracle run on that slice alone —
    which is what `parallel.shard_parity.sampled_shard_parity` checks at
    sizes where the full Python oracle cannot run.

    with_voltopo adds the ISSUE 19 CSI volume-topology mask leg: a
    second node_val column carries a shard-prefixed "csi zone" id and
    every 4th group requires mount 0 to match one of two zone values of
    ITS OWN shard. The leg is node-local (a pure static-mask AND), so
    slicing is trivially sound — but shard-prefixed values keep the
    synthetic honest: a group's rows can never match outside its slice.

    strategy stamps the problem's scoring engine ("spread" | "binpack" |
    "topology" — topology is spread with the axis already folded into
    the level-0 ranks here, so it shares the spread code path).

    Returns (EncodedProblem, group_shard int32[G]).
    """
    import numpy as np

    from ..scheduler.encode import (
        OP_EQ,
        VOL_TOPO_SEGS,
        EncodedProblem,
        _empty_vol_topo,
    )

    assert n_nodes % n_shards == 0, "shards are contiguous equal slices"
    per = n_nodes // n_shards
    N = n_nodes
    G = n_shards * groups_per_shard
    rng = np.random.RandomState(seed)
    shard_of_node = np.repeat(np.arange(n_shards, dtype=np.int32), per)
    # groups interleave shards so the kernel's sequential fold alternates
    # slices (the realistic store order, and the harder parity case)
    group_shard = (np.arange(G, dtype=np.int32) % n_shards)

    p = EncodedProblem(
        node_ids=[f"n{i:07d}" for i in range(N)],
        group_keys=[(f"svc-{gi:04d}", 1) for gi in range(G)],
        service_ids=[f"svc-{gi:04d}" for gi in range(G)],
        groups=[],
    )
    p.ready = rng.rand(N) > 0.01
    p.strategy = strategy
    if with_voltopo:
        # csi zone column (node_val col 1): shard-prefixed ids so a
        # group's vol-topo rows can only ever match inside its slice
        ZV = 3
        zone = (shard_of_node * ZV
                + rng.randint(0, ZV, N) + 1).astype(np.int32)
        p.node_val = np.stack(
            [(shard_of_node + 1).astype(np.int32), zone], axis=1)
    else:
        p.node_val = (shard_of_node + 1).reshape(N, 1).astype(np.int32)
    p.node_plat = np.zeros((N, 2), np.int32)
    p.node_plugins = np.zeros((N, 1), bool)
    PV = 4
    p.port_used0 = np.zeros((N, PV), bool)
    if with_ports:
        # a sprinkle of pre-used host ports (column 1) so the conflict
        # mask is live from tick 0
        p.port_used0[rng.rand(N) < 0.002, 1] = True
    p.avail_res = np.stack(
        [rng.randint(20, 400, N), rng.randint(50, 1000, N)],
        axis=1).astype(np.int32)
    p.total0 = rng.randint(0, 5, N).astype(np.int32)
    # warm per-service counts, CONFINED to the owning shard's slice
    p.svc_count0 = np.zeros((G, N), np.int32)
    for gi in range(0, G, 2):
        s = int(group_shard[gi])
        a, b = s * per, (s + 1) * per
        hot = rng.rand(per) < 0.05
        p.svc_count0[gi, a:b][hot] = rng.randint(
            1, 4, int(hot.sum())).astype(np.int32)

    p.n_tasks = np.full(G, tasks_per_group, np.int32)
    p.svc_idx = np.arange(G, dtype=np.int32)
    p.svc_idx_persistent = np.arange(G, dtype=np.int32)
    p.n_svc_rows = G
    p.need_res = np.stack(
        [rng.randint(0, 4, G), rng.randint(0, 5, G)],
        axis=1).astype(np.int32)
    p.max_replicas = np.where(np.arange(G) % 5 == 0, 3, 0).astype(np.int32)
    p.constraints = np.full((G, 1, 3), -1, np.int32)
    p.constraints[:, 0, 0] = 0                       # key col: shard label
    p.constraints[:, 0, 1] = OP_EQ
    p.constraints[:, 0, 2] = group_shard + 1         # interned shard value
    p.plat_req = np.full((G, 1, 2), -2, np.int32)
    p.req_plugins = np.zeros((G, 1), bool)
    p.has_ports = np.zeros(G, bool)
    p.group_ports = np.zeros((G, PV), bool)
    if with_ports:
        # every 6th group publishes a host port; groups of the SAME shard
        # reuse columns, so within-tick conflicts are exercised without
        # cross-shard coupling
        for gi in range(5, G, 6):
            p.has_ports[gi] = True
            p.group_ports[gi, (gi // n_shards) % 2] = True
    p.penalty = np.zeros((G, N), bool)
    p.penalty_nonzero = False
    p.extra_mask = np.ones((G, N), bool)
    p.extra_mask_all = True
    if with_voltopo:
        # every 4th group: mount 0 accepts either of two zone values of
        # the group's OWN shard (two alternative rows — the ∃-candidate
        # OR the kernel leg evaluates)
        ZV = 3
        W = 1 + 2 * VOL_TOPO_SEGS
        p.vol_topo = np.full((G, 2, W), -1, np.int32)
        for gi in range(3, G, 4):
            s = int(group_shard[gi])
            p.vol_topo[gi, 0, :3] = (0, 1, s * ZV + 1 + (gi % ZV))
            p.vol_topo[gi, 1, :3] = (0, 1, s * ZV + 1 + ((gi + 1) % ZV))
        p.vol_topo_any = True
    else:
        p.vol_topo = _empty_vol_topo(G)
        p.vol_topo_any = False
    # spread tree nested within shards: level-0 branch id encodes the
    # shard (branches never span a slice); level l+1 refines level l with
    # a contiguous child-id range per parent — the encoder's prefix-rank
    # invariant, constructed directly
    if lmax:
        Z, W = 4, 4
        r0 = shard_of_node * Z + rng.randint(0, Z, N).astype(np.int32)
        levels = [r0]
        for _ in range(1, lmax):
            levels.append(levels[-1] * W
                          + rng.randint(0, W, N).astype(np.int32))
        tree = np.stack(levels, axis=0).astype(np.int32)     # [L, N]
        # identical tree for every group: a broadcast VIEW, so the [G, L,
        # N] table costs [L, N] host memory (chunked uploads make shards
        # contiguous on demand)
        p.spread_rank = np.broadcast_to(tree[None], (G, lmax, N))
    else:
        p.spread_rank = np.zeros((G, 0, N), np.int32)
    return p, group_shard


def example_inputs(n_nodes: int = 256, n_groups: int = 4,
                   tasks_per_group: int = 64, n_managers: int = 5,
                   log_len: int = 1024, seed: int = 0):
    """(acks, quorum, *placement_args) ready for `cluster_step`."""
    import jax.numpy as jnp
    import numpy as np

    from ..scheduler.encode import encode, kernel_args

    infos, groups = example_cluster(n_nodes, n_groups, tasks_per_group, seed)
    p = encode(infos, groups)
    placement_args = tuple(jnp.asarray(a) for a in kernel_args(p))

    rng = np.random.RandomState(seed)
    acks = jnp.asarray(rng.rand(n_managers, log_len) < 0.8)
    quorum = jnp.asarray(np.int32(n_managers // 2 + 1))
    return (acks, quorum) + placement_args
