"""Random IDs (reference: identity/randomid.go — crockford base32 of 16 bytes)."""
from __future__ import annotations

import os

_ALPHABET = "0123456789abcdefghjkmnpqrstvwxyz"  # crockford base32, lowercase


def new_id() -> str:
    raw = int.from_bytes(os.urandom(16), "big")
    out = []
    for _ in range(25):
        out.append(_ALPHABET[raw & 31])
        raw >>= 5
    return "".join(reversed(out))
