"""Random IDs (reference: identity/randomid.go — crockford base32 of 16 bytes)."""
from __future__ import annotations

import os

_ALPHABET = "0123456789abcdefghjkmnpqrstvwxyz"  # crockford base32, lowercase


def new_id() -> str:
    raw = int.from_bytes(os.urandom(16), "big")
    out = []
    for _ in range(25):
        out.append(_ALPHABET[raw & 31])
        raw >>= 5
    return "".join(reversed(out))


def new_secret_token(kind: str = "") -> str:
    """Join/unlock token (reference: ca/config.go GenerateJoinToken —
    'SWMTKN-1-<ca digest>-<secret>'; here the digest slot carries the kind
    marker until the CA layer fills in the real root digest)."""
    return f"SWMTKN-1-{kind or 'token'}-{new_id()}"
