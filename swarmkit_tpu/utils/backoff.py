"""Unified retry/backoff policy: exponential delay with full jitter.

Before this module, ~20 call sites each hand-rolled their retry loop
(fixed 1 s raft reconnect pauses, the renewer's "pass, retried next
interval", RemoteControl's 0.5 s spin). Every caller-side retry now
states an explicit, bounded policy:

    policy = Backoff(base=0.05, factor=2.0, max_delay=2.0, max_attempts=5)
    result = retry(dial, policy=policy, retryable=is_transient)

Delays come from `Backoff.delay(attempt, rng)` — full jitter
(uniform(0, min(max_delay, base*factor^attempt)), the AWS-recommended
shape: retries from many clients decorrelate instead of thundering in
lockstep. Sleeps go through an injectable Clock (utils/clock.py), so a
FakeClock test drives every retry deterministically, and a seeded RNG
makes the jitter itself reproducible.
"""
from __future__ import annotations

import random
import threading
from dataclasses import dataclass
from typing import Callable, TypeVar

from .clock import REAL_CLOCK, Clock

T = TypeVar("T")

_DEFAULT_RNG = random.Random()


@dataclass(frozen=True)
class Backoff:
    """Retry policy. Immutable: share one instance across callers.

    max_attempts counts ALL tries including the first; max_attempts=1
    means "no retry". jitter=False gives the deterministic envelope
    (tests asserting exact delays)."""

    base: float = 0.05
    factor: float = 2.0
    max_delay: float = 2.0
    max_attempts: int = 5
    jitter: bool = True

    def envelope(self, attempt: int) -> float:
        """Upper bound of the delay after failed attempt #`attempt`
        (0-based). Unbounded policies (raft reconnect, CA renewal) feed
        a monotonically growing attempt count — float pow overflows near
        attempt 1024, so saturate to the cap instead of raising (an
        OverflowError here would kill the retrying thread)."""
        try:
            raw = self.base * self.factor ** attempt
        except OverflowError:
            return self.max_delay
        return min(self.max_delay, raw)

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        cap = self.envelope(attempt)
        if not self.jitter:
            return cap
        return (rng or _DEFAULT_RNG).uniform(0.0, cap)

    def delays(self, rng: random.Random | None = None):
        """The policy's delay sequence (max_attempts - 1 sleeps)."""
        return [self.delay(i, rng) for i in range(self.max_attempts - 1)]


# a shared conservative default for RPC-ish transients; callers with a
# known failure profile (raft reconnect, CA renewal) declare their own
DEFAULT_RPC = Backoff(base=0.05, factor=2.0, max_delay=2.0, max_attempts=4)


def sleep(clock: Clock, delay: float) -> None:
    """Clock-driven sleep: real time under Clock, fake-time under
    FakeClock (advance() wakes it) — the seam that makes retry loops
    deterministic in tests."""
    if delay <= 0:
        return
    clock.wait(threading.Event(), delay)


def retry(fn: Callable[[], T], *,
          policy: Backoff,
          retryable: Callable[[Exception], bool] = lambda exc: True,
          clock: Clock | None = None,
          rng: random.Random | None = None,
          on_retry: Callable[[int, Exception, float], None] | None = None,
          ) -> T:
    """Run `fn` under `policy`: non-retryable errors and the final
    attempt's error raise unchanged. `on_retry(attempt, exc, delay)`
    observes each scheduled retry (logging/metrics)."""
    clock = clock or REAL_CLOCK
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as exc:
            if attempt + 1 >= policy.max_attempts or not retryable(exc):
                raise
            d = policy.delay(attempt, rng)
            if on_retry is not None:
                try:
                    on_retry(attempt, exc, d)
                except Exception:
                    pass
            sleep(clock, d)
            attempt += 1
