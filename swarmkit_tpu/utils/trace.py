"""Trace plane: in-process span tracer with a crash-dumpable flight
recorder.

SURVEY §5 records the reference's observability gap verbatim: "Tracing /
profiling. No distributed tracer" — SwarmKit ships pprof hooks and
Prometheus gauges only. This build has four asynchronous planes (async
commit, raft group-commit, failpoints/chaos, dispatcher fan-out) whose
latency structure those surfaces cannot see: when a barrier stalls or a
heavy commit eats a wave period, thread stacks say *where* code sits,
never *which* stage of *which* wave took the time. A Dapper-style
in-process tracer closes that: named spans with causal parent links,
propagated across threads (the CommitWorker's heavy half links back to
its originating wave), across RPC calls (context rides a reserved
`_trace_ctx` kwarg in the frame payload) and across raft consensus
(context rides the Entry, so a follower's WAL fsync and apply join the
leader-side proposal's trace).

Cost contract — the same one `utils/failpoints.py` holds and the bench
accepts: DISARMED, every instrumentation site costs one module-global
truthiness test (`trace._REC is None`) and never constructs a Span,
files a record, or builds a closure. `with trace.span(...)`-style sites
at per-WAVE boundaries additionally pay the interpreter's transient
empty-kwargs dict for the call itself; per-ENTRY hot loops (the raft
apply loop, the ready flush, wheel beats) use the guarded
`trace.enabled()` pattern and allocate nothing at all. The conftest
fails any test that leaks an armed tracer, and the disarmed-overhead
guard in tests/test_trace.py pins the no-Span/no-record property on the
tick, dispatcher-flush, and raft ready-loop hot paths. Sites sit at
DECISION boundaries only — never
inside the C segment walk, never in per-entry WAL write loops — and
device syncs follow the tunnel rule: one `tick.device_sync` span per
burst (the real value pull), never one per kernel.

Armed, a finished span goes two places:

  * the FLIGHT RECORDER — a bounded ring of completed-span records the
    wedge monitor and the chaos harness dump next to CHAOS_SEED, and
    `/debug/trace/recent` serves as JSON span trees;
  * derived STAGE HISTOGRAMS — span names map by prefix onto the
    `tick_stage_seconds{stage=…}` / `raft_commit_path_seconds{stage=…}`
    / `dispatcher_flush_seconds{stage=…}` HistogramFamily-s, feeding the
    existing /metrics exposition (so arming the tracer is also how an
    operator gets per-stage latency percentiles).

Span taxonomy and parent rules are documented in docs/observability.md.
"""
from __future__ import annotations

import os
import threading
from ..analysis.lockgraph import make_lock
import time
from contextlib import contextmanager
from typing import Any, Callable

_REG_LOCK = make_lock('utils.trace.REG_LOCK')
# The armed recorder, or None. Replaced wholesale on arm/disarm so hot
# sites read it without a lock; the disarmed fast path everywhere is
# `if _REC is None: return` / `rec = _REC; if rec is not None: ...`.
_REC: "FlightRecorder | None" = None

_tls = threading.local()          # per-thread implicit-parent span stack
# arm generation: bumped on every arm(). Thread-local stacks are stamped
# with the generation they were built under, so a span left open on SOME
# OTHER thread across a disarm/re-arm (an rpc handler, a CommitWorker
# job) can never become an implicit parent under the NEW recorder —
# disarm() can only clear the CALLING thread's stack.
_GEN = 0

DEFAULT_CAPACITY = 4096

# span-name prefix -> (metrics family, help). The stage label is the
# span name with the prefix stripped. Families are created lazily at
# first armed use, so merely importing this module registers nothing.
_STAGE_FAMILIES: tuple[tuple[str, str, str], ...] = (
    ("tick.", "tick_stage_seconds",
     "Scheduler tick stage latency, derived from trace spans"),
    ("sched.", "tick_stage_seconds",
     "Scheduler tick stage latency, derived from trace spans"),
    ("raft.", "raft_commit_path_seconds",
     "Raft propose->flush->commit->apply stage latency, derived from "
     "trace spans"),
    ("dispatcher.", "dispatcher_flush_seconds",
     "Dispatcher fan-out flush stage latency, derived from trace spans"),
    ("hb.", "dispatcher_flush_seconds",
     "Dispatcher fan-out flush stage latency, derived from trace spans"),
)


def _new_id() -> str:
    # 64-bit hex, cheap and collision-safe at flight-recorder scale
    return os.urandom(8).hex()


class Span:
    """One in-flight span. Created ONLY while armed (recorder sites
    guard on `_REC is None` first); `end()` files the completed record
    into the recorder that was armed at start time, so a span that
    straddles a disarm still lands (in the retired recorder) instead of
    crashing its owner thread."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "_rec", "_t0", "_wall", "_on_stack", "_ended")

    def __init__(self, rec: "FlightRecorder", name: str,
                 parent: "tuple[str, str] | Span | None", attrs: dict,
                 on_stack: bool):
        self.name = name
        self.attrs = attrs
        parent = _coerce_ctx(parent)
        if parent is None:
            parent = _current_ctx()
        if parent is not None:
            self.trace_id, self.parent_id = parent
        else:
            self.trace_id, self.parent_id = _new_id(), None
        self.span_id = _new_id()
        self._rec = rec
        self._wall = rec.clock.monotonic() if rec.clock is not None \
            else time.time()
        self._t0 = time.perf_counter()
        self._on_stack = on_stack
        self._ended = False
        if on_stack:
            _stack().append(self)

    def ctx(self) -> tuple[str, str]:
        """The propagable context: (trace_id, span_id). Codec-safe (a
        plain tuple of strings) — it rides RPC kwargs and raft entries."""
        return (self.trace_id, self.span_id)

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def end(self, **attrs) -> None:
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        if self._on_stack:
            stack = _stack()
            if stack and stack[-1] is self:
                stack.pop()
            else:                      # ended out of order: drop by identity
                try:
                    stack.remove(self)
                except ValueError:
                    pass
        self._rec.record(self.name, self._wall,
                         time.perf_counter() - self._t0,
                         self.trace_id, self.span_id, self.parent_id,
                         self.attrs)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.attrs.setdefault("error", repr(exc))
        self.end()


class _NoopSpan:
    """Singleton returned by span() when disarmed: no allocation, every
    method a no-op."""

    __slots__ = ()

    def ctx(self):
        return None

    def set(self, **attrs):
        return self

    def end(self, **attrs):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


NOOP = _NoopSpan()


class FlightRecorder:
    """Bounded ring of completed-span records.

    A record is a plain dict (codec/JSON-safe):
      {name, t0, dur, trace, span, parent, thread, attrs}
    `t0` is wall-clock seconds (or the injected clock's monotonic time —
    tests pin expiry logic with FakeClock), `dur` is perf_counter
    seconds. The ring is `capacity` records deep; old spans fall off —
    exactly the crash-forensics shape: the TAIL near the wedge/failure
    is what matters.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock=None):
        self.capacity = max(16, int(capacity))
        self.clock = clock
        self._lock = make_lock('utils.trace.recorder')
        self._ring: list[dict] = []
        self.spans_started = 0       # observability + the disarmed guard
        self.dropped = 0             # records that fell off the ring

    def _count_start(self) -> None:
        # spans open from many threads at once (tick, CommitWorker, rpc
        # handlers); a bare += is a lost-update race on the counter
        with self._lock:
            self.spans_started += 1

    # ------------------------------------------------------------- writing
    def record(self, name: str, t0: float, dur: float, trace_id: str,
               span_id: str, parent_id: str | None, attrs: dict) -> None:
        rec = {"name": name, "t0": t0, "dur": dur, "trace": trace_id,
               "span": span_id, "parent": parent_id,
               "thread": threading.current_thread().name,
               "attrs": attrs}
        with self._lock:
            self._ring.append(rec)
            if len(self._ring) > self.capacity:
                # trim in chunks: one del per capacity/8 appends, not one
                # memmove per record
                cut = max(1, self.capacity // 8)
                del self._ring[:cut]
                self.dropped += cut
        if dur > 0.0 and _REC is self:
            # zero-duration point events (trace.event: raft.stage,
            # raft.commit) are trace markers, not latency samples — they
            # must not flood the derived stage histograms with 0s; and a
            # span ending into a RETIRED recorder (it straddled a
            # disarm) keeps its forensics record but must not grow the
            # histograms — those populate only while armed (CLAUDE.md)
            _observe_stage(name, dur)

    # ------------------------------------------------------------- reading
    def snapshot(self, seconds: float | None = None) -> list[dict]:
        """Completed records, oldest first; `seconds` keeps spans that
        RETIRED within the trailing window — keyed on end time, not
        start, so a span LONGER than the window (the slow stage an
        operator is hunting) still shows up in the capture."""
        with self._lock:
            out = list(self._ring)
        if seconds is not None:
            now = self.clock.monotonic() if self.clock is not None \
                else time.time()
            out = [r for r in out if now - (r["t0"] + r["dur"]) <= seconds]
        return out

    def tail(self, n: int = 64) -> list[dict]:
        with self._lock:
            return self._ring[-n:]

    def trees(self, seconds: float | None = None) -> list[dict]:
        """Group records into trace trees: one root per trace whose
        parent is absent from the window (JSON-ready for /debug/trace)."""
        recs = self.snapshot(seconds)
        by_span = {r["span"]: dict(r, children=[]) for r in recs}
        roots = []
        for r in by_span.values():
            parent = by_span.get(r["parent"]) if r["parent"] else None
            if parent is not None:
                parent["children"].append(r)
            else:
                roots.append(r)
        for r in by_span.values():
            r["children"].sort(key=lambda c: c["t0"])
        roots.sort(key=lambda c: c["t0"])
        return roots

    def tail_text(self, n: int = 64) -> str:
        """The crash-forensics dump: the recorder tail, one span per
        line, newest last (wedge monitor / chaos-failure output)."""
        lines = []
        for r in self.tail(n):
            parent = f" <{r['parent'][:8]}" if r["parent"] else ""
            attrs = "".join(f" {k}={v}" for k, v in r["attrs"].items())
            lines.append(
                f"[{r['t0']:.6f} +{r['dur'] * 1e3:8.3f}ms] "
                f"{r['name']} trace={r['trace'][:8]} "
                f"span={r['span'][:8]}{parent}"
                f" thread={r['thread']}{attrs}")
        return "\n".join(lines)


def _stack() -> list:
    if getattr(_tls, "gen", -1) != _GEN:
        # stale stack from a previous arm window: spans still on it end
        # fine (they hold their recorder; end() tolerates a missing
        # stack entry) but must not parent this window's spans
        _tls.gen = _GEN
        _tls.stack = []
    return _tls.stack


def _current_ctx() -> tuple[str, str] | None:
    if getattr(_tls, "gen", -1) != _GEN:
        return None
    s = getattr(_tls, "stack", None)
    if s:
        return s[-1].ctx()
    return None


def _coerce_ctx(parent) -> tuple[str, str] | None:
    """Normalize a parent that may have arrived OFF THE WIRE (an
    Entry.trace field, the RPC `_trace_ctx` kwarg): anything that is
    not a 2-sequence of strings is treated as absent — a version-skewed
    or buggy peer's garbage ctx must never raise inside the consumer's
    apply loop (it would wedge commit application on that node)."""
    if parent is None:
        return None
    if isinstance(parent, Span):
        return parent.ctx()
    if isinstance(parent, (tuple, list)) and len(parent) == 2 \
            and isinstance(parent[0], str) and isinstance(parent[1], str):
        return (parent[0], parent[1])
    return None


# prefix -> resolved HistogramFamily, filled at first armed use (the
# registry lookup + import per record was measurable armed overhead)
_STAGE_FAMILY_CACHE: dict[str, Any] = {}


def _observe_stage(name: str, dur: float) -> None:
    for prefix, family, help_ in _STAGE_FAMILIES:
        if name.startswith(prefix):
            fam = _STAGE_FAMILY_CACHE.get(prefix)
            if fam is None:
                from . import metrics

                fam = metrics.histogram_family(family, help_, ("stage",))
                _STAGE_FAMILY_CACHE[prefix] = fam
            fam.observe((name[len(prefix):] or name.rstrip("."),), dur)
            return


# ------------------------------------------------------------------ sites
def enabled() -> bool:
    return _REC is not None


def span(name: str, parent=None, **attrs):
    """Open a span (context manager). Disarmed: returns the NOOP
    singleton — nothing allocated. The span parents to `parent` (a ctx
    tuple or Span) or, implicitly, to the calling thread's innermost
    open span."""
    rec = _REC
    if rec is None:
        return NOOP
    rec._count_start()
    return Span(rec, name, parent, attrs, on_stack=True)


def start(name: str, parent=None, **attrs):
    """Open a span WITHOUT installing it as the thread's implicit
    parent (cross-thread spans: the owner ends it from wherever the
    work completes). Returns None when disarmed — callers guard."""
    rec = _REC
    if rec is None:
        return None
    rec._count_start()
    return Span(rec, name, parent, attrs, on_stack=False)


def ctx() -> tuple[str, str] | None:
    """The current propagable context, None when disarmed or no span is
    open. What RPC calls and raft proposals carry across boundaries."""
    if _REC is None:
        return None
    return _current_ctx()


def rec(name: str, seconds: float, parent=None, **attrs) -> None:
    """Record an already-measured stage as a completed span (the
    instrumented hot paths already time their stages into dicts — this
    files those measurements without restructuring them into `with`
    blocks). Disarmed: one truthiness test, nothing else."""
    r = _REC
    if r is None:
        return
    r._count_start()
    parent = _coerce_ctx(parent)
    if parent is None:
        parent = _current_ctx()
    if parent is not None:
        trace_id, parent_id = parent
    else:
        trace_id, parent_id = _new_id(), None
    wall = (r.clock.monotonic() if r.clock is not None else time.time())
    r.record(name, wall - seconds, seconds, trace_id, _new_id(),
             parent_id, attrs)


def event(name: str, parent=None, **attrs) -> None:
    """A zero-duration point annotation (e.g. `raft.stage`)."""
    rec(name, 0.0, parent=parent, **attrs)


def wrap(name: str, fn: Callable[[], Any], parent=None, **attrs):
    """Wrap a thunk so it runs under a span parented to `parent` —
    the cross-thread link for CommitWorker jobs (the heavy commit half
    joins its originating wave's trace). Disarmed: returns `fn`
    unchanged, no closure allocated beyond this call."""
    if _REC is None:
        return fn
    if isinstance(parent, Span):
        parent = parent.ctx()
    if parent is None:
        parent = _current_ctx()

    def run():
        # ON-stack on the worker thread: spans the job opens inside
        # (tick.commit.materialize/writeback, a raft.propose from the
        # store write-back) nest under this one instead of becoming
        # orphan roots — the whole point of the cross-thread link
        with span(name, parent=parent, **attrs):
            return fn()

    return run


# ----------------------------------------------------------------- arming
def arm(capacity: int = DEFAULT_CAPACITY, clock=None) -> FlightRecorder:
    """Arm the tracer (idempotent re-arm replaces the recorder)."""
    global _REC, _GEN
    r = FlightRecorder(capacity=capacity, clock=clock)
    with _REG_LOCK:
        _GEN += 1
        _REC = r
    return r


def disarm() -> None:
    global _REC, _RETIRED_TAIL
    with _REG_LOCK:
        if _REC is not None:
            # keep the tail across the disarm: report hooks (the chaos
            # makereport section) run AFTER the harness disarmed
            _RETIRED_TAIL = _REC.tail_text(64)
        _REC = None
    # a disarm must not leave implicit parents behind for the next arm
    s = getattr(_tls, "stack", None)
    if s:
        del s[:]


def active() -> bool:
    return _REC is not None


def recorder() -> FlightRecorder | None:
    return _REC


@contextmanager
def armed(capacity: int = DEFAULT_CAPACITY, clock=None):
    """`with trace.armed() as rec: ...` — the per-test arming surface;
    always disarms on exit (the conftest guard fails leaks)."""
    r = arm(capacity=capacity, clock=clock)
    try:
        yield r
    finally:
        disarm()


def tail_text(n: int = 64) -> str:
    """Crash-forensics helper: the armed recorder's tail, or "" when
    disarmed — callers (wedge monitor, chaos harness) print it next to
    their stack dump / CHAOS_SEED without caring whether tracing is on."""
    r = _REC
    return r.tail_text(n) if r is not None else ""


# tail captured by the most recent disarm() — lets a post-teardown
# report hook still show what the retired recorder held
_RETIRED_TAIL = ""


def last_tail_text(n: int = 64) -> str:
    """The armed tail, falling back to the tail captured at the last
    disarm — for hooks that run after the owning harness already
    disarmed (the conftest chaos report section). Clear the retired
    copy with `clear_retired_tail()` before each scope that must not
    see a stale predecessor's spans."""
    r = _REC
    if r is not None:
        return r.tail_text(n)
    return _RETIRED_TAIL


def clear_retired_tail() -> None:
    global _RETIRED_TAIL
    _RETIRED_TAIL = ""


# ---------------------------------------------------------------- env var
# SWARMKIT_TPU_TRACE arms the tracer in subprocesses (multi-process
# swarmd tests, operator debugging): "1" or a ring capacity.
_ENV_VAR = "SWARMKIT_TPU_TRACE"

_env_val = os.environ.get(_ENV_VAR, "").strip().lower()
if _env_val and _env_val not in ("0", "false", "off", "no"):
    try:
        _cap = int(_env_val)
    except ValueError:
        _cap = DEFAULT_CAPACITY
    arm(capacity=_cap if _cap > 1 else DEFAULT_CAPACITY)
