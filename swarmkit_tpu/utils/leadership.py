"""Demotion-tolerant store writes for leader-only component threads.

Every manager control component (role manager, key manager, CA signer,
orchestrators, …) runs as a thread started on leadership win and stopped
on leadership loss (reference manager/manager.go:1093-1149). Between the
raft step-down and the manager's stop() reaching the component there is a
window where a store write fails with ProposeError/NotLeader; the
reference components treat that as a normal shutdown signal and exit
cleanly (manager.go:1149+), never as a crash. These helpers give the
Python threads the same contract: `leadership_lost(exc)` classifies the
exception, `leader_write(store, txn)` returns False instead of raising
when leadership is gone mid-write.
"""
from __future__ import annotations

import logging

log = logging.getLogger("swarmkit_tpu.leadership")


def _lost_types() -> tuple[type, ...]:
    # lazy: utils must not import raft at module load (raft imports utils).
    # NOTE: plain ProposeError (quorum-loss timeout, dropped proposal) is
    # deliberately NOT here — it can happen while still leading, and a
    # component that stops on it would never come back until the next
    # leadership change; only the structured demotion signals count.
    from ..raft.node import NotLeader
    from ..raft.proposer import LeadershipLost

    return (LeadershipLost, NotLeader)


def leadership_lost(exc: BaseException) -> bool:
    """True if `exc` means this manager stopped being the raft leader (or
    never was) — the component should stop cleanly, not crash."""
    return isinstance(exc, _lost_types())


def leader_write(store, txn, component: str = "") -> bool:
    """Run a leader-only store update. Returns True on commit, False when
    leadership was lost mid-write (logged at info — it is an expected
    shutdown signal, the manager's stop() is already on its way). Any
    other failure propagates."""
    try:
        store.update(txn)
        return True
    except Exception as exc:
        if leadership_lost(exc):
            log.info("%s: leadership lost during store write (%s)",
                     component or "component", exc)
            return False
        raise
