"""Cluster telemetry rollup plane (ISSUE 15): heartbeat-piggybacked
node metric snapshots, merged manager-side into cluster-level families.

The trace plane (utils/trace.py) gave the system causal DEPTH — where a
given operation's time went. This plane adds the Monarch/Borg BREADTH
axis: every agent ships a compact snapshot of its metric registry
(utils/metrics.py `registry_snapshot`) on every Kth heartbeat; the
dispatcher stores the latest report in the session's owning SHARD (the
ISSUE 13 fan-out plane — the rollup scales with the dispatcher instead
of adding a scrape fan-in); the manager-side aggregator
(manager/telemetry.py) merges shard-partial rollups with its own local
families into `swarm_cluster_*` /metrics families, `/debug/cluster`,
and `control.get_cluster_telemetry` (leader-forwarded), with per-node
FRESHNESS tracked explicitly — a node whose beats stop goes stale and
is listed, never silently averaged in.

Cost contract — identical to utils/failpoints.py, utils/trace.py and
utils/lifecycle.py: DISARMED, the beat path costs ONE module-global
truthiness test (`telemetry._STATE is None`) and never builds a
snapshot, takes a lock, or walks the registry. Sites that assemble a
snapshot guard the assembly with `telemetry.enabled()` (the
span-in-loop lint rule audits `telemetry.*` calls in the hot modules).
The conftest fails any test that leaks an armed plane; the bench
`telemetry_plane` row pins `disarmed_beat_allocs == 0`.

Piggyback cadence and size bounds: every `report_every`-th beat
(default 6 — ~30 s at the 5 s heartbeat period) builds one snapshot,
bounded to `max_bytes` JSON-encoded (oversize reports degrade to a
gauges-only snapshot with `truncated` set — partial data beats a
dropped node). The dispatcher additionally enforces a structural bound
(`MAX_REPORT_SERIES`) on arrival: the wire codec rebuilds payloads
without field checks, and one hostile agent must not balloon a shard's
report store.

Documented in docs/observability.md (snapshot codec, freshness
semantics) and docs/dispatcher.md (shard-stored snapshots).
"""
from __future__ import annotations

import json
import os
from contextlib import contextmanager

from ..analysis.lockgraph import make_lock

_REG_LOCK = make_lock('utils.telemetry.REG_LOCK')
# The armed plane state, or None. Replaced wholesale on arm/disarm so
# hot sites read it without a lock; the disarmed fast path everywhere
# is `if _STATE is None: return`.
_STATE: "TelemetryState | None" = None

# The live manager-side aggregator (manager/telemetry.py registers on
# start, clears on stop) — how control.get_cluster_telemetry and the
# debugserver find it without threading a handle through ControlAPI.
_AGG = None

DEFAULT_REPORT_EVERY = 6          # beats between piggybacked snapshots
DEFAULT_MAX_BYTES = 128 * 1024    # JSON-encoded snapshot budget
MAX_REPORT_SERIES = 4096          # dispatcher-side structural bound


class TelemetryState:
    """Armed-plane config + counters (reports built/truncated/rejected —
    the observability of the observability plane)."""

    def __init__(self, report_every: int = DEFAULT_REPORT_EVERY,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self.report_every = max(1, int(report_every))
        self.max_bytes = int(max_bytes)
        self._lock = make_lock('utils.telemetry.state')
        self.reports_built = 0
        self.reports_truncated = 0
        self.reports_stored = 0
        self.reports_rejected = 0

    def bump(self, attr: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, attr, getattr(self, attr) + n)


# ------------------------------------------------------------------ sites
def enabled() -> bool:
    return _STATE is not None


def state() -> TelemetryState | None:
    return _STATE


def report_every() -> int:
    s = _STATE
    return s.report_every if s is not None else DEFAULT_REPORT_EVERY


def node_snapshot(agent=None, gauges: dict | None = None) -> dict | None:
    """Build one node's piggyback payload: the process metric registry
    plus the small additive gauge set — lifecycle task-state census
    (armed recorders only), the agent's status-report queue depth and
    locally-known task count. Returns None when the plane is disarmed
    (callers guard with `telemetry.enabled()` anyway so the disarmed
    beat path never even reaches here)."""
    s = _STATE
    if s is None:
        return None
    from . import lifecycle, metrics

    g: dict = dict(gauges or ())
    rec = lifecycle.recorder()
    if rec is not None:
        for stage, n in rec.stage_census().items():
            g[f"tasks_{stage.lower()}"] = n
    if agent is not None:
        pending = getattr(agent, "_pending", None)
        if pending is not None:
            g["agent_pending_statuses"] = len(pending)
        worker = getattr(agent, "worker", None)
        tasks = getattr(worker, "_tasks", None)
        if tasks is not None:
            g["agent_tasks"] = len(tasks)
    snap = metrics.registry_snapshot(gauges=g)
    s.bump("reports_built")
    try:
        if len(json.dumps(snap)) > s.max_bytes:
            # oversize: degrade to gauges-only rather than dropping the
            # node from the rollup entirely
            snap = {"v": 1, "counters": {}, "histograms": {},
                    "gauges": dict(g), "truncated": True}
            s.bump("reports_truncated")
    except (TypeError, ValueError):
        snap = {"v": 1, "counters": {}, "histograms": {}, "gauges": {},
                "truncated": True}
        s.bump("reports_truncated")
    return snap


# ------------------------------------------------------------ aggregator
def aggregator():
    """The live manager-side TelemetryAggregator (leader only), or
    None."""
    return _AGG


def set_aggregator(agg) -> None:
    global _AGG
    with _REG_LOCK:
        _AGG = agg


def clear_aggregator(agg) -> None:
    """Unregister `agg` if it is still the live one (a newer leadership
    cycle's aggregator must not be clobbered by the old one's stop)."""
    global _AGG
    with _REG_LOCK:
        if _AGG is agg:
            _AGG = None


# ----------------------------------------------------------------- arming
def arm(report_every: int = DEFAULT_REPORT_EVERY,
        max_bytes: int = DEFAULT_MAX_BYTES) -> TelemetryState:
    """Arm the telemetry plane (idempotent re-arm replaces the state)."""
    global _STATE
    s = TelemetryState(report_every=report_every, max_bytes=max_bytes)
    with _REG_LOCK:
        _STATE = s
    return s


def disarm() -> None:
    global _STATE
    with _REG_LOCK:
        _STATE = None


def active() -> bool:
    return _STATE is not None


@contextmanager
def armed(report_every: int = DEFAULT_REPORT_EVERY,
          max_bytes: int = DEFAULT_MAX_BYTES):
    """`with telemetry.armed() as st: ...` — the per-test arming
    surface; always disarms on exit (the conftest guard fails leaks)."""
    s = arm(report_every=report_every, max_bytes=max_bytes)
    try:
        yield s
    finally:
        disarm()


# ---------------------------------------------------------------- env var
# SWARMKIT_TPU_TELEMETRY arms the plane in subprocesses (multi-process
# swarmd, live-daemon rollup capture): "1" or a report_every cadence.
_ENV_VAR = "SWARMKIT_TPU_TELEMETRY"

_env_val = os.environ.get(_ENV_VAR, "").strip().lower()
if _env_val and _env_val not in ("0", "false", "off", "no"):
    try:
        _every = int(_env_val)
    except ValueError:
        _every = DEFAULT_REPORT_EVERY
    arm(report_every=_every if _every > 1 else DEFAULT_REPORT_EVERY)
