"""Retry queue with exponential backoff for volume operations.

Re-derivation of volumequeue/queue.go: entries are (id, attempt); each
enqueue schedules the id after `base * 2^attempt`, capped (100ms → 10min).
`wait` blocks until the soonest entry is ripe. Used by the CSI manager and
the agent volume manager to retry plugin calls.
"""
from __future__ import annotations

import heapq
import threading
import time

from ..analysis.lockgraph import make_rlock

BASE_RETRY_INTERVAL = 0.1  # volumequeue/queue.go baseRetryInterval 100ms
MAX_RETRY_INTERVAL = 600.0  # maxRetryInterval 10min


class VolumeQueue:
    def __init__(self):
        self._lock = threading.Condition(
            make_rlock("utils.volumequeue.cond"))
        self._heap: list[tuple[float, str, int]] = []  # (ready_at, id, attempt)
        self._pending: dict[str, int] = {}  # id -> attempt (dedupe)
        self._stopped = False

    def enqueue(self, vid: str, attempt: int = 0):
        """Schedule `vid` after the backoff for `attempt`
        (queue.go Enqueue; attempt 0 is immediate)."""
        delay = 0.0
        if attempt > 0:
            delay = min(BASE_RETRY_INTERVAL * (2 ** (attempt - 1)), MAX_RETRY_INTERVAL)
        with self._lock:
            if self._stopped:
                return
            if vid in self._pending:
                return  # already queued; keep the earlier schedule
            self._pending[vid] = attempt
            heapq.heappush(self._heap, (time.monotonic() + delay, vid, attempt))
            self._lock.notify_all()

    def wait(self, timeout: float | None = None) -> tuple[str, int] | None:
        """Block until an entry is ripe; returns (id, attempt) or None on
        stop/timeout (queue.go Wait)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                if self._stopped:
                    return None
                now = time.monotonic()
                if self._heap:
                    ready_at, vid, attempt = self._heap[0]
                    if ready_at <= now:
                        heapq.heappop(self._heap)
                        if self._pending.get(vid) == attempt:
                            del self._pending[vid]
                            return vid, attempt
                        continue  # stale (outdated/removed); skip
                    wait_for = ready_at - now
                else:
                    wait_for = None
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        return None
                    wait_for = remaining if wait_for is None else min(wait_for, remaining)
                self._lock.wait(timeout=wait_for)

    def outdated(self, vid: str):
        """Drop a queued id (queue.go Outdated: the object changed, pending
        retries are stale)."""
        with self._lock:
            self._pending.pop(vid, None)

    def stop(self):
        with self._lock:
            self._stopped = True
            self._lock.notify_all()
