"""Hot-path latency histograms (the reference's prometheus timers:
store tx / lock-hold — memory.go:99-112, raft propose — raft.go:204-209,
dispatcher scheduling delay — dispatcher.go:72-77).

A tiny fixed-bucket histogram with a process-global registry; the metrics
collector appends these to its Prometheus text exposition. Observation is
a few dict ops under a lock — cheap enough for every store transaction.
"""
from __future__ import annotations

import bisect
import threading

# prometheus-style default buckets, seconds
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram:
    def __init__(self, name: str, help_: str = "",
                 buckets: tuple = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float):
        i = bisect.bisect_left(self.buckets, seconds)
        with self._lock:
            self._counts[i] += 1
            self._sum += seconds
            self._n += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._n

    def prometheus_text(self) -> str:
        counts, total, n = self.snapshot()
        lines = [f"# TYPE {self.name} histogram"]
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            lines.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {n}')
        lines.append(f"{self.name}_sum {total:.6f}")
        lines.append(f"{self.name}_count {n}")
        return "\n".join(lines)


_registry: dict[str, Histogram] = {}
_registry_lock = threading.Lock()


def histogram(name: str, help_: str = "") -> Histogram:
    with _registry_lock:
        h = _registry.get(name)
        if h is None:
            h = Histogram(name, help_)
            _registry[name] = h
        return h


def all_histograms() -> list[Histogram]:
    with _registry_lock:
        return list(_registry.values())
