"""Hot-path latency histograms (the reference's prometheus timers:
store tx / lock-hold — memory.go:99-112, raft propose — raft.go:204-209,
dispatcher scheduling delay — dispatcher.go:72-77).

A tiny fixed-bucket histogram with a process-global registry; the metrics
collector appends these to its Prometheus text exposition. Observation is
a few dict ops under a lock — cheap enough for every store transaction.
"""
from __future__ import annotations

import bisect
import threading
from ..analysis.lockgraph import make_lock

# prometheus-style default buckets, seconds
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram:
    def __init__(self, name: str, help_: str = "",
                 buckets: tuple = DEFAULT_BUCKETS, labels: str = ""):
        self.name = name
        self.help = help_
        self.labels = labels          # pre-rendered 'k="v",...' or ""
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._n = 0
        self._lock = make_lock('utils.metrics.histogram')

    def observe(self, seconds: float):
        i = bisect.bisect_left(self.buckets, seconds)
        with self._lock:
            self._counts[i] += 1
            self._sum += seconds
            self._n += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._n

    def prometheus_text(self, type_line: bool = True) -> str:
        counts, total, n = self.snapshot()
        lines = []
        if type_line:
            # HELP precedes TYPE (promtool order); families render their
            # own header and pass type_line=False per child
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
            lines.append(f"# TYPE {self.name} histogram")
        lbl = (self.labels + ",") if self.labels else ""
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            lines.append(f'{self.name}_bucket{{{lbl}le="{b}"}} {cum}')
        lines.append(f'{self.name}_bucket{{{lbl}le="+Inf"}} {n}')
        suffix = f"{{{self.labels}}}" if self.labels else ""
        lines.append(f"{self.name}_sum{suffix} {total:.6f}")
        lines.append(f"{self.name}_count{suffix} {n}")
        return "\n".join(lines)


def _escape_label_value(v) -> str:
    # Prometheus text exposition: backslash, double-quote and newline must
    # be escaped inside label values or the scrape breaks mid-page.
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v) -> str:
    # HELP text: backslash and newline only (quotes are legal there)
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(label_names: tuple, values: tuple) -> str:
    return ",".join(f'{k}="{_escape_label_value(v)}"'
                    for k, v in zip(label_names, values))


class CounterFamily:
    """Labeled monotonic counters (the grpc_prometheus
    grpc_server_handled_total shape): one family, one series per label
    tuple, created on first increment."""

    def __init__(self, name: str, help_: str, label_names: tuple):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._series: dict[tuple, int] = {}
        self._lock = make_lock('utils.metrics.counter_family')

    def inc(self, values: tuple, n: int = 1):
        with self._lock:
            self._series[values] = self._series.get(values, 0) + n

    def value(self, values: tuple) -> int:
        with self._lock:
            return self._series.get(values, 0)

    def prometheus_text(self) -> str:
        with self._lock:
            items = sorted(self._series.items())
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} counter"]
        for values, n in items:
            lines.append(
                f"{self.name}{{{_render_labels(self.label_names, values)}}}"
                f" {n}")
        return "\n".join(lines)


class HistogramFamily:
    """Labeled histograms (grpc_server_handling_seconds shape)."""

    def __init__(self, name: str, help_: str, label_names: tuple,
                 buckets: tuple = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self.buckets = buckets
        self._series: dict[tuple, Histogram] = {}
        self._lock = make_lock('utils.metrics.histogram_family')

    def child(self, values: tuple) -> Histogram:
        with self._lock:
            h = self._series.get(values)
            if h is None:
                h = Histogram(self.name, self.help, self.buckets,
                              labels=_render_labels(self.label_names,
                                                    values))
                self._series[values] = h
            return h

    def observe(self, values: tuple, seconds: float):
        self.child(values).observe(seconds)

    def prometheus_text(self) -> str:
        with self._lock:
            items = sorted(self._series.items())
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} histogram"]
        for _values, h in items:
            lines.append(h.prometheus_text(type_line=False))
        return "\n".join(lines)


_registry: dict[str, Histogram] = {}
_families: dict[str, object] = {}
_registry_lock = make_lock('utils.metrics.registry_lock')


def histogram(name: str, help_: str = "") -> Histogram:
    with _registry_lock:
        h = _registry.get(name)
        if h is None:
            h = Histogram(name, help_)
            _registry[name] = h
        return h


def counter_family(name: str, help_: str = "",
                   label_names: tuple = ()) -> CounterFamily:
    with _registry_lock:
        f = _families.get(name)
        if f is None:
            f = CounterFamily(name, help_, label_names)
            _families[name] = f
        return f


def histogram_family(name: str, help_: str = "",
                     label_names: tuple = ()) -> HistogramFamily:
    with _registry_lock:
        f = _families.get(name)
        if f is None:
            f = HistogramFamily(name, help_, label_names)
            _families[name] = f
        return f


def all_histograms() -> list[Histogram]:
    with _registry_lock:
        return list(_registry.values())


def all_families() -> list:
    with _registry_lock:
        return list(_families.values())
