"""Hot-path latency histograms (the reference's prometheus timers:
store tx / lock-hold — memory.go:99-112, raft propose — raft.go:204-209,
dispatcher scheduling delay — dispatcher.go:72-77).

A tiny fixed-bucket histogram with a process-global registry; the metrics
collector appends these to its Prometheus text exposition. Observation is
a few dict ops under a lock — cheap enough for every store transaction.
"""
from __future__ import annotations

import bisect
import threading
from ..analysis.lockgraph import make_lock

# prometheus-style default buckets, seconds
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram:
    def __init__(self, name: str, help_: str = "",
                 buckets: tuple = DEFAULT_BUCKETS, labels: str = ""):
        self.name = name
        self.help = help_
        self.labels = labels          # pre-rendered 'k="v",...' or ""
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._n = 0
        self._lock = make_lock('utils.metrics.histogram')

    def observe(self, seconds: float):
        i = bisect.bisect_left(self.buckets, seconds)
        with self._lock:
            self._counts[i] += 1
            self._sum += seconds
            self._n += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._n

    def prometheus_text(self, type_line: bool = True) -> str:
        counts, total, n = self.snapshot()
        lines = []
        if type_line:
            # HELP precedes TYPE (promtool order); families render their
            # own header and pass type_line=False per child
            lines.append(f"# HELP {self.name} {_escape_help(self.help)}")
            lines.append(f"# TYPE {self.name} histogram")
        lbl = (self.labels + ",") if self.labels else ""
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            lines.append(f'{self.name}_bucket{{{lbl}le="{b}"}} {cum}')
        lines.append(f'{self.name}_bucket{{{lbl}le="+Inf"}} {n}')
        suffix = f"{{{self.labels}}}" if self.labels else ""
        lines.append(f"{self.name}_sum{suffix} {total:.6f}")
        lines.append(f"{self.name}_count{suffix} {n}")
        return "\n".join(lines)


def _escape_label_value(v) -> str:
    # Prometheus text exposition: backslash, double-quote and newline must
    # be escaped inside label values or the scrape breaks mid-page.
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v) -> str:
    # HELP text: backslash and newline only (quotes are legal there)
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _render_labels(label_names: tuple, values: tuple) -> str:
    return ",".join(f'{k}="{_escape_label_value(v)}"'
                    for k, v in zip(label_names, values))


class Counter:
    """Unlabeled monotonic counter. `inc` serializes under an internal
    lock — `+=` on an int attribute is not atomic across threads, and
    the telemetry rollup (utils/telemetry.py) sums these across nodes,
    so a lost increment here is a wrong cluster number there. The same
    internal-lock contract covers CounterFamily/Histogram; component
    code must NOT add its own ad-hoc guard locks around these."""

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._n = 0
        self._lock = make_lock('utils.metrics.counter')

    def inc(self, n: int = 1):
        with self._lock:
            self._n += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._n

    def prometheus_text(self) -> str:
        return (f"# HELP {self.name} {_escape_help(self.help)}\n"
                f"# TYPE {self.name} counter\n"
                f"{self.name} {self.value}")


class CounterDict(dict):
    """A dict of named counters with an atomic `inc` — the shape the
    dispatcher's `metrics` bag needs: plain-dict READ surface (tests and
    the bench read `metrics["flushes"]`), internally-locked writes for
    keys bumped from several threads. Single-writer keys may keep using
    plain item assignment; any key incremented from more than one thread
    must go through `inc`."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._lock = make_lock('utils.metrics.counter_dict')

    def inc(self, key, n=1):
        with self._lock:
            self[key] = self.get(key, 0) + n


class CounterFamily:
    """Labeled monotonic counters (the grpc_prometheus
    grpc_server_handled_total shape): one family, one series per label
    tuple, created on first increment."""

    def __init__(self, name: str, help_: str, label_names: tuple):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._series: dict[tuple, int] = {}
        self._lock = make_lock('utils.metrics.counter_family')

    def inc(self, values: tuple, n: int = 1):
        with self._lock:
            self._series[values] = self._series.get(values, 0) + n

    def value(self, values: tuple) -> int:
        with self._lock:
            return self._series.get(values, 0)

    def prometheus_text(self) -> str:
        with self._lock:
            items = sorted(self._series.items())
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} counter"]
        for values, n in items:
            lines.append(
                f"{self.name}{{{_render_labels(self.label_names, values)}}}"
                f" {n}")
        return "\n".join(lines)


class HistogramFamily:
    """Labeled histograms (grpc_server_handling_seconds shape)."""

    def __init__(self, name: str, help_: str, label_names: tuple,
                 buckets: tuple = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self.buckets = buckets
        self._series: dict[tuple, Histogram] = {}
        self._lock = make_lock('utils.metrics.histogram_family')

    def child(self, values: tuple) -> Histogram:
        with self._lock:
            h = self._series.get(values)
            if h is None:
                h = Histogram(self.name, self.help, self.buckets,
                              labels=_render_labels(self.label_names,
                                                    values))
                self._series[values] = h
            return h

    def observe(self, values: tuple, seconds: float):
        self.child(values).observe(seconds)

    def prometheus_text(self) -> str:
        with self._lock:
            items = sorted(self._series.items())
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} histogram"]
        for _values, h in items:
            lines.append(h.prometheus_text(type_line=False))
        return "\n".join(lines)


_registry: dict[str, Histogram] = {}
_families: dict[str, object] = {}
_registry_lock = make_lock('utils.metrics.registry_lock')


def histogram(name: str, help_: str = "") -> Histogram:
    with _registry_lock:
        h = _registry.get(name)
        if h is None:
            h = Histogram(name, help_)
            _registry[name] = h
        return h


def counter(name: str, help_: str = "") -> Counter:
    with _registry_lock:
        c = _families.get(name)
        if c is None:
            c = Counter(name, help_)
            _families[name] = c
        return c


def counter_family(name: str, help_: str = "",
                   label_names: tuple = ()) -> CounterFamily:
    with _registry_lock:
        f = _families.get(name)
        if f is None:
            f = CounterFamily(name, help_, label_names)
            _families[name] = f
        return f


def histogram_family(name: str, help_: str = "",
                     label_names: tuple = ()) -> HistogramFamily:
    with _registry_lock:
        f = _families.get(name)
        if f is None:
            f = HistogramFamily(name, help_, label_names)
            _families[name] = f
        return f


def all_histograms() -> list[Histogram]:
    with _registry_lock:
        return list(_registry.values())


def all_families() -> list:
    with _registry_lock:
        return list(_families.values())


# --------------------------------------------------------------------------
# Telemetry snapshot codec (ISSUE 15): a compact JSON-safe encoding of the
# registry that agents piggyback on heartbeats and the manager-side
# aggregator merges into cluster-level families.
#
# Shape (version 1):
#   {"v": 1,
#    "counters":   {name: {"labels": [...], "help": str,
#                          "series": [[<label values>, n], ...]}},
#    "histograms": {name: {"labels": [...], "help": str, "buckets": [...],
#                          "series": [[<label values>, counts, sum, n],
#                                     ...]}},
#    "gauges":     {name: number}}
#
# Counters ship CUMULATIVE values and histograms full bucket vectors, so
# "latest report per node" is all the rollup state a manager needs —
# merge is a plain per-series sum.  merge_snapshot is ASSOCIATIVE and
# COMMUTATIVE (integer sums per key; series keyed by label-value tuples;
# gauges summed), so shard-partial rollups compose in any order.
# Everything inside is JSON-safe (lists, never tuples) — the wire codec
# and swarmbench's JSON report carry snapshots verbatim.
# --------------------------------------------------------------------------


def empty_snapshot() -> dict:
    return {"v": 1, "counters": {}, "histograms": {}, "gauges": {}}


def registry_snapshot(gauges: dict | None = None, families=None,
                      histograms=None) -> dict:
    """Snapshot the process registry (or, for tests/partial rollups, the
    explicit `families`/`histograms` lists) into the codec shape above.
    `gauges` is the caller's small additive gauge set (task-state
    census, queue depths) merged in as-is."""
    fams = all_families() if families is None else list(families)
    hists = all_histograms() if histograms is None else list(histograms)
    snap = empty_snapshot()
    for f in fams:
        if isinstance(f, Counter):
            snap["counters"][f.name] = {
                "labels": [], "help": f.help,
                "series": [[[], f.value]]}
        elif isinstance(f, CounterFamily):
            with f._lock:
                items = sorted(f._series.items())
            snap["counters"][f.name] = {
                "labels": list(f.label_names), "help": f.help,
                "series": [[[str(v) for v in values], n]
                           for values, n in items]}
        elif isinstance(f, HistogramFamily):
            with f._lock:
                items = sorted(f._series.items())
            snap["histograms"][f.name] = {
                "labels": list(f.label_names), "help": f.help,
                "buckets": list(f.buckets),
                "series": [[[str(v) for v in values]]
                           + [list(s[0]), s[1], s[2]]
                           for values, h in items
                           for s in (h.snapshot(),)]}
    for h in hists:
        counts, total, n = h.snapshot()
        snap["histograms"][h.name] = {
            "labels": [], "help": h.help, "buckets": list(h.buckets),
            "series": [[[], counts, total, n]]}
    if gauges:
        snap["gauges"].update({str(k): v for k, v in gauges.items()})
    return snap


def snapshot_series_count(snap: dict) -> int:
    """Cheap structural size of a snapshot (the dispatcher's defensive
    bound on hostile payloads — no JSON encode on the beat path)."""
    try:
        return (sum(len(f.get("series", ())) for f in
                    snap.get("counters", {}).values())
                + sum(len(f.get("series", ())) for f in
                      snap.get("histograms", {}).values())
                + len(snap.get("gauges", {})))
    except AttributeError:
        return 0


def snapshot_within_budget(snap, max_cells: int = 200_000) -> bool:
    """Cheap structural budget over an UNTRUSTED snapshot: counts every
    container slot / scalar / string chunk visited and bails the moment
    the budget is crossed — len() is O(1), so one hostile 50M-element
    counts vector (or a giant string under an unknown key) is rejected
    without walking it and without a JSON encode on the beat path."""
    stack = [snap]
    cells = 0
    while stack:
        o = stack.pop()
        if isinstance(o, dict):
            cells += len(o)
            if cells > max_cells:
                return False
            stack.extend(o.keys())
            stack.extend(o.values())
        elif isinstance(o, (list, tuple)):
            cells += len(o)
            if cells > max_cells:
                return False
            stack.extend(o)
        elif isinstance(o, str):
            cells += 1 + len(o) // 64
        else:
            cells += 1
        if cells > max_cells:
            return False
    return True


def merge_snapshot(dst: dict, src: dict) -> dict:
    """Pure merge of two snapshots into a NEW snapshot: counter series
    sum per (name, label values), histogram bucket vectors sum
    element-wise (same bounds required — a bounds mismatch keeps the
    larger-n series and counts the drop under gauges["merge_dropped"]),
    gauges sum. Associative and commutative, so per-shard partial
    rollups compose in any order."""
    out = empty_snapshot()
    for snap in (dst, src):
        if not snap:
            continue
        for name, fam in snap.get("counters", {}).items():
            cur = out["counters"].setdefault(
                name, {"labels": list(fam.get("labels", ())),
                       "help": fam.get("help", ""), "series": []})
            have = {tuple(s[0]): s for s in cur["series"]}
            for values, n in fam.get("series", ()):
                key = tuple(values)
                if key in have:
                    have[key][1] += n
                else:
                    s = [list(values), n]
                    cur["series"].append(s)
                    have[key] = s
            cur["series"].sort(key=lambda s: s[0])
        for name, fam in snap.get("histograms", {}).items():
            cur = out["histograms"].setdefault(
                name, {"labels": list(fam.get("labels", ())),
                       "help": fam.get("help", ""),
                       "buckets": list(fam.get("buckets", ())),
                       "series": []})
            have = {tuple(s[0]): s for s in cur["series"]}
            compatible = list(fam.get("buckets", ())) == cur["buckets"]
            for values, counts, total, n in fam.get("series", ()):
                key = tuple(values)
                if key not in have:
                    if not compatible:
                        # a NEW series from a mismatched grid must not
                        # land raw under this family's bucket header —
                        # that would render its counts against wrong
                        # bounds. Same policy as the same-key case:
                        # drop and surface.
                        out["gauges"]["merge_dropped"] = \
                            out["gauges"].get("merge_dropped", 0) + 1
                        continue
                    s = [list(values), list(counts), total, n]
                    cur["series"].append(s)
                    have[key] = s
                elif compatible and len(counts) == len(have[key][1]):
                    s = have[key]
                    s[1] = [a + b for a, b in zip(s[1], counts)]
                    s[2] += total
                    s[3] += n
                else:
                    # incompatible bucket grid (mixed code versions):
                    # keep the series with more observations, surface
                    # the drop — never silently mix bucket spaces
                    if n > have[key][3]:
                        have[key][1] = list(counts)
                        have[key][2] = total
                        have[key][3] = n
                    out["gauges"]["merge_dropped"] = \
                        out["gauges"].get("merge_dropped", 0) + 1
            cur["series"].sort(key=lambda s: s[0])
        for name, v in snap.get("gauges", {}).items():
            out["gauges"][name] = out["gauges"].get(name, 0) + v
    return out


def snapshot_counter_value(snap: dict, name: str, values=()) -> int:
    """One counter series' value out of a snapshot (0 when absent) —
    the read helper rollup consumers and tests share."""
    fam = snap.get("counters", {}).get(name)
    if fam is None:
        return 0
    want = [str(v) for v in values]
    for series_values, n in fam.get("series", ()):
        if list(series_values) == want:
            return n
    return 0
