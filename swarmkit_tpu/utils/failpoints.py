"""Deterministic fault-injection plane: named failpoints.

The reference gets its durability confidence from years of soak testing;
this build gets it from *deterministic* fault injection instead. A
failpoint is a named site compiled into production code as

    failpoints.fp("raft.wal.fsync")

which, disarmed, costs ONE module-global truthiness test and a dict miss
— no allocation, no lock, no branch into policy code. Armed (per-test
via the `armed()` context manager, or for subprocesses via the
SWARMKIT_TPU_FAILPOINTS env var), a site can:

  * raise a chosen exception (instance, class, or factory);
  * inject latency (`delay=` seconds, real time);
  * substitute a value (`value=` — read by `fp_value` sites, e.g. a
    torn-write fraction);
  * transform a payload (`transform=` — `fp_transform` sites);
  * fire once / the first N times (`times=`), only after K clean passes
    (`skip=`), every Nth evaluation (`every=`), or probabilistically
    (`prob=` under a seeded RNG) — the chaos harness's mix, reproducible
    from one seed.

Site naming convention: `<layer>.<component>.<operation>` —
`raft.wal.fsync`, `rpc.wire.send`, `commit.worker.job`,
`dispatcher.heartbeat`. Sites live at DECISION boundaries (where an
error changes durability, replication, or liveness behavior), never
inside per-entry hot loops.

Arming is copy-on-write on the registry dict, so firing threads never
take the registry lock; each armed failpoint serializes its own
counters under a private lock (sites fire from many threads).
"""
from __future__ import annotations

import errno as _errno
import os
import random
import threading
from ..analysis.lockgraph import make_lock
import time
from contextlib import contextmanager
from typing import Any, Callable

_REG_LOCK = make_lock('utils.failpoints.REG_LOCK')
# name -> _Failpoint; REPLACED wholesale on arm/disarm (copy-on-write):
# `fp()` reads it without a lock. Empty when nothing is armed — the
# disarmed fast path is `if not _ARMED: return`.
_ARMED: dict[str, "_Failpoint"] = {}


class FailpointError(Exception):
    """Default injected error when a site is armed with error=True."""


def _make_exc(spec) -> BaseException:
    """Build a fresh exception per fire (re-raising one instance would
    chain tracebacks across fires)."""
    if spec is True:
        return FailpointError("injected failure")
    if isinstance(spec, BaseException):
        # re-build same-type/same-args so every fire gets a clean
        # traceback; OSError keeps its errno
        if isinstance(spec, OSError) and spec.errno is not None:
            return type(spec)(spec.errno, spec.strerror or str(spec))
        return type(spec)(*spec.args) if spec.args else type(spec)(str(spec))
    if isinstance(spec, type) and issubclass(spec, BaseException):
        return spec("injected failure")
    if callable(spec):
        return spec()
    raise TypeError(f"bad error spec for failpoint: {spec!r}")


def enospc() -> OSError:
    """Convenience: the ENOSPC OSError the WAL degradation contract keys
    on (tests arm `raft.wal.fsync` with `error=failpoints.enospc`)."""
    return OSError(_errno.ENOSPC, "No space left on device [injected]")


class _Failpoint:
    """One armed site. Counters are serialized under a private lock; the
    action (raise/sleep/value) runs OUTSIDE it."""

    def __init__(self, name: str, *,
                 error: Any = None,
                 delay: float = 0.0,
                 value: Any = None,
                 transform: Callable[[Any], Any] | None = None,
                 prob: float = 1.0,
                 times: int | None = None,
                 skip: int = 0,
                 every: int | None = None,
                 rng: random.Random | None = None,
                 on_fire: Callable[[str], None] | None = None):
        self.name = name
        self.error = error
        self.delay = delay
        self.value = value
        self.transform = transform
        self.prob = prob
        self.times = times
        self.skip = skip
        self.every = every
        self.rng = rng or random.Random(0)
        self.on_fire = on_fire
        self.evaluated = 0          # site reached while armed
        self.fired = 0              # action actually taken
        self._lock = make_lock('utils.failpoints.counters')

    def _should_fire(self) -> bool:
        with self._lock:
            self.evaluated += 1
            if self.times is not None and self.fired >= self.times:
                return False
            if self.evaluated <= self.skip:
                return False
            if self.every is not None \
                    and (self.evaluated - self.skip) % self.every != 0:
                return False
            if self.prob < 1.0 and self.rng.random() >= self.prob:
                return False
            self.fired += 1
            return True

    def _fire_common(self):
        if self.on_fire is not None:
            try:
                self.on_fire(self.name)
            except Exception:
                pass
        if self.delay:
            time.sleep(self.delay)

    def trigger(self):
        """fp() semantics: sleep and/or raise."""
        if not self._should_fire():
            return
        self._fire_common()
        if self.error is not None:
            raise _make_exc(self.error)

    def trigger_value(self, default):
        """fp_value() semantics: sleep/raise/substitute a value."""
        if not self._should_fire():
            return default
        self._fire_common()
        if self.error is not None:
            raise _make_exc(self.error)
        return self.value if self.value is not None else default

    def trigger_transform(self, payload):
        """fp_transform() semantics: sleep/raise/transform a payload."""
        if not self._should_fire():
            return payload
        self._fire_common()
        if self.error is not None:
            raise _make_exc(self.error)
        if self.transform is not None:
            return self.transform(payload)
        return payload


# ------------------------------------------------------------------ sites
def fp(name: str) -> None:
    """Injection site: no-op unless `name` is armed; may sleep or raise."""
    if not _ARMED:
        return
    p = _ARMED.get(name)
    if p is not None:
        p.trigger()


def fp_value(name: str, default=None):
    """Injection site that can substitute a value (e.g. a torn-write
    fraction). Returns `default` unless armed and firing."""
    if not _ARMED:
        return default
    p = _ARMED.get(name)
    if p is None:
        return default
    return p.trigger_value(default)


def fp_transform(name: str, payload):
    """Injection site that can corrupt/shorten a payload in flight.
    Returns `payload` unchanged unless armed and firing."""
    if not _ARMED:
        return payload
    p = _ARMED.get(name)
    if p is None:
        return payload
    return p.trigger_transform(payload)


# ----------------------------------------------------------------- arming
def arm(name: str, **kw) -> _Failpoint:
    """Arm `name`; returns the failpoint (its .fired/.evaluated counters
    are test observability). Re-arming replaces the previous config."""
    p = _Failpoint(name, **kw)
    with _REG_LOCK:
        new = dict(_ARMED)
        new[name] = p
        _set_registry(new)
    return p


def disarm(name: str) -> None:
    with _REG_LOCK:
        if name in _ARMED:
            new = dict(_ARMED)
            new.pop(name, None)
            _set_registry(new)


def disarm_all() -> None:
    with _REG_LOCK:
        _set_registry({})


def active() -> list[str]:
    return sorted(_ARMED)


def _set_registry(new: dict) -> None:
    global _ARMED
    _ARMED = new


@contextmanager
def armed(name: str, **kw):
    """`with failpoints.armed("raft.wal.fsync", error=OSError): ...` —
    the per-test arming surface; always disarms on exit."""
    p = arm(name, **kw)
    try:
        yield p
    finally:
        disarm(name)


# ---------------------------------------------------------------- env var
# SWARMKIT_TPU_FAILPOINTS arms sites in subprocesses (multi-process swarmd
# tests) where a context manager cannot reach:
#   name=error:OSError:msg;name2=delay:0.05;name3=error:enospc,times:1
_ENV_VAR = "SWARMKIT_TPU_FAILPOINTS"

_ENV_ERRORS = {
    "oserror": OSError,
    "enospc": enospc,
    "connectionreset": ConnectionResetError,
    "timeout": TimeoutError,
    "valueerror": ValueError,
    "runtimeerror": RuntimeError,
    "failpoint": FailpointError,
}


def _parse_env(spec: str) -> None:
    for item in spec.split(";"):
        item = item.strip()
        if not item or "=" not in item:
            continue
        name, actions = item.split("=", 1)
        kw: dict[str, Any] = {}
        for action in actions.split(","):
            parts = action.split(":")
            kind = parts[0].strip().lower()
            if kind == "error":
                exc = _ENV_ERRORS.get(
                    parts[1].strip().lower() if len(parts) > 1 else "",
                    FailpointError)
                if len(parts) > 2 and exc is not enospc:
                    msg = parts[2]
                    kw["error"] = (lambda e=exc, m=msg: e(m))
                else:
                    kw["error"] = exc
            elif kind == "delay":
                kw["delay"] = float(parts[1])
            elif kind == "times":
                kw["times"] = int(parts[1])
            elif kind == "skip":
                kw["skip"] = int(parts[1])
            elif kind == "every":
                kw["every"] = int(parts[1])
            elif kind == "prob":
                kw["prob"] = float(parts[1])
            elif kind == "seed":
                kw["rng"] = random.Random(int(parts[1]))
        if kw:
            arm(name.strip(), **kw)


if os.environ.get(_ENV_VAR):
    _parse_env(os.environ[_ENV_VAR])
