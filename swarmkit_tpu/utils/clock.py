"""Injectable time source (reference: manager/state/raft/raft.go:186-190
ClockSource + testutils.go:50 AdvanceTicks).

Production code takes a `Clock` and uses it for monotonic reads, timed
waits, and one-shot timers; tests inject `FakeClock` and drive time with
`advance()` so timer-dependent logic (raft tickers, heartbeat expiry)
runs deterministically instead of racing the wall clock on a loaded
machine — the round-2 verdict's fix for the daemon tier's load flakes.
"""
from __future__ import annotations

import threading
from ..analysis.lockgraph import make_lock, make_rlock
import time
from typing import Callable


class _WheelTimer:
    __slots__ = ("due", "fn", "cancelled", "seq", "_wheel")

    def __init__(self, due: float, fn, seq: int, wheel):
        self.due = due
        self.fn = fn
        self.seq = seq
        self.cancelled = False
        self._wheel = wheel

    def cancel(self):
        if not self.cancelled:
            self.cancelled = True
            w = self._wheel
            if w is not None:
                w._note_cancel()

    def __lt__(self, other):          # heap ordering
        return (self.due, self.seq) < (other.due, other.seq)


class TimerWheel:
    """Shared timer service: ONE heap-walking thread plus a small firing
    pool serve every timer in the process.

    The survey's §7 hard-parts note made this a requirement: the
    reference leans on cheap goroutines for 10k per-node heartbeat
    timers; `threading.Timer` spawns an OS THREAD per armed timer and
    the dispatcher re-arms one per node per beat — 10k live timer
    threads and thousands of thread creations/s at the design point.
    Here arming is a heap push; cancellation is a flag (lazily dropped
    when popped). Callbacks fire on a 4-thread pool so one slow expiry
    handler (e.g. a node-down store write during an election) cannot
    stall the wheel."""

    POOL_WORKERS = 4

    def __init__(self):
        self._heap: list[_WheelTimer] = []
        self._cond = threading.Condition(make_rlock("utils.clock.wheel_cond"))
        self._seq = 0
        self._thread: threading.Thread | None = None
        self._pool = None
        self._stopped = False
        self._n_cancelled = 0
        self._busy = 0                 # callbacks currently executing

    def _ensure_started(self):
        if self._thread is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self.POOL_WORKERS,
                thread_name_prefix="timer-fire")
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="timer-wheel")
            self._thread.start()

    def _note_cancel(self):
        with self._cond:
            self._n_cancelled += 1

    def timer(self, delay: float, fn: Callable[[], None]) -> _WheelTimer:
        import heapq

        with self._cond:
            if self._stopped:
                raise RuntimeError("timer wheel stopped")
            self._ensure_started()
            self._seq += 1
            t = _WheelTimer(time.monotonic() + delay, fn, self._seq, self)
            heapq.heappush(self._heap, t)
            # heap hygiene (asyncio's rule): cancel-and-re-arm consumers
            # (Heartbeat.beat) would otherwise accumulate dead entries
            # proportional to timeout/beat-interval per node
            if (self._n_cancelled > len(self._heap) // 2
                    and len(self._heap) > 64):
                self._heap = [x for x in self._heap if not x.cancelled]
                heapq.heapify(self._heap)
                self._n_cancelled = 0
            if self._heap[0] is t:
                self._cond.notify()       # new earliest deadline
            return t

    def stop(self):
        """Tests/embedding cleanup; the process-wide wheel never stops."""
        with self._cond:
            self._stopped = True
            self._cond.notify()
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    def _fire(self, t: _WheelTimer):
        try:
            t.fn()
        except BaseException as exc:   # noqa: BLE001
            # route to threading.excepthook so crashing timer callbacks
            # surface exactly like crashing threads — the conftest guard
            # FAILS the suite on these (a swallowed Future would not)
            threading.excepthook(threading.ExceptHookArgs(
                (type(exc), exc, exc.__traceback__,
                 threading.current_thread())))
        finally:
            with self._cond:
                self._busy -= 1

    def _run(self):
        import heapq

        while True:
            with self._cond:
                if self._stopped:
                    return
                now = time.monotonic()
                due: list[_WheelTimer] = []
                while self._heap and (self._heap[0].cancelled
                                      or self._heap[0].due <= now):
                    t = heapq.heappop(self._heap)
                    if t.cancelled:
                        self._n_cancelled = max(0, self._n_cancelled - 1)
                    else:
                        due.append(t)
                timeout = (self._heap[0].due - now) if self._heap else None
                if not due:
                    self._cond.wait(timeout)
                    continue
                shed = []
                for t in due:
                    # pool saturated (e.g. many node-down handlers stalled
                    # on a raft write during an election): shed to one-off
                    # threads rather than queueing behind blocked workers
                    if self._busy >= self.POOL_WORKERS:
                        shed.append(t)
                    else:
                        self._busy += 1
                        self._pool.submit(self._fire, t)
            for t in shed:
                with self._cond:
                    self._busy += 1
                threading.Thread(target=self._fire, args=(t,),
                                 daemon=True,
                                 name="timer-fire-overflow").start()


class Clock:
    """Real time. Subclass-compatible surface kept deliberately tiny."""

    _wheel: TimerWheel | None = None
    _wheel_lock = make_lock('utils.clock.wheel_lock')

    def monotonic(self) -> float:
        return time.monotonic()

    def time(self) -> float:
        """Wall-clock seconds (certificate validity windows are wall time —
        ca/renewer.go computes the renewal point from NotAfter/NotBefore)."""
        return time.time()

    def wait(self, event: threading.Event, timeout: float | None) -> bool:
        """Event.wait under this clock; returns event state like Event.wait."""
        return event.wait(timeout)

    def timer(self, delay: float, fn: Callable[[], None]):
        """One-shot timer; returns an object with .cancel(). Served by the
        process-wide TimerWheel — O(log n) to arm, no thread per timer."""
        if Clock._wheel is None:
            with Clock._wheel_lock:
                if Clock._wheel is None:
                    Clock._wheel = TimerWheel()
        return Clock._wheel.timer(delay, fn)


REAL_CLOCK = Clock()


class _FakeTimer:
    __slots__ = ("due", "fn", "cancelled")

    def __init__(self, due: float, fn):
        self.due = due
        self.fn = fn
        self.cancelled = False

    def cancel(self):
        self.cancelled = True


class FakeClock(Clock):
    """Manually-advanced clock. `advance(dt)` moves time forward, fires
    due timers (in due order, outside the lock), and wakes `wait`ers so
    they can re-check their deadlines. Waits on real Events still notice
    sets promptly via a short real-time poll — threads not driven by the
    test cannot deadlock it."""

    def __init__(self, start: float = 1000.0, poll: float = 0.01):
        self._now = start
        self._poll = poll
        self._cond = threading.Condition(make_rlock("utils.clock.fake_cond"))
        self._timers: list[_FakeTimer] = []

    def monotonic(self) -> float:
        with self._cond:
            return self._now

    def time(self) -> float:
        # the fake clock's single timeline serves as wall time too; start
        # it at time.time() in tests that exercise certificate windows
        with self._cond:
            return self._now

    def wait(self, event: threading.Event, timeout: float | None) -> bool:
        if timeout is None:
            return event.wait(None)
        with self._cond:
            deadline = self._now + timeout
            while not event.is_set() and self._now < deadline:
                self._cond.wait(self._poll)
        return event.is_set()

    def timer(self, delay: float, fn):
        with self._cond:
            t = _FakeTimer(self._now + delay, fn)
            self._timers.append(t)
            return t

    def advance(self, dt: float):
        with self._cond:
            self._now += dt
            now = self._now
            due = sorted((t for t in self._timers
                          if not t.cancelled and t.due <= now),
                         key=lambda t: t.due)
            self._timers = [t for t in self._timers
                            if not t.cancelled and t.due > now]
            self._cond.notify_all()
        for t in due:
            t.fn()
