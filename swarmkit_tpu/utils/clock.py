"""Injectable time source (reference: manager/state/raft/raft.go:186-190
ClockSource + testutils.go:50 AdvanceTicks).

Production code takes a `Clock` and uses it for monotonic reads, timed
waits, and one-shot timers; tests inject `FakeClock` and drive time with
`advance()` so timer-dependent logic (raft tickers, heartbeat expiry)
runs deterministically instead of racing the wall clock on a loaded
machine — the round-2 verdict's fix for the daemon tier's load flakes.
"""
from __future__ import annotations

import threading
import time
from typing import Callable


class Clock:
    """Real time. Subclass-compatible surface kept deliberately tiny."""

    def monotonic(self) -> float:
        return time.monotonic()

    def wait(self, event: threading.Event, timeout: float | None) -> bool:
        """Event.wait under this clock; returns event state like Event.wait."""
        return event.wait(timeout)

    def timer(self, delay: float, fn: Callable[[], None]):
        """One-shot timer; returns an object with .cancel()."""
        t = threading.Timer(delay, fn)
        t.daemon = True
        t.start()
        return t


REAL_CLOCK = Clock()


class _FakeTimer:
    __slots__ = ("due", "fn", "cancelled")

    def __init__(self, due: float, fn):
        self.due = due
        self.fn = fn
        self.cancelled = False

    def cancel(self):
        self.cancelled = True


class FakeClock(Clock):
    """Manually-advanced clock. `advance(dt)` moves time forward, fires
    due timers (in due order, outside the lock), and wakes `wait`ers so
    they can re-check their deadlines. Waits on real Events still notice
    sets promptly via a short real-time poll — threads not driven by the
    test cannot deadlock it."""

    def __init__(self, start: float = 1000.0, poll: float = 0.01):
        self._now = start
        self._poll = poll
        self._cond = threading.Condition()
        self._timers: list[_FakeTimer] = []

    def monotonic(self) -> float:
        with self._cond:
            return self._now

    def wait(self, event: threading.Event, timeout: float | None) -> bool:
        if timeout is None:
            return event.wait(None)
        with self._cond:
            deadline = self._now + timeout
            while not event.is_set() and self._now < deadline:
                self._cond.wait(self._poll)
        return event.is_set()

    def timer(self, delay: float, fn):
        with self._cond:
            t = _FakeTimer(self._now + delay, fn)
            self._timers.append(t)
            return t

    def advance(self, dt: float):
        with self._cond:
            self._now += dt
            now = self._now
            due = sorted((t for t in self._timers
                          if not t.cancelled and t.due <= now),
                         key=lambda t: t.due)
            self._timers = [t for t in self._timers
                            if not t.cancelled and t.due > now]
            self._cond.notify_all()
        for t in due:
            t.fn()
