"""Declarative SLO specs over the task lifecycle plane.

An SLO here is "percentile P of metric M stays at or under T seconds",
evaluated from the data the lifecycle recorder (utils/lifecycle.py)
produces: exact startup/transition samples when a recorder is at hand
(tests, the chaos soak, /debug/slo), or the derived
`task_startup_seconds` / `task_transition_seconds{from,to}` histograms
when only the /metrics exposition is (bucket-upper-bound estimates —
conservative, never optimistic).

Also home of the shared percentile math: `quantile_nearest_rank` is the
ONE nearest-rank implementation (swarmbench's old
`int(p/100*len(lat))` was biased — p50 of 2 samples returned the MAX;
correct nearest-rank is `ceil(p/100*n) - 1`), reused by
cmd/swarmbench.py, bench.py and the evaluators below.

The stage-attribution report decomposes end-to-end NEW→RUNNING latency
into per-leg (from→to) slices from the same timelines. Per task the leg
durations telescope to the e2e exactly, so the aggregate invariant —
total per-leg seconds over complete timelines equals total e2e seconds
within tolerance — is the report's self-check (`reconciled`); a
violation means a record site double-filed or a timeline was truncated
mid-analysis.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

from ..api.types import TaskState


def quantile_nearest_rank(values, p: float):
    """Nearest-rank percentile (R-1): the smallest sample at or above
    rank ceil(p/100 * n). p=0 → min, p=100 → max; None on no samples.
    `values` need not be sorted."""
    return quantiles_nearest_rank(values, (p,))[p]


def quantiles_nearest_rank(values, ps) -> dict:
    """Several nearest-rank percentiles over ONE sort (report builders
    ask for p50/p90/p99 of the same samples — re-sorting per percentile
    was measurable at recorder capacity). Returns {p: value-or-None}."""
    for p in ps:
        if not 0 <= p <= 100:
            raise ValueError(f"percentile out of range: {p}")
    if not values:
        return {p: None for p in ps}
    vs = sorted(values)
    n = len(vs)
    return {p: vs[max(0, min(n, math.ceil(p / 100.0 * n)) - 1)]
            for p in ps}


def histogram_quantile(hist, p: float):
    """Nearest-rank estimate from a utils.metrics Histogram: the upper
    bound of the first bucket whose cumulative count reaches the rank —
    conservative, the estimate only ever rounds UP. A rank landing in
    the +Inf tail returns math.inf (the sample exceeded every finite
    bucket; an SLO check against it must FAIL, never pass on the
    largest finite bound). None on an empty histogram."""
    counts, _total, n = hist.snapshot()
    if n == 0:
        return None
    if not 0 <= p <= 100:
        raise ValueError(f"percentile out of range: {p}")
    rank = max(1, math.ceil(p / 100.0 * n))
    cum = 0
    for bound, c in zip(hist.buckets, counts):
        cum += c
        if cum >= rank:
            return bound
    return math.inf


@dataclass(frozen=True)
class SLOSpec:
    """One objective: percentile `p` of `metric` ≤ `target_s`.

    metric: "startup" (NEW→RUNNING e2e) or a ("FROM", "TO") stage pair
    (one timeline leg, e.g. ("ASSIGNED", "SHIPPED")).
    min_samples: below this the SLO is VACUOUS (ok, n counted) rather
    than failed — a fresh window with two tasks must not page.
    """

    name: str
    p: float
    target_s: float
    metric: object = "startup"
    min_samples: int = 1


@dataclass
class SLOResult:
    spec: SLOSpec
    n: int
    observed_s: float | None
    ok: bool

    def as_dict(self) -> dict:
        m = self.spec.metric
        return {
            "name": self.spec.name,
            "metric": (m if isinstance(m, str) else f"{m[0]}->{m[1]}"),
            "p": self.spec.p,
            "target_s": self.spec.target_s,
            "observed_s": (None if self.observed_s is None
                           else round(self.observed_s, 6)),
            "n": self.n,
            "ok": self.ok,
        }


@dataclass
class SLOReport:
    results: list[SLOResult] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    def as_dict(self) -> dict:
        return {"ok": self.ok,
                "results": [r.as_dict() for r in self.results]}

    def render(self) -> str:
        lines = []
        for r in self.results:
            status = "OK " if r.ok else "FAIL"
            obs = ("n/a" if r.observed_s is None
                   else f"{r.observed_s * 1e3:.1f}ms")
            lines.append(
                f"[{status}] {r.spec.name}: p{r.spec.p:g} = {obs} "
                f"(target {r.spec.target_s * 1e3:.1f}ms, n={r.n})")
        return "\n".join(lines)


def _leg_samples(timelines: dict, leg: tuple, since: float | None) -> list:
    out = []
    for tl in timelines.values():
        for a, b in zip(tl, tl[1:]):
            if a[0] == leg[0] and b[0] == leg[1] \
                    and (since is None or b[1] >= since):
                out.append(b[1] - a[1])
    return out


def _eval_one(spec: SLOSpec, samples: list) -> SLOResult:
    """THE per-spec evaluation semantics (vacuous below min_samples,
    nearest-rank, ≤ target) — shared by evaluate() and
    evaluate_samples() so the two can never diverge."""
    n = len(samples)
    if n < spec.min_samples:
        return SLOResult(spec, n, None, True)
    obs = quantile_nearest_rank(samples, spec.p)
    return SLOResult(spec, n, obs, obs <= spec.target_s)


def evaluate_samples(specs, samples: list) -> SLOReport:
    """Evaluate specs against one pre-collected sample list (swarmbench's
    client-side latencies; every spec reads the same samples)."""
    report = SLOReport()
    for spec in specs:
        report.results.append(_eval_one(spec, samples))
    return report


def evaluate(specs, rec, since: float | None = None) -> SLOReport:
    """Evaluate specs against a LifecycleRecorder's exact samples.
    `since` restricts to legs/startups whose COMPLETING record landed at
    or after that wall-clock time — the recovery-SLO window."""
    timelines = None
    report = SLOReport()
    for spec in specs:
        if spec.metric == "startup":
            samples = rec.startup_samples(since=since)
        else:
            if timelines is None:
                timelines = rec.timelines()
            samples = _leg_samples(timelines, tuple(spec.metric), since)
        report.results.append(_eval_one(spec, samples))
    return report


def evaluate_histograms(specs) -> SLOReport:
    """Evaluate specs against the derived /metrics histograms (no
    recorder needed — what an operator's alerting would do; estimates
    are bucket upper bounds, so only conservative failures)."""
    from . import lifecycle

    report = SLOReport()
    for spec in specs:
        if spec.metric == "startup":
            hist = lifecycle.startup_histogram()
        else:
            hist = lifecycle.transition_family().child(tuple(spec.metric))
        _counts, _total, n = hist.snapshot()
        if n < spec.min_samples:
            report.results.append(SLOResult(spec, n, None, True))
            continue
        obs = histogram_quantile(hist, spec.p)
        report.results.append(
            SLOResult(spec, n, obs,
                      obs is not None and obs <= spec.target_s))
    return report


# --------------------------------------------------------- attribution
RUNNING = TaskState.RUNNING.name
NEW = TaskState.NEW.name


def attribution(rec, since: float | None = None,
                tolerance: float = 1e-6) -> dict:
    """Stage-attribution report over COMPLETE timelines (NEW first,
    RUNNING reached): per-leg {n, total_s, mean_s, p50_s, p99_s, share}
    plus the reconciliation self-check — summed leg seconds must equal
    summed e2e seconds within `tolerance` (relative). Legs PAST the
    RUNNING record (failure/teardown) are excluded: attribution explains
    startup latency only."""
    legs: dict[tuple[str, str], list[float]] = {}
    e2e: list[float] = []
    for tl in rec.timelines().values():
        if not tl or tl[0][0] != NEW:
            continue
        # the startup prefix: everything through the RUNNING record
        idx = next((i for i, e in enumerate(tl) if e[0] == RUNNING), None)
        if idx is None:
            continue
        if since is not None and tl[idx][1] < since:
            continue
        e2e.append(tl[idx][1] - tl[0][1])
        for a, b in zip(tl[:idx], tl[1:idx + 1]):
            legs.setdefault((a[0], b[0]), []).append(b[1] - a[1])
    total_e2e = sum(e2e)
    total_legs = sum(sum(ds) for ds in legs.values())
    reconciled = (abs(total_legs - total_e2e)
                  <= max(tolerance * max(total_e2e, total_legs), 1e-9))

    def leg_stats(ds):
        qs = quantiles_nearest_rank(ds, (50, 99))
        return {
            "n": len(ds),
            "total_s": round(sum(ds), 6),
            "mean_s": round(sum(ds) / len(ds), 6),
            "p50_s": round(qs[50], 6),
            "p99_s": round(qs[99], 6),
            "share": round(sum(ds) / total_e2e, 4) if total_e2e else None,
        }

    stages = {f"{a}->{b}": leg_stats(ds)
              for (a, b), ds in sorted(legs.items(),
                                       key=lambda kv: -sum(kv[1]))}
    e2e_qs = quantiles_nearest_rank(e2e, (50, 99))
    return {
        "tasks": len(e2e),
        "e2e": {
            "total_s": round(total_e2e, 6),
            "mean_s": round(total_e2e / len(e2e), 6) if e2e else None,
            "p50_s": (round(e2e_qs[50], 6) if e2e else None),
            "p99_s": (round(e2e_qs[99], 6) if e2e else None),
        },
        "stages": stages,
        "stage_total_s": round(total_legs, 6),
        "reconciled": reconciled,
    }


def report(rec, since: float | None = None) -> dict:
    """The canonical SLO snapshot dict over a LifecycleRecorder — the
    ONE report builder behind `control.get_slo_report` and the
    debugserver's `/debug/slo` (which extends it with its
    histogram-estimate/transition extras); `{"armed": False}` when
    `rec` is None."""
    if rec is None:
        return {"armed": False}
    samples = rec.startup_samples(since=since)
    qs = quantiles_nearest_rank(samples, (50, 90, 99))
    return {
        "armed": True,
        "tasks": len(rec),
        "records": rec.records,
        "startup": {
            "n": len(samples),
            "p50_s": qs[50],
            "p90_s": qs[90],
            "p99_s": qs[99],
        },
        "attribution": attribution(rec, since=since),
    }


def parse_slo_arg(spec: str, metric="startup") -> list[SLOSpec]:
    """Parse the CLI `--slo "p50:0.5,p99:2.0"` form into specs (seconds
    targets; swarmbench and the soak share it)."""
    specs = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part or not part.lower().startswith("p"):
            raise ValueError(f"bad SLO spec {part!r} (want pNN:seconds)")
        p_s, target_s = part.split(":", 1)
        specs.append(SLOSpec(name=f"startup_{p_s.lower()}",
                             p=float(p_s[1:]), target_s=float(target_s),
                             metric=metric))
    return specs
