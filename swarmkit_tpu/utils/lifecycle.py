"""Task lifecycle SLO plane: per-task state-transition timelines.

The reference measures time-to-RUNNING with an external polling tool
(cmd/swarm-bench `collector.go`) — containers phone home over UDP and a
client computes percentiles. That signal is exactly what a production
SLO needs (p50/p99 NEW→RUNNING, recovery-after-fault), but polling
cannot attribute WHERE the time went: orchestrator create → allocator
PENDING → scheduler wave commit → dispatcher ship → agent RUNNING each
own a slice, and the trace plane (utils/trace.py) only times the stages
themselves, never a given task's path through them. This module makes
the task lifecycle a first-class observability plane: a per-task
timeline of (stage, t) entries recorded at the decision boundaries that
already write task state, from which

  * `task_transition_seconds{from,to}` — a HistogramFamily of per-leg
    latencies (every consecutive timeline pair), and
  * `task_startup_seconds` — the end-to-end NEW→RUNNING histogram

are derived into the /metrics exposition, `/debug/slo` and
`/debug/tasks?id=` serve timelines from the debugserver, and
`utils/slo.py` evaluates declarative SLO specs against the data.

Cost contract — identical to utils/failpoints.py and utils/trace.py:
DISARMED, every record site costs ONE module-global truthiness test
(`lifecycle._REC is None`) and never constructs a timeline entry, takes
a lock, or builds a list. Sites that must assemble an id list first
guard the assembly with `lifecycle.enabled()`. The conftest fails any
test that leaks an armed recorder; the bench `slo_plane` row pins
`disarmed_record_allocs == 0` on the steady wave and dispatcher flush
paths.

Batching discipline: the scheduler's record site is ONE
`record_batch()` call per wave covering every placed task — never a
per-task call inside the commit walk; the dispatcher's status flush
files every written status in ONE `record_pairs()` call; the
dispatcher's ship site files one batch per served session. The
span-in-loop lint rule (analysis/lint.py) enforces the guarded pattern
for any `lifecycle.*` call inside a loop body in the audited hot
modules.

Timeline taxonomy and SLO spec format are documented in
docs/observability.md.
"""
from __future__ import annotations

import os
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Iterable

from ..analysis.lockgraph import make_lock
from ..api.types import TaskState

_REG_LOCK = make_lock('utils.lifecycle.REG_LOCK')
# The armed recorder, or None. Replaced wholesale on arm/disarm so hot
# sites read it without a lock; the disarmed fast path everywhere is
# `if _REC is None: return`.
_REC: "LifecycleRecorder | None" = None

DEFAULT_CAPACITY = 16384

# Synthetic stage: the dispatcher delivered the task's assignment to its
# node's agent. Not a TaskState — the store never sees it — but it is
# the decision boundary that splits "scheduler committed" from "agent
# acted", which is exactly the attribution an SLO burn needs.
SHIPPED = "SHIPPED"

# Stage ordering: TaskState's monotonic ranks, with SHIPPED slotted
# between ASSIGNED (the scheduler committed the placement) and ACCEPTED
# (the agent took it). Timelines reject non-advancing records — a
# re-ship after a version bump, a repeated RUNNING report, or an
# out-of-order arrival never pollutes the transition histograms.
STAGE_RANK: dict[str, int] = {s.name: int(s) for s in TaskState}
STAGE_RANK[SHIPPED] = int(TaskState.ASSIGNED) + 1


def _stage_name(stage) -> str:
    # accepts TaskState members, their ints, and plain stage strings
    if isinstance(stage, TaskState):
        return stage.name
    if isinstance(stage, int):
        try:
            return TaskState(stage).name
        except ValueError:
            return str(stage)
    return str(stage)


class LifecycleRecorder:
    """Bounded map of task id -> timeline (list of (stage, t) pairs).

    `capacity` bounds the number of TASKS tracked; when full, the
    oldest-inserted timeline is evicted (FIFO — under churn the old
    tasks are the retired ones; a long-stuck task re-enters the map on
    its next record, with its NEW lost, and simply stops contributing
    startup samples). Records arrive from many threads (orchestrator
    txs, the scheduler's CommitWorker, the dispatcher flush loop), so
    every mutation serializes under one lock; the timestamp for a batch
    is taken ONCE.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock=None):
        self.capacity = max(16, int(capacity))
        self.clock = clock
        self._lock = make_lock('utils.lifecycle.recorder')
        # task id -> list[(stage, t)]; OrderedDict for FIFO eviction
        self._timelines: "OrderedDict[str, list]" = OrderedDict()
        self.records = 0          # timeline entries appended
        self.batches = 0          # record_batch/record_pairs calls filed
        self.rejected = 0         # non-advancing records dropped
        self.evicted = 0          # timelines that fell off the map

    # ------------------------------------------------------------- writing
    def _now(self) -> float:
        return self.clock.time() if self.clock is not None else time.time()

    def _append(self, task_id: str, stage: str, t: float) -> None:
        """Append under self._lock (caller holds it). Non-advancing
        stages (rank <= last rank) are dropped: timelines mirror the
        task state machine's monotonicity, so re-ships and repeated
        status reports never create phantom transitions."""
        tl = self._timelines.get(task_id)
        if tl is None:
            if len(self._timelines) >= self.capacity:
                self._timelines.popitem(last=False)
                self.evicted += 1
            tl = []
            self._timelines[task_id] = tl
        if tl:
            last_rank = STAGE_RANK.get(tl[-1][0], -1)
            if STAGE_RANK.get(stage, last_rank + 1) <= last_rank:
                self.rejected += 1
                return
        tl.append((stage, t))
        self.records += 1
        if _REC is self:
            # a record landing in a RETIRED recorder (site read _REC just
            # before a disarm) keeps its timeline for forensics but must
            # not grow the process-global histograms — those populate
            # only while armed (the trace-plane rule)
            self._observe(tl, stage, t)

    @staticmethod
    def _observe(tl: list, stage: str, t: float) -> None:
        prev_stage, prev_t = tl[-2] if len(tl) >= 2 else (None, 0.0)
        if prev_stage is not None:
            _transition_family().observe((prev_stage, stage),
                                         max(0.0, t - prev_t))
        if stage == TaskState.RUNNING.name:
            t0 = next((e[1] for e in tl if e[0] == TaskState.NEW.name),
                      None)
            if t0 is not None:
                _startup_histogram().observe(max(0.0, t - t0))

    def record(self, task_id: str, stage, t: float | None = None) -> None:
        stage = _stage_name(stage)
        with self._lock:
            self._append(task_id, stage, self._now() if t is None else t)

    def record_batch(self, stage, task_ids: Iterable[str],
                     t: float | None = None) -> None:
        """One stage for many tasks — the scheduler's one-call-per-wave
        shape. One lock hold, one timestamp for the whole batch."""
        stage = _stage_name(stage)
        with self._lock:
            now = self._now() if t is None else t
            self.batches += 1
            for task_id in task_ids:
                self._append(task_id, stage, now)

    def record_pairs(self, pairs: Iterable[tuple],
                     t: float | None = None) -> None:
        """Mixed (task_id, stage) pairs in one call — the dispatcher's
        status-flush shape (a flush writes RUNNING for some tasks,
        FAILED for others)."""
        with self._lock:
            now = self._now() if t is None else t
            self.batches += 1
            for task_id, stage in pairs:
                self._append(task_id, _stage_name(stage), now)

    # ------------------------------------------------------------- reading
    def timeline(self, task_id: str) -> list[tuple[str, float]]:
        with self._lock:
            return list(self._timelines.get(task_id, ()))

    def timelines(self) -> dict[str, list]:
        """Snapshot of every tracked timeline (copies — safe to iterate
        while records keep landing)."""
        with self._lock:
            return {tid: list(tl) for tid, tl in self._timelines.items()}

    def task_ids(self) -> list[str]:
        with self._lock:
            return list(self._timelines)

    def __len__(self) -> int:
        with self._lock:
            return len(self._timelines)

    def startup_samples(self, since: float | None = None) -> list[float]:
        """NEW→RUNNING seconds for every task whose timeline holds both
        endpoints; `since` keeps tasks whose RUNNING landed at/after that
        wall-clock time (recovery-SLO windows)."""
        out = []
        with self._lock:
            for tl in self._timelines.values():
                t0 = t1 = None
                for stage, t in tl:
                    if stage == TaskState.NEW.name:
                        t0 = t
                    elif stage == TaskState.RUNNING.name:
                        t1 = t
                        break
                if t0 is not None and t1 is not None \
                        and (since is None or t1 >= since):
                    out.append(t1 - t0)
        return out

    def stage_census(self) -> dict[str, int]:
        """Latest-stage census over tracked tasks ({stage: count}) —
        the telemetry plane's task-state gauge set (one lock hold, no
        timeline copies)."""
        out: dict[str, int] = {}
        with self._lock:
            for tl in self._timelines.values():
                if tl:
                    stage = tl[-1][0]
                    out[stage] = out.get(stage, 0) + 1
        return out

    def transition_counts(self) -> dict[tuple[str, str], int]:
        counts: dict[tuple[str, str], int] = {}
        with self._lock:
            for tl in self._timelines.values():
                for a, b in zip(tl, tl[1:]):
                    key = (a[0], b[0])
                    counts[key] = counts.get(key, 0) + 1
        return counts

    def stuck_tasks(self, older_than: float = 0.0) -> list[tuple]:
        """(task_id, last_stage, age_s, timeline) for every task whose
        latest stage is non-terminal and short of RUNNING — the
        chaos-failure forensics payload (dumped next to CHAOS_SEED)."""
        now = self._now()
        out = []
        with self._lock:
            for tid, tl in self._timelines.items():
                if not tl:
                    continue
                stage, t = tl[-1]
                rank = STAGE_RANK.get(stage, 0)
                if rank >= int(TaskState.RUNNING):
                    continue
                age = now - t
                if age >= older_than:
                    out.append((tid, stage, age, list(tl)))
        out.sort(key=lambda r: -r[2])
        return out

    def stuck_text(self, n: int = 16, older_than: float = 0.0) -> str:
        """Human-readable stuck-task tails, oldest first — what the
        chaos harness prints under CHAOS_SEED."""
        lines = []
        for tid, stage, age, tl in self.stuck_tasks(older_than)[:n]:
            path = " -> ".join(
                f"{s}@{t - tl[0][1]:+.3f}s" for s, t in tl)
            lines.append(f"task {tid} stuck at {stage} for {age:.3f}s: "
                         f"{path}")
        return "\n".join(lines)


# ------------------------------------------------- derived metric families
# resolved lazily at first armed observation so importing this module
# registers nothing (the trace-plane rule for derived families)
_FAMILIES: dict[str, Any] = {}


def _transition_family():
    fam = _FAMILIES.get("transition")
    if fam is None:
        from . import metrics

        fam = metrics.histogram_family(
            "task_transition_seconds",
            "Per-task lifecycle transition latency, derived from the "
            "lifecycle timeline recorder (armed only)",
            ("from", "to"))
        _FAMILIES["transition"] = fam
    return fam


def _startup_histogram():
    h = _FAMILIES.get("startup")
    if h is None:
        from . import metrics

        h = metrics.histogram(
            "task_startup_seconds",
            "End-to-end NEW->RUNNING task startup latency, derived from "
            "the lifecycle timeline recorder (armed only)")
        _FAMILIES["startup"] = h
    return h


def startup_histogram():
    """The e2e histogram (creating it if needed) — the read surface for
    /debug/slo and SLO evaluation against /metrics data."""
    return _startup_histogram()


def transition_family():
    return _transition_family()


# ------------------------------------------------------------------ sites
def enabled() -> bool:
    return _REC is not None


def record(task_id: str, stage, t: float | None = None) -> None:
    """Record one task's stage crossing. Disarmed: one truthiness test,
    nothing else."""
    r = _REC
    if r is None:
        return
    r.record(task_id, stage, t=t)


def record_batch(stage, task_ids, t: float | None = None) -> None:
    """One stage, many tasks, ONE call — the per-wave shape. Callers
    that must first assemble `task_ids` guard the assembly with
    `lifecycle.enabled()` so the disarmed path allocates nothing."""
    r = _REC
    if r is None:
        return
    r.record_batch(stage, task_ids, t=t)


def record_pairs(pairs, t: float | None = None) -> None:
    """Mixed (task_id, stage) pairs, ONE call — the status-flush shape."""
    r = _REC
    if r is None:
        return
    r.record_pairs(pairs, t=t)


# ----------------------------------------------------------------- arming
def arm(capacity: int = DEFAULT_CAPACITY, clock=None) -> LifecycleRecorder:
    """Arm the lifecycle plane (idempotent re-arm replaces the
    recorder)."""
    global _REC
    r = LifecycleRecorder(capacity=capacity, clock=clock)
    with _REG_LOCK:
        _REC = r
    return r


def disarm() -> None:
    global _REC
    with _REG_LOCK:
        _REC = None


def active() -> bool:
    return _REC is not None


def recorder() -> LifecycleRecorder | None:
    return _REC


@contextmanager
def armed(capacity: int = DEFAULT_CAPACITY, clock=None):
    """`with lifecycle.armed() as rec: ...` — the per-test arming
    surface; always disarms on exit (the conftest guard fails leaks)."""
    r = arm(capacity=capacity, clock=clock)
    try:
        yield r
    finally:
        disarm()


def stuck_text(n: int = 16, older_than: float = 0.0) -> str:
    """Forensics helper: stuck-task timeline tails from the armed
    recorder, or "" when disarmed — the chaos harness prints it next to
    CHAOS_SEED and the flight-recorder tail without caring whether the
    plane is on."""
    r = _REC
    return r.stuck_text(n, older_than=older_than) if r is not None else ""


# ---------------------------------------------------------------- env var
# SWARMKIT_TPU_LIFECYCLE arms the recorder in subprocesses (multi-process
# swarmd tests, live-daemon SLO capture): "1" or a task capacity.
_ENV_VAR = "SWARMKIT_TPU_LIFECYCLE"

_env_val = os.environ.get(_ENV_VAR, "").strip().lower()
if _env_val and _env_val not in ("0", "false", "off", "no"):
    try:
        _cap = int(_env_val)
    except ValueError:
        _cap = DEFAULT_CAPACITY
    arm(capacity=_cap if _cap > 1 else DEFAULT_CAPACITY)
