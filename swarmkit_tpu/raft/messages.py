"""Raft message and log-entry types.

Mirrors the semantic content of raftpb messages the reference streams over
gRPC (api/raft.proto, manager/state/raft/transport/): vote, append, snapshot
installation, plus configuration-change entries. Entries carry opaque
`data` — for this framework, a serialized changelist of StoreActions.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# entry kinds
ENTRY_NORMAL = 0
ENTRY_CONF_CHANGE = 1

# canonical demotion markers for propose-callback error strings. The
# callback protocol carries (ok, err_string); RaftNode builds demotion
# errors FROM these constants and RaftProposer classifies errors BY them
# (raising LeadershipLost), so rewording one site can't silently break
# the clean-shutdown signal leader-only components rely on.
ERR_NOT_LEADER = "not leader"
ERR_LEADERSHIP_LOST = "leadership lost"


class MemberRemovedError(Exception):
    """Typed marker a peer answers raft.step with when the SENDER was
    removed from the cluster (reference membership ErrMemberRemoved).
    Registered with the RPC error registry, so the sender's transport can
    match on the TYPE — a coincidental substring in some other peer error
    must never self-demote a node (ADVICE r03)."""


@dataclass
class Entry:
    term: int
    index: int
    kind: int = ENTRY_NORMAL
    data: Any = None
    request_id: str = ""  # correlates proposals with wait callbacks
    # trace-plane context (utils/trace.py): the (trace_id, span_id) of
    # the originating proposal's span, or None when tracing was off at
    # propose time. Rides replication (AppendEntries) and the WAL via
    # the ordinary codec path, so a follower's fsync/apply spans join
    # the leader-side trace; pre-trace WAL records decode with the
    # default. Never interpreted by consensus.
    trace: Any = None


@dataclass
class ConfChange:
    action: str        # "add" | "remove"
    raft_id: int
    node_id: str = ""  # cluster member identity (cert CN)
    addr: str = ""


@dataclass
class Message:
    frm: int = 0
    to: int = 0
    term: int = 0
    kind: str = ""     # vote_req | vote_resp | append | append_resp | snapshot


@dataclass
class VoteRequest(Message):
    last_log_index: int = 0
    last_log_term: int = 0
    # set on leadership-transfer campaigns (etcd campaignTransfer): the
    # vote must bypass peers' leader leases, which otherwise ignore
    # disruptive campaigns while a leader is live (CheckQuorum's lease)
    transfer: bool = False
    # pre-vote poll (raft §9.6): term is the PROSPECTIVE term (current+1);
    # granting changes no persistent state on either side
    pre: bool = False
    kind: str = "vote_req"


@dataclass
class VoteResponse(Message):
    granted: bool = False
    pre: bool = False
    kind: str = "vote_resp"


@dataclass
class AppendEntries(Message):
    prev_log_index: int = 0
    prev_log_term: int = 0
    entries: list[Entry] = field(default_factory=list)
    leader_commit: int = 0
    # read-lease grant (ISSUE 13, Raft dissertation §6.4 lease reads):
    # seconds of read lease the leader extends with this append. A
    # follower holding a live lease may serve reads from a snapshot no
    # older than `leader_commit` (rpc/services.py routes streams there);
    # 0.0 = no grant (lease disabled, or sender not a signalled leader).
    # A RELATIVE ttl, never an absolute deadline: clocks are unsynced
    # across nodes — only bounded drift RATE is assumed, and the
    # follower additionally subtracts a skew margin (raft/node.py).
    lease_ttl: float = 0.0
    kind: str = "append"


@dataclass
class AppendResponse(Message):
    success: bool = False
    match_index: int = 0
    kind: str = "append_resp"


@dataclass
class InstallSnapshot(Message):
    snapshot_index: int = 0
    snapshot_term: int = 0
    members: dict[int, tuple[str, str]] = field(default_factory=dict)
    # ids of REMOVED members ride with the membership so a catcher-upper
    # learns them even when the conf changes were compacted away
    removed: list[int] = field(default_factory=list)
    data: Any = None
    kind: str = "snapshot"


@dataclass
class SnapshotChunk(Message):
    """One chunk of a streamed snapshot install (reference streams large
    raft messages: manager/state/raft/transport/peer.go:26-142). The
    payload is codec-serialized snapshot state split into fixed-size byte
    chunks; metadata rides on every chunk so reassembly needs no ordering
    handshake. The follower applies only when all `total` chunks for this
    (snapshot_index, term) arrived."""

    snapshot_index: int = 0
    snapshot_term: int = 0
    members: dict[int, tuple[str, str]] = field(default_factory=dict)
    removed: list[int] = field(default_factory=list)
    seq: int = 0
    total: int = 1
    chunk: bytes = b""
    kind: str = "snap_chunk"


@dataclass
class SnapshotAck(Message):
    """Follower → leader progress report for a streamed snapshot
    (recovery plane, ISSUE 18): `acked` is the highest CONTIGUOUS chunk
    seq the follower holds for `snapshot_index`. The leader re-arms the
    resend deadline on progress and, on expiry, re-sends ONLY the
    suffix past `acked` — never the whole blob. Ack loss is harmless:
    the state is monotone and the next chunk re-acks."""

    snapshot_index: int = 0
    acked: int = -1
    kind: str = "snap_ack"


@dataclass
class TimeoutNow(Message):
    """Leadership transfer (raft §3.10 / etcd MsgTimeoutNow): the leader
    tells its most caught-up peer to campaign immediately; the new term
    deposes the sender (used by the wedge monitor, raft.go:589-606)."""

    kind: str = "timeout_now"
