"""Raft consensus node.

A from-scratch re-derivation of the consensus behavior the reference gets
from vendored etcd/raft plus its own wrapper (manager/state/raft/raft.go):
leader election with randomized timeouts, log replication, commit-index
advancement by quorum match, snapshot install for lagging followers, single
-step membership changes, a wait registry correlating proposals with commit
callbacks (wait.go:8-77), and leadership-change notification that the
manager uses to start/stop leader-only components.

Architecture notes (tpu-first build):
  * step model: every input — network message, clock tick, proposal — is a
    queued event processed by one worker thread, so the core is single
    -threaded and deterministic under the fake-clock test harness
    (mirroring the reference's NodeOptions.ClockSource tier-2 strategy);
  * the batched commit math (quorum tally over a simulated manager mesh)
    also exists as the TPU kernel in ops/raft_replay.py — used for
    benchmark-scale log replay, while this class owns protocol correctness.
"""
from __future__ import annotations

import logging
import queue
import random
import threading
from dataclasses import dataclass
from typing import Any, Callable

from .messages import (
    ENTRY_CONF_CHANGE,
    ENTRY_NORMAL,
    ERR_LEADERSHIP_LOST,
    ERR_NOT_LEADER,
    AppendEntries,
    AppendResponse,
    ConfChange,
    Entry,
    InstallSnapshot,
    VoteRequest,
    VoteResponse,
)

log = logging.getLogger("swarmkit_tpu.raft")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

MAX_ENTRIES_PER_APPEND = 64


class NotLeader(Exception):
    def __init__(self, leader_id: int | None, leader_addr: str | None = None):
        super().__init__(f"not the leader (leader={leader_id})")
        self.leader_id = leader_id
        self.leader_addr = leader_addr


class ProposalDropped(Exception):
    pass


@dataclass
class Peer:
    raft_id: int
    node_id: str
    addr: str


class RaftNode:
    def __init__(
        self,
        raft_id: int,
        transport,
        storage=None,
        apply_entry: Callable[[Entry], None] | None = None,
        snapshot_state: Callable[[], Any] | None = None,
        restore_state: Callable[[Any], None] | None = None,
        on_leadership: Callable[[bool], None] | None = None,
        election_tick: int = 10,
        heartbeat_tick: int = 1,
        snapshot_interval: int = 1000,
        rng: random.Random | None = None,
        auto_recover: bool = True,
    ):
        self.id = raft_id
        self.transport = transport
        self.storage = storage
        self.apply_entry = apply_entry or (lambda e: None)
        self.snapshot_state = snapshot_state or (lambda: None)
        self.restore_state = restore_state or (lambda s: None)
        self.on_leadership = on_leadership or (lambda is_leader: None)
        # fired (from a fresh thread) when this node applies its OWN removal
        # from the membership — the reference surfaces this as
        # ErrMemberRemoved to node.superviseManager, which demotes
        self.on_removed: Callable[[], None] | None = None
        self.election_tick = election_tick
        self.heartbeat_tick = heartbeat_tick
        self.snapshot_interval = snapshot_interval
        self._rng = rng or random.Random()

        # persistent state
        self.term = 0
        self.voted_for: int | None = None
        self.log: list[Entry] = []
        self.first_index = 1          # index of log[0] (post-snapshot base)
        self.snapshot_index = 0
        self.snapshot_term = 0

        # volatile
        self.role = FOLLOWER
        self.leader_id: int | None = None
        self.commit_index = 0
        self.last_applied = 0
        self.members: dict[int, Peer] = {}
        self.next_index: dict[int, int] = {}
        self.match_index: dict[int, int] = {}
        self.votes: set[int] = set()
        self.election_elapsed = 0
        self.heartbeat_elapsed = 0
        self._randomized_timeout = self._next_timeout()

        self._waits: dict[str, Callable[[bool, str], None]] = {}
        self._inbox: queue.Queue = queue.Queue()
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

        # Signalled leadership (reference raft.go signalledLeadership +
        # :644-670 ordering): election alone does not make a usable leader —
        # the new term's no-op barrier entry must commit AND every earlier
        # -term entry must be applied first. Only then is leadership
        # announced and proposals accepted. Without this, leader-side
        # components start writing (taking the store update lock) while this
        # worker thread still needs that lock to apply the previous
        # leader's tail entries — a deadlock until the propose timeout.
        self._signalled = False
        self._barrier_index = 0

        self._recovered = False
        if auto_recover:
            self.recover()

    def recover(self):
        """Replay persisted state (WAL + snapshot). Callers that swap in
        apply_entry/restore_state after construction (e.g. RaftProposer)
        pass auto_recover=False and invoke this once wiring is complete —
        otherwise recovered entries would be applied into a void."""
        if self._recovered:
            return
        self._recovered = True
        if self.storage is not None:
            self._restore_from_storage()

    # ------------------------------------------------------------- lifecycle
    def start(self):
        self.recover()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"raft-{self.id}")
        self._thread.start()

    def stop(self):
        self._stopped.set()
        self._inbox.put(("stop",))
        if self._thread:
            self._thread.join(timeout=5)
        was_leader = self.role == LEADER
        self.role = FOLLOWER
        if was_leader:
            self._notify_leadership(False)

    def bootstrap(self, peers: list[Peer]):
        """Initialize a fresh cluster membership (first node, or test rig)."""
        for p in peers:
            self.members[p.raft_id] = p
        if self.storage is not None:
            self.storage.save_membership(self.members)

    # -------------------------------------------------------------- external
    def step(self, msg):
        """Feed a network message (thread-safe)."""
        self._inbox.put(("msg", msg))

    def tick(self):
        self._inbox.put(("tick",))

    def propose(self, data: Any, request_id: str,
                callback: Callable[[bool, str], None]):
        """Propose a normal entry; callback(ok, err) fires on commit (from
        the worker thread) or on drop."""
        self._inbox.put(("propose", data, request_id, callback))

    def propose_conf_change(self, cc: ConfChange, request_id: str,
                            callback: Callable[[bool, str], None]):
        self._inbox.put(("conf", cc, request_id, callback))

    def transfer_leadership(self):
        """Hand leadership to the most caught-up peer (wedged-store escape
        hatch, raft.go:589-606): send it TimeoutNow so it campaigns at once;
        its higher term deposes us. No-op unless we lead with peers."""
        self._inbox.put(("transfer",))

    def campaign(self):
        """Force an immediate election (tests / bootstrap)."""
        self._inbox.put(("campaign",))

    # -------------------------------------------------- node-id membership
    # (reference: manager/state/raft/membership/cluster.go keeps the
    # raft-id ↔ node-id registry; role manager addresses members by node id)

    def member_by_node_id(self, node_id: str) -> Peer | None:
        members = self.members  # snapshot: membership is copy-on-write
        for p in members.values():
            if p.node_id == node_id:
                return p
        return None

    def is_member(self, node_id: str) -> bool:
        return self.member_by_node_id(node_id) is not None

    def can_remove_member(self, node_id: str) -> bool:
        members = self.members  # snapshot: membership is copy-on-write
        peer = None
        for p in members.values():
            if p.node_id == node_id:
                peer = p
                break
        if peer is None:
            return True  # nothing to remove
        remaining = [p for p in members if p != peer.raft_id]
        if not remaining:
            return False
        reachable = sum(
            1 for p in remaining if p == self.id or self.transport.active(p)
        )
        return reachable >= len(remaining) // 2 + 1

    def remove_member_by_node_id(self, node_id: str, timeout: float = 10.0) -> bool:
        """Propose removal of the member with this node id, blocking until
        the conf change commits (reference raft.go Leave/RemoveMember)."""
        peer = self.member_by_node_id(node_id)
        if peer is None:
            return True
        done = threading.Event()
        result: dict[str, Any] = {}

        def cb(ok, err=""):
            result["ok"] = ok
            result["err"] = err
            done.set()

        from ..utils.identity import new_id as _new_id

        self.propose_conf_change(
            ConfChange(action="remove", raft_id=peer.raft_id, node_id=node_id),
            _new_id(),
            cb,
        )
        done.wait(timeout)
        return bool(result.get("ok"))

    # ------------------------------------------------------------ event loop
    def _run(self):
        while not self._stopped.is_set():
            try:
                item = self._inbox.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._dispatch(item)
            except Exception:
                log.exception("raft-%d: error processing %r", self.id, item[0])

    def process_all(self):
        """Drain the inbox synchronously (fake-clock tests drive this)."""
        while True:
            try:
                item = self._inbox.get_nowait()
            except queue.Empty:
                return
            self._dispatch(item)

    def _dispatch(self, item):
        kind = item[0]
        if kind == "msg":
            self._step(item[1])
        elif kind == "tick":
            self._on_tick()
        elif kind == "propose":
            self._on_propose(item[1], item[2], item[3])
        elif kind == "conf":
            self._on_conf_change(item[1], item[2], item[3])
        elif kind == "campaign":
            self._campaign()
        elif kind == "transfer":
            self._on_transfer()

    # ----------------------------------------------------------------- ticks
    def _next_timeout(self) -> int:
        return self.election_tick + self._rng.randrange(self.election_tick)

    def _on_tick(self):
        if self.role == LEADER:
            self.heartbeat_elapsed += 1
            if self.heartbeat_elapsed >= self.heartbeat_tick:
                self.heartbeat_elapsed = 0
                self._broadcast_append()
        else:
            self.election_elapsed += 1
            if self.election_elapsed >= self._randomized_timeout:
                self._campaign()

    # -------------------------------------------------------------- election
    def _campaign(self):
        if self.id not in self.members:
            # removed members must not start elections, and a freshly joined
            # node that has not yet learned the membership (empty config)
            # must not self-elect as a quorum-of-one
            return
        self.role = CANDIDATE
        self.term += 1
        self.voted_for = self.id
        self.votes = {self.id}
        self.leader_id = None
        self.election_elapsed = 0
        self._randomized_timeout = self._next_timeout()
        self._persist_hard_state()
        if self._quorum(len(self.votes)):
            self._become_leader()
            return
        for peer_id in self.members:
            if peer_id == self.id:
                continue
            self._send(VoteRequest(
                frm=self.id, to=peer_id, term=self.term,
                last_log_index=self._last_index(),
                last_log_term=self._last_term(),
            ))

    def _quorum(self, n: int) -> bool:
        voters = len(self.members) or 1
        return n >= voters // 2 + 1

    def _become_leader(self):
        self.role = LEADER
        self.leader_id = self.id
        self.heartbeat_elapsed = 0
        last = self._last_index()
        self.next_index = {p: last + 1 for p in self.members if p != self.id}
        self.match_index = {p: 0 for p in self.members if p != self.id}
        # commit a no-op entry from the new term so earlier-term entries can
        # commit (raft §5.4.2 safety rule); leadership is signalled only
        # once this barrier applies (_apply_committed)
        self._signalled = False
        self._barrier_index = last + 1
        self._append_local(Entry(term=self.term, index=last + 1,
                                 kind=ENTRY_NORMAL, data=None))
        self._broadcast_append()
        self._maybe_advance_commit()

    def _become_follower(self, term: int, leader_id: int | None):
        was_leader = self.role == LEADER
        was_signalled = self._signalled
        self._signalled = False
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_hard_state()
        self.role = FOLLOWER
        self.leader_id = leader_id
        self.election_elapsed = 0
        self._randomized_timeout = self._next_timeout()
        if was_leader:
            self._drop_waits(ERR_LEADERSHIP_LOST)
            if was_signalled:
                self._notify_leadership(False)

    def _notify_leadership(self, is_leader: bool):
        try:
            self.on_leadership(is_leader)
        except Exception:
            log.exception("raft-%d: leadership callback failed", self.id)

    # ------------------------------------------------------------------ step
    def _step(self, msg):
        if msg.term > self.term:
            self._become_follower(msg.term, getattr(msg, "frm", None)
                                  if msg.kind == "append" else None)
        handler = {
            "vote_req": self._on_vote_request,
            "vote_resp": self._on_vote_response,
            "append": self._on_append,
            "append_resp": self._on_append_response,
            "snapshot": self._on_install_snapshot,
            "timeout_now": self._on_timeout_now,
        }.get(msg.kind)
        if handler:
            handler(msg)

    def _on_timeout_now(self, msg):
        """Leadership-transfer target: campaign immediately (raft §3.10).
        Gated on the CURRENT term's leader — a delayed/replayed transfer
        from a deposed leader must not disrupt a healthy one (etcd gates
        MsgTimeoutNow the same way)."""
        if self.id in self.members and msg.term == self.term \
                and msg.frm == self.leader_id:
            self._campaign()

    def _on_transfer(self):
        from .messages import TimeoutNow

        if self.role != LEADER:
            return
        peers = [p for p in self.members if p != self.id]
        if not peers:
            return
        target = max(peers, key=lambda p: self.match_index.get(p, 0))
        self._send(TimeoutNow(frm=self.id, to=target, term=self.term))

    def _on_vote_request(self, msg: VoteRequest):
        grant = False
        if msg.term >= self.term:
            up_to_date = (msg.last_log_term, msg.last_log_index) >= (
                self._last_term(), self._last_index())
            not_voted = self.voted_for in (None, msg.frm)
            if up_to_date and not_voted and msg.term == self.term:
                grant = True
                self.voted_for = msg.frm
                self.election_elapsed = 0
                self._persist_hard_state()
        self._send(VoteResponse(frm=self.id, to=msg.frm, term=self.term,
                                granted=grant))

    def _on_vote_response(self, msg: VoteResponse):
        if self.role != CANDIDATE or msg.term != self.term:
            return
        if msg.granted:
            self.votes.add(msg.frm)
            if self._quorum(len(self.votes)):
                self._become_leader()

    def _on_append(self, msg: AppendEntries):
        if msg.term < self.term:
            self._send(AppendResponse(frm=self.id, to=msg.frm, term=self.term,
                                      success=False, match_index=0))
            return
        self.role = FOLLOWER
        self.leader_id = msg.frm
        self.election_elapsed = 0

        # prev entry check
        if msg.prev_log_index > 0:
            if msg.prev_log_index < self.snapshot_index:
                # already compacted; our snapshot covers it
                pass
            elif msg.prev_log_index > self._last_index() or (
                    self._term_at(msg.prev_log_index) != msg.prev_log_term):
                self._send(AppendResponse(
                    frm=self.id, to=msg.frm, term=self.term, success=False,
                    match_index=min(self._last_index(), msg.prev_log_index - 1)))
                return

        for e in msg.entries:
            if e.index <= self.snapshot_index:
                continue
            if e.index <= self._last_index():
                if self._term_at(e.index) != e.term:
                    # conflict: truncate from here
                    self.log = self.log[: e.index - self.first_index]
                    self._append_entry_storage_truncate(e.index)
                    self.log.append(e)
                    self._persist_entry(e)
            else:
                self.log.append(e)
                self._persist_entry(e)

        if msg.leader_commit > self.commit_index:
            self.commit_index = min(msg.leader_commit, self._last_index())
            self._apply_committed()

        self._send(AppendResponse(frm=self.id, to=msg.frm, term=self.term,
                                  success=True,
                                  match_index=self._last_index()))

    def _on_append_response(self, msg: AppendResponse):
        if self.role != LEADER or msg.term != self.term:
            return
        if msg.success:
            self.match_index[msg.frm] = max(
                self.match_index.get(msg.frm, 0), msg.match_index)
            self.next_index[msg.frm] = self.match_index[msg.frm] + 1
            self._maybe_advance_commit()
        else:
            # follower hinted how far behind it is
            self.next_index[msg.frm] = max(1, msg.match_index + 1)
            self._send_append_to(msg.frm)

    def _on_install_snapshot(self, msg: InstallSnapshot):
        if msg.term < self.term:
            return
        self.role = FOLLOWER
        self.leader_id = msg.frm
        self.election_elapsed = 0
        if msg.snapshot_index <= self.snapshot_index:
            return
        self.snapshot_index = msg.snapshot_index
        self.snapshot_term = msg.snapshot_term
        self.log = []
        self.first_index = msg.snapshot_index + 1
        self.commit_index = max(self.commit_index, msg.snapshot_index)
        self.last_applied = msg.snapshot_index
        self.members = {
            rid: Peer(rid, nid, addr)
            for rid, (nid, addr) in msg.members.items()
        }
        self.restore_state(msg.data)
        if self.storage is not None:
            self.storage.save_snapshot(
                msg.snapshot_index, msg.snapshot_term, msg.data, self.members)
        self._send(AppendResponse(frm=self.id, to=msg.frm, term=self.term,
                                  success=True, match_index=msg.snapshot_index))

    # ------------------------------------------------------------- proposing
    def _on_propose(self, data, request_id, callback):
        if self.role != LEADER or not self._signalled:
            # an unsignalled leader has unapplied earlier-term entries;
            # accepting a proposal now deadlocks the applier against the
            # proposer's store lock (raft.go processInternalRaftRequest
            # fails on !signalledLeadership for the same reason)
            callback(False, f"{ERR_NOT_LEADER}; leader is {self.leader_id}")
            return
        self._waits[request_id] = callback
        e = Entry(term=self.term, index=self._last_index() + 1,
                  kind=ENTRY_NORMAL, data=data, request_id=request_id)
        self._append_local(e)
        self._broadcast_append()
        self._maybe_advance_commit()  # single-node commits immediately

    def _on_conf_change(self, cc: ConfChange, request_id, callback):
        if self.role != LEADER or not self._signalled:
            callback(False, f"{ERR_NOT_LEADER}; leader is {self.leader_id}")
            return
        if cc.action == "remove" and not self._can_remove(cc.raft_id):
            callback(False, "removal would break quorum of reachable members")
            return
        self._waits[request_id] = callback
        e = Entry(term=self.term, index=self._last_index() + 1,
                  kind=ENTRY_CONF_CHANGE, data=cc, request_id=request_id)
        self._append_local(e)
        self._broadcast_append()
        self._maybe_advance_commit()

    def _can_remove(self, raft_id: int) -> bool:
        """reference raft.go:1170-1193 CanRemoveMember: removal must leave a
        reachable quorum."""
        remaining = [p for p in self.members if p != raft_id]
        if not remaining:
            return False
        reachable = sum(
            1 for p in remaining
            if p == self.id or self.transport.active(p))
        return reachable >= len(remaining) // 2 + 1

    def _drop_waits(self, reason: str):
        waits, self._waits = self._waits, {}
        for cb in waits.values():
            try:
                cb(False, reason)
            except Exception:
                pass

    # ------------------------------------------------------------ replication
    def _append_local(self, e: Entry):
        self.log.append(e)
        self._persist_entry(e)
        if self.role == LEADER:
            self._maybe_snapshot()

    def _broadcast_append(self):
        for peer_id in self.members:
            if peer_id != self.id:
                self._send_append_to(peer_id)

    def _send_append_to(self, peer_id: int):
        next_idx = self.next_index.get(peer_id, self._last_index() + 1)
        if next_idx <= self.snapshot_index:
            self._send(InstallSnapshot(
                frm=self.id, to=peer_id, term=self.term,
                snapshot_index=self.snapshot_index,
                snapshot_term=self.snapshot_term,
                members={rid: (p.node_id, p.addr)
                         for rid, p in self.members.items()},
                data=self.snapshot_state(),
            ))
            self.next_index[peer_id] = self.snapshot_index + 1
            return
        prev_index = next_idx - 1
        prev_term = self._term_at(prev_index) if prev_index > 0 else 0
        start = next_idx - self.first_index
        entries = self.log[start:start + MAX_ENTRIES_PER_APPEND]
        self._send(AppendEntries(
            frm=self.id, to=peer_id, term=self.term,
            prev_log_index=prev_index, prev_log_term=prev_term,
            entries=list(entries), leader_commit=self.commit_index,
        ))

    def _maybe_advance_commit(self):
        if self.role != LEADER:
            return
        matches = sorted(
            [self._last_index()]
            + [self.match_index.get(p, 0) for p in self.members if p != self.id],
            reverse=True,
        )
        voters = len(self.members) or 1
        quorum_match = matches[voters // 2] if voters > 1 else matches[0]
        # only commit entries from the current term directly (raft §5.4.2)
        if quorum_match > self.commit_index and \
                self._term_at(quorum_match) == self.term:
            self.commit_index = quorum_match
            self._apply_committed()
            self._broadcast_append()  # propagate the new commit index

    def _apply_committed(self):
        if self.last_applied < self.commit_index:
            # persist the advanced commit (etcd HardState semantics: term,
            # vote and commit survive restarts together)
            self._persist_hard_state()
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            idx = self.last_applied - self.first_index
            if idx < 0:
                continue  # covered by snapshot
            if idx >= len(self.log):
                # commit raced ahead of a truncated log; stop rather than crash
                self.last_applied -= 1
                break
            e = self.log[idx]
            if e.kind == ENTRY_CONF_CHANGE:
                self._apply_conf_change(e)
            elif e.data is not None:
                try:
                    self.apply_entry(e)
                except Exception:
                    log.exception("raft-%d: apply failed at %d", self.id, e.index)
            cb = self._waits.pop(e.request_id, None) if e.request_id else None
            if cb is not None:
                try:
                    cb(True, "")
                except Exception:
                    log.exception("raft-%d: wait callback failed", self.id)
        if self.role == LEADER and not self._signalled \
                and self.last_applied >= self._barrier_index:
            # the new-term barrier (and everything before it) is applied:
            # leadership is now usable (raft.go:644-670 ordering)
            self._signalled = True
            self._notify_leadership(True)
        self._maybe_snapshot()

    def _apply_conf_change(self, e: Entry):
        # membership is updated copy-on-write: cross-thread readers (role
        # manager via member_by_node_id/can_remove_member) snapshot the dict
        # reference and iterate safely without locks
        cc: ConfChange = e.data
        if cc.action == "add":
            members = dict(self.members)
            members[cc.raft_id] = Peer(cc.raft_id, cc.node_id, cc.addr)
            self.members = members
            if self.role == LEADER and cc.raft_id != self.id:
                self.next_index.setdefault(cc.raft_id, self._last_index() + 1)
                self.match_index.setdefault(cc.raft_id, 0)
        elif cc.action == "remove":
            members = dict(self.members)
            members.pop(cc.raft_id, None)
            self.members = members
            self.next_index.pop(cc.raft_id, None)
            self.match_index.pop(cc.raft_id, None)
            if cc.raft_id == self.id:
                self._become_follower(self.term, None)
                if self.on_removed is not None:
                    # off-thread: the apply loop must not run teardown
                    threading.Thread(target=self.on_removed, daemon=True,
                                     name="raft-removed").start()
        if self.storage is not None:
            self.storage.save_membership(self.members)

    # -------------------------------------------------------------- snapshots
    def _maybe_snapshot(self):
        applied_in_log = self.last_applied - self.snapshot_index
        if applied_in_log < self.snapshot_interval:
            return
        data = self.snapshot_state()
        self.snapshot_term = self._term_at(self.last_applied)
        self.snapshot_index = self.last_applied
        keep_from = self.last_applied + 1 - self.first_index
        self.log = self.log[keep_from:]
        self.first_index = self.last_applied + 1
        if self.storage is not None:
            self.storage.save_snapshot(
                self.snapshot_index, self.snapshot_term, data, self.members)
            self.storage.compact(self.first_index)

    # ------------------------------------------------------------ persistence
    def _persist_hard_state(self):
        if self.storage is not None:
            self.storage.save_hard_state(self.term, self.voted_for,
                                         self.commit_index)

    def _persist_entry(self, e: Entry):
        if self.storage is not None:
            self.storage.append_entries([e])

    def _append_entry_storage_truncate(self, from_index: int):
        if self.storage is not None:
            self.storage.truncate_from(from_index)

    def _restore_from_storage(self):
        state = self.storage.load()
        if state is None:
            return
        self.term = state.term
        self.voted_for = state.voted_for
        self.snapshot_index = state.snapshot_index
        self.snapshot_term = state.snapshot_term
        self.first_index = state.snapshot_index + 1
        self.log = list(state.entries)
        self.members = dict(state.members)
        # a torn WAL tail (or undecryptable entries) can leave the persisted
        # commit ahead of the recovered log; cap it so replay can't index
        # past the entries we actually have
        self.commit_index = min(max(state.commit_index, state.snapshot_index),
                                self._last_index())
        self.last_applied = self.snapshot_index
        if state.snapshot_data is not None:
            self.restore_state(state.snapshot_data)
        self._apply_committed()

    # ----------------------------------------------------------------- helpers
    def _last_index(self) -> int:
        return self.first_index + len(self.log) - 1 if self.log else self.snapshot_index

    def _last_term(self) -> int:
        return self.log[-1].term if self.log else self.snapshot_term

    def _term_at(self, index: int) -> int:
        if index == self.snapshot_index:
            return self.snapshot_term
        i = index - self.first_index
        if 0 <= i < len(self.log):
            return self.log[i].term
        return -1

    def _send(self, msg):
        try:
            self.transport.send(msg)
        except Exception:
            log.debug("raft-%d: send to %d failed", self.id, msg.to)

    # ------------------------------------------------------------- introspect
    @property
    def is_leader(self) -> bool:
        """Usable leadership: elected AND the new-term barrier has applied
        (proposals before that point are rejected)."""
        return self.role == LEADER and self._signalled

    def status(self) -> dict:
        return {
            "id": self.id,
            "role": self.role,
            "term": self.term,
            "leader": self.leader_id,
            "commit": self.commit_index,
            "applied": self.last_applied,
            "last_index": self._last_index(),
            "members": {p.raft_id: p.addr for p in self.members.values()},
        }
