"""Raft consensus node.

A from-scratch re-derivation of the consensus behavior the reference gets
from vendored etcd/raft plus its own wrapper (manager/state/raft/raft.go):
leader election with randomized timeouts, log replication, commit-index
advancement by quorum match, snapshot install for lagging followers, single
-step membership changes, a wait registry correlating proposals with commit
callbacks (wait.go:8-77), and leadership-change notification that the
manager uses to start/stop leader-only components.

Architecture notes (tpu-first build):
  * step model: every input — network message, clock tick, proposal — is a
    queued event processed by one worker thread, so the core is single
    -threaded and deterministic under the fake-clock test harness
    (mirroring the reference's NodeOptions.ClockSource tier-2 strategy);
  * the batched commit math (quorum tally over a simulated manager mesh)
    also exists as the TPU kernel in ops/raft_replay.py — used for
    benchmark-scale log replay, while this class owns protocol correctness.
"""
from __future__ import annotations

import logging
import queue
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

from ..utils import failpoints, trace
from ..utils.metrics import counter_family

from .messages import (
    ENTRY_CONF_CHANGE,
    ENTRY_NORMAL,
    ERR_LEADERSHIP_LOST,
    ERR_NOT_LEADER,
    AppendEntries,
    AppendResponse,
    ConfChange,
    Entry,
    InstallSnapshot,
    SnapshotAck,
    SnapshotChunk,
    VoteRequest,
    VoteResponse,
)

log = logging.getLogger("swarmkit_tpu.raft")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

# group commit: the worker drains up to this many inbox items per loop
# iteration and performs ONE Ready flush for the whole batch — one WAL
# append + fsync, one dirty-gated hardstate save, one coalesced
# AppendEntries per peer, one commit-advance + apply pass (the
# Ready/Advance batching the reference gets from etcd/raft)
MAX_READY_BATCH = 256

MAX_ENTRIES_PER_APPEND = 64
# pipelined replication: optimistic appends may run this many messages
# ahead of the follower's last ack (reference MaxInflightMsgs: 256,
# manager/state/raft/raft.go:490); the per-peer entry window is
# MAX_INFLIGHT_APPENDS * MAX_ENTRIES_PER_APPEND
MAX_INFLIGHT_APPENDS = 256
# streamed snapshot installs (reference transport/peer.go:26-142 streams
# large messages instead of one oversized gRPC frame)
SNAPSHOT_CHUNK_BYTES = 256 * 1024
# ticks before an unacked streamed snapshot is re-sent (the follower may
# have lost chunks; until then the peer is paused, not re-blasted).
# Kept for back-compat derivation; the live deadline is CLOCK-based
# (SNAPSHOT_RESEND_SECONDS / the snapshot_resend_seconds ctor param) so
# chunk-loss schedules replay exactly under a FakeClock (ISSUE 18).
SNAPSHOT_RESEND_TICKS = 50
# seconds before an unacked streamed snapshot suffix is re-sent — the
# tick-count constant above at the daemon's 0.2 s tick cadence
SNAPSHOT_RESEND_SECONDS = SNAPSHOT_RESEND_TICKS * 0.2
# follower-side reassembly cap: a stream whose DECLARED size
# (total × chunk bytes) exceeds this is rejected outright — a buggy or
# deposed leader must not balloon follower memory with orphan chunk maps
SNAPSHOT_STREAM_MAX_BYTES = 1 << 30

# recovery-plane event counters (ISSUE 18): process-global family so the
# events ride registry_snapshot() into the PR 15 telemetry rollup; exact
# per-node assertions use the RaftNode snap_* ints instead
_snap_events = counter_family(
    "swarm_raft_snapshot_total",
    "streamed-snapshot recovery events (chunk sent/resent, suffix "
    "resume, chunk rejected, install)",
    ("event",))
# wedge-triggered leadership transfers are rate limited (reference
# raft.go:569-604 caps transfers at one per minute). Expressed in ticks
# so the deterministic fake-clock harness can drive expiry; at the
# daemon's 0.2 s tick this is one minute.
TRANSFER_MIN_TICKS = 300

# Read-lease clock-skew margin (ISSUE 13): a follower discounts every
# lease grant by this fraction before trusting it, so bounded clock-RATE
# drift between leader and follower cannot stretch a lease past the
# leader's guarantee window. 10% covers drift orders of magnitude worse
# than real hardware exhibits over a ~1 s lease.
READ_LEASE_SKEW = 0.1


class NotLeader(Exception):
    def __init__(self, leader_id: int | None, leader_addr: str | None = None):
        super().__init__(f"not the leader (leader={leader_id})")
        self.leader_id = leader_id
        self.leader_addr = leader_addr


class ProposalDropped(Exception):
    pass


@dataclass
class Peer:
    raft_id: int
    node_id: str
    addr: str


@dataclass
class _SnapPending:
    """Leader-side progress of one streamed snapshot install (etcd
    ProgressStateSnapshot analogue, resumable): `acked` is the highest
    CONTIGUOUS chunk seq the follower reported via SnapshotAck, and
    `deadline` (clock.monotonic seconds) is when an unacked stream gets
    its missing SUFFIX re-sent — never the whole blob."""

    snap_idx: int
    deadline: float
    acked: int = -1


class RaftNode:
    def __init__(
        self,
        raft_id: int,
        transport,
        storage=None,
        apply_entry: Callable[[Entry], None] | None = None,
        snapshot_state: Callable[[], Any] | None = None,
        restore_state: Callable[[Any], None] | None = None,
        on_leadership: Callable[[bool], None] | None = None,
        election_tick: int = 10,
        heartbeat_tick: int = 1,
        snapshot_interval: int = 1000,
        rng: random.Random | None = None,
        auto_recover: bool = True,
        lease_duration: float = 0.0,
        clock=None,
        snapshot_resend_seconds: float = SNAPSHOT_RESEND_SECONDS,
        snap_stream_max_bytes: int = SNAPSHOT_STREAM_MAX_BYTES,
    ):
        self.id = raft_id
        self.transport = transport
        self.storage = storage
        self.apply_entry = apply_entry or (lambda e: None)
        self.snapshot_state = snapshot_state or (lambda: None)
        self.restore_state = restore_state or (lambda s: None)
        self.on_leadership = on_leadership or (lambda is_leader: None)
        # fired (from a fresh thread) when this node learns of its OWN
        # removal from the membership — by applying the conf change, or
        # from a peer's removed-member reply (notify_removed). The
        # reference surfaces this as ErrMemberRemoved to
        # node.superviseManager, which demotes
        self.on_removed: Callable[[], None] | None = None
        # raft ids of members REMOVED from this cluster: peers answer
        # their messages with the removed marker so a member demoted
        # while down learns its fate when it comes back
        # (reference manager/state/raft/membership ErrMemberRemoved)
        self.removed_ids: set[int] = set()
        self._self_removed = False
        self.election_tick = election_tick
        self.heartbeat_tick = heartbeat_tick
        self.snapshot_interval = snapshot_interval
        self._rng = rng or random.Random()

        # persistent state
        self.term = 0
        self.voted_for: int | None = None
        self.log: list[Entry] = []
        self.first_index = 1          # index of log[0] (post-snapshot base)
        self.snapshot_index = 0
        self.snapshot_term = 0

        # volatile
        self.role = FOLLOWER
        self.leader_id: int | None = None
        self.commit_index = 0
        self.last_applied = 0
        self.members: dict[int, Peer] = {}
        self.next_index: dict[int, int] = {}
        self.match_index: dict[int, int] = {}
        self.votes: set[int] = set()
        self.election_elapsed = 0
        self.heartbeat_elapsed = 0
        self._randomized_timeout = self._next_timeout()

        self._waits: dict[str, Callable[[bool, str], None]] = {}
        self._inbox: queue.Queue = queue.Queue()
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

        # CheckQuorum leader lease (reference raft.go:237 CheckQuorum):
        # a leader that hears from fewer than a quorum of peers within one
        # election timeout steps down instead of accepting work while
        # partitioned. _recent_active records peers that responded since
        # the last lease checkpoint.
        self.check_quorum = True
        self._quorum_elapsed = 0
        self._recent_active: set[int] = set()

        # Read lease (ISSUE 13; Raft dissertation §6.4 lease/ReadIndex):
        # the leader piggybacks `lease_duration` seconds of read lease +
        # its commit index on every AppendEntries; a follower may serve
        # BOUNDED-STALENESS reads (snapshot no older than that commit
        # index) while its discounted lease is live. Soundness rides two
        # legs: the CheckQuorum vote-withholding half (followers that
        # heard this leader within election_tick ignore campaigns — the
        # operator, i.e. the daemon wiring, must keep lease_duration
        # BELOW election_tick × tick_interval), and QUORUM-ANCHORED
        # granting (_lease_ttl: grants shrink as the leader's last
        # observed quorum contact ages, so a minority-partitioned
        # leader stops extending leases at once instead of until its
        # CheckQuorum step-down). 0.0 disables granting entirely.
        # Leader-side state is worker-thread-only; the follower-side
        # triple below is written by the worker and read lock-free by
        # RPC threads (plain floats/ints under the GIL). The _on_append
        # grant site orders the writes — deadline zeroed first on a term
        # change, written last on a grant, after the index — so a torn
        # read can only look like an expired lease or an over-strict
        # index, never a live lease gating on a stale index.
        from ..utils.clock import REAL_CLOCK

        self.lease_duration = lease_duration
        self.clock = clock or REAL_CLOCK
        self._read_lease_until = 0.0     # local monotonic deadline
        self._read_lease_index = 0       # leader commit at grant
        self._read_lease_term = -1       # grants die with their term
        # leader-side grant anchor: the last time THIS leader observed
        # responses from a quorum (see _lease_ttl — grants SHRINK as
        # quorum contact ages, so a partitioned leader stops extending
        # leases long before its CheckQuorum step-down fires)
        self._lease_quorum_contact = 0.0
        self._lease_acked: set[int] = set()

        # PreVote (raft §9.6 / etcd PreVote): an election-timeout node
        # first polls peers with a NON-disruptive pre-vote at term+1 —
        # only a pre-quorum starts a real campaign and bumps the term.
        # The reference leaves etcd's PreVote off and eats the inflated-
        # term disruption when a starved/partitioned node wakes up; under
        # CPU-starved hosts that wake-up churns elections, so this build
        # turns it on (deliberate robustness divergence). Leadership
        # transfers skip straight to a real campaign (etcd
        # campaignTransfer).
        self.pre_vote = True
        self._pre_votes: set[int] | None = None

        # streamed-snapshot pause state: peer -> _SnapPending; while set,
        # data appends to that peer are withheld (heartbeats still flow)
        # and stale failure hints ignored (etcd ProgressStateSnapshot
        # analogue). The resend deadline is CLOCK-based so chunk-loss
        # schedules replay deterministically under a FakeClock.
        self.snapshot_resend_seconds = snapshot_resend_seconds
        self.snap_stream_max_bytes = snap_stream_max_bytes
        self._snap_pending: dict[int, _SnapPending] = {}
        # follower-side chunk reassembly: (frm, snapshot_index) -> {seq: bytes}
        self._snap_chunks: dict[tuple[int, int], dict[int, bytes]] = {}
        # highest CONTIGUOUS seq held per reassembly buffer — what the
        # follower acks; pruned in lockstep with _snap_chunks
        self._snap_contig: dict[tuple[int, int], int] = {}
        # recovery-plane observability (worker-thread ints; status() and
        # the debugserver expose them, tests assert on them exactly)
        self.snap_chunks_sent = 0
        self.snap_chunks_resent = 0
        self.snap_resume_suffix = 0
        self.snap_chunks_rejected = 0
        self.snap_installs = 0
        self.snap_install_seconds = 0.0
        # per-peer count of unacked append messages — the pipelining
        # window; reset on rewind, decremented per response
        self._inflight: dict[int, int] = {}

        self.transfer_min_ticks = TRANSFER_MIN_TICKS
        self._transfer_cooldown = 0
        # leader-side cache of the serialized snapshot blob: re-streams of
        # the same snapshot_index must be byte-identical, or a follower
        # reassembling across two streams installs a state no leader had
        self._snap_blob: tuple[int, bytes] | None = None

        # Signalled leadership (reference raft.go signalledLeadership +
        # :644-670 ordering): election alone does not make a usable leader —
        # the new term's no-op barrier entry must commit AND every earlier
        # -term entry must be applied first. Only then is leadership
        # announced and proposals accepted. Without this, leader-side
        # components start writing (taking the store update lock) while this
        # worker thread still needs that lock to apply the previous
        # leader's tail entries — a deadlock until the propose timeout.
        self._signalled = False
        self._barrier_index = 0

        # ---- batched Ready plane (group commit) ----
        # entries appended since the last flush, persisted in ONE
        # append_entries call (one WAL write + one fsync for the batch)
        self._ready_entries: list[Entry] = []
        # term/vote/commit changed since the last flush (dirty-gated
        # save_hard_state, at most one per flush)
        self._hs_dirty = False
        # outgoing messages buffered until AFTER the flush persisted
        # entries + hard state: nothing leaves this node before the state
        # it claims is durable (votes/term bumps persist before any
        # message leaves — the raft durability contract)
        self._out_msgs: list = []
        # peers owed an AppendEntries this flush: peer -> allow_empty
        # (True once any requester allowed a heartbeat); coalesced to ONE
        # send_append per peer per flush
        self._append_dirty: dict[int, bool] = {}
        # flush observability (worker-thread ints; status() exposes them)
        self.ready_flushes = 0
        self.ready_items = 0
        self.commits_applied = 0

        # read-only degradation (ISSUE 3): an ENOSPC on the WAL demotes
        # this node to a follower that keeps serving reads/heartbeats
        # but REJECTS proposals, instead of crash-looping the worker.
        # A periodic storage probe (election_tick cadence) lifts the
        # degradation once the disk accepts durable writes again.
        self.storage_degraded = False
        self._degraded_elapsed = 0
        self.storage_errors = 0

        self._recovered = False
        if auto_recover:
            self.recover()

    def recover(self):
        """Replay persisted state (WAL + snapshot). Callers that swap in
        apply_entry/restore_state after construction (e.g. RaftProposer)
        pass auto_recover=False and invoke this once wiring is complete —
        otherwise recovered entries would be applied into a void."""
        if self._recovered:
            return
        self._recovered = True
        if self.storage is not None:
            self._restore_from_storage()
            self._flush_ready()   # replay marked hardstate dirty; settle it

    # ------------------------------------------------------------- lifecycle
    def start(self):
        self.recover()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"raft-{self.id}")
        self._thread.start()

    def stop(self):
        self._stopped.set()
        self._inbox.put(("stop",))
        if self._thread:
            self._thread.join(timeout=5)
        was_leader = self.role == LEADER
        self.role = FOLLOWER
        if was_leader:
            self._notify_leadership(False)

    def bootstrap(self, peers: list[Peer]):
        """Initialize a fresh cluster membership (first node, or test rig)."""
        for p in peers:
            self.members[p.raft_id] = p
        if self.storage is not None:
            self.storage.save_membership(self.members, self.removed_ids)

    # -------------------------------------------------------------- external
    def step(self, msg):
        """Feed a network message (thread-safe)."""
        self._inbox.put(("msg", msg))

    def tick(self):
        self._inbox.put(("tick",))

    def propose(self, data: Any, request_id: str,
                callback: Callable[[bool, str], None],
                trace_ctx=None):
        """Propose a normal entry; callback(ok, err) fires on commit (from
        the worker thread) or on drop. `trace_ctx` (optional) is the
        proposer's span context — it rides the staged Entry so the WAL
        fsync / commit / apply spans on every replica join the
        proposal's trace (utils/trace.py)."""
        self._inbox.put(("propose", data, request_id, callback, trace_ctx))

    def propose_conf_change(self, cc: ConfChange, request_id: str,
                            callback: Callable[[bool, str], None]):
        self._inbox.put(("conf", cc, request_id, callback))

    def transfer_leadership(self):
        """Hand leadership to the most caught-up peer (wedged-store escape
        hatch, raft.go:589-606): send it TimeoutNow so it campaigns at once;
        its higher term deposes us. No-op unless we lead with peers."""
        self._inbox.put(("transfer",))

    def notify_removed(self):
        """The transport learned from a peer that WE were removed from
        the membership (the peer's removed-member reply) — e.g. this
        member was demoted while down and restarted with a stale
        membership. Thread-safe."""
        self._inbox.put(("removed",))

    def campaign(self):
        """Force an immediate election (tests / bootstrap)."""
        self._inbox.put(("campaign",))

    # -------------------------------------------------- node-id membership
    # (reference: manager/state/raft/membership/cluster.go keeps the
    # raft-id ↔ node-id registry; role manager addresses members by node id)

    def member_by_node_id(self, node_id: str) -> Peer | None:
        members = self.members  # snapshot: membership is copy-on-write
        for p in members.values():
            if p.node_id == node_id:
                return p
        return None

    def is_member(self, node_id: str) -> bool:
        return self.member_by_node_id(node_id) is not None

    def can_remove_member(self, node_id: str) -> bool:
        members = self.members  # snapshot: membership is copy-on-write
        peer = None
        for p in members.values():
            if p.node_id == node_id:
                peer = p
                break
        if peer is None:
            return True  # nothing to remove
        remaining = [p for p in members if p != peer.raft_id]
        if not remaining:
            return False
        reachable = sum(
            1 for p in remaining if p == self.id or self.transport.active(p)
        )
        return reachable >= len(remaining) // 2 + 1

    def remove_member_by_node_id(self, node_id: str, timeout: float = 10.0) -> bool:
        """Propose removal of the member with this node id, blocking until
        the conf change commits (reference raft.go Leave/RemoveMember)."""
        peer = self.member_by_node_id(node_id)
        if peer is None:
            return True
        done = threading.Event()
        result: dict[str, Any] = {}

        def cb(ok, err=""):
            result["ok"] = ok
            result["err"] = err
            done.set()

        from ..utils.identity import new_id as _new_id

        self.propose_conf_change(
            ConfChange(action="remove", raft_id=peer.raft_id, node_id=node_id),
            _new_id(),
            cb,
        )
        done.wait(timeout)
        return bool(result.get("ok"))

    # ------------------------------------------------------------ event loop
    def _run(self):
        """Batched Ready loop: drain the inbox (bounded batch), dispatch
        every item, then perform ONE flush for the whole batch — the
        group-commit plane. Handlers only mutate volatile state and mark
        work (entries to persist, peers to append to, messages to send);
        `_flush_ready` is the single point where durability and the
        network happen."""
        while not self._stopped.is_set():
            try:
                item = self._inbox.get(timeout=0.2)
            except queue.Empty:
                continue
            batch = [item]
            while len(batch) < MAX_READY_BATCH:
                try:
                    batch.append(self._inbox.get_nowait())
                except queue.Empty:
                    break
            for it in batch:
                try:
                    self._dispatch(it)
                except Exception:
                    log.exception("raft-%d: error processing %r",
                                  self.id, it[0])
            try:
                self._flush_ready()
            except Exception:
                # unsent messages may claim durability the failed flush
                # never provided — drop them; raft retransmits
                self._out_msgs.clear()
                log.exception("raft-%d: ready flush failed", self.id)

    def process_all(self):
        """Drain the inbox synchronously (fake-clock tests drive this):
        the same dispatch-all-then-flush-once shape as the live worker."""
        processed = False
        while True:
            try:
                item = self._inbox.get_nowait()
            except queue.Empty:
                break
            processed = True
            self._dispatch(item)
        # flush pending ready state even when the inbox was empty: tests
        # drive handlers directly (e.g. _on_transfer) and their buffered
        # output must still reach the wire
        if processed or self._out_msgs or self._ready_entries \
                or self._hs_dirty or self._append_dirty:
            self._flush_ready()

    def _flush_ready(self):
        """The group-commit flush (etcd Ready/Advance analogue), in strict
        order: (1) persist the batch's entries — one WAL append, one
        fsync; (2) advance the commit frontier off the durable state and
        apply, firing wait callbacks in log order; (3) dirty-gated
        hardstate save — votes/term bumps/commit persist here, BEFORE any
        message leaves; (4) one coalesced AppendEntries per dirty peer;
        (5) release the buffered outgoing messages to the transport."""
        self.ready_flushes += 1
        # ready-loop trace guard: ONE module-global truthiness test when
        # disarmed (the bench/test acceptance); per-entry work below only
        # happens for entries that carry a trace ctx
        traced = trace.enabled()
        if self._ready_entries:
            if self.storage is not None:
                if traced:
                    _t0 = time.perf_counter()
                    _tctx = next((e.trace for e in self._ready_entries
                                  if e.trace is not None), None)
                    _n = len(self._ready_entries)
                try:
                    self.storage.append_entries(self._ready_entries)
                except OSError as exc:
                    self._on_append_failure(exc)
                    return
                if traced:
                    # one span per GROUP append (one WAL write + fsync),
                    # parented to the first traced entry so the fsync
                    # joins the proposal's causal trace; never per-entry
                    trace.rec("raft.wal_fsync",
                              time.perf_counter() - _t0, parent=_tctx,
                              node=self.id, entries=_n)
                if self.storage_degraded:
                    # the disk took a durable batch again: leave
                    # read-only mode (the follower catch-up path heals
                    # without waiting for the tick-driven probe)
                    self.storage_degraded = False
                    log.info("raft-%d: WAL writable again; leaving "
                             "read-only degradation", self.id)
            self._ready_entries = []
        self._maybe_advance_commit()
        self._apply_committed()
        if self._hs_dirty:
            if self.storage is not None:
                try:
                    self.storage.save_hard_state(self.term, self.voted_for,
                                                 self.commit_index)
                except OSError as exc:
                    # votes/term bumps are NOT durable: nothing buffered
                    # may leave (a granted vote without a persisted
                    # voted_for can elect two leaders across a restart)
                    self.storage_errors += 1
                    self._out_msgs.clear()
                    self._append_dirty.clear()
                    self._maybe_degrade(exc)
                    log.warning("raft-%d: hardstate save failed (%s); "
                                "holding %s", self.id, exc,
                                "read-only" if self.storage_degraded
                                else "retry")
                    return
            # cleared only AFTER a successful save (like _ready_entries):
            # a failed write must leave the flag set so the next flush
            # retries before any message claims the state is durable
            self._hs_dirty = False
        if self._append_dirty:
            dirty, self._append_dirty = self._append_dirty, {}
            if self.role == LEADER:
                for peer_id, allow_empty in dirty.items():
                    if peer_id in self.members and peer_id != self.id:
                        self._send_append_to(peer_id,
                                             allow_empty=allow_empty)
        if self._out_msgs:
            msgs, self._out_msgs = self._out_msgs, []
            for m in msgs:
                try:
                    self.transport.send(m)
                except Exception:
                    log.debug("raft-%d: send to %d failed", self.id, m.to)

    def _on_append_failure(self, exc: OSError):
        """A group append failed. The storage rolled the batch back, so
        the volatile state must follow: the batch ATOMICALLY never
        happened. Staged entries leave the in-memory log, every staged
        proposal's wait callback fires with the error (no proposal may
        hang forever on a dropped batch), and nothing buffered reaches
        the network — the messages claim durability the flush never
        provided. A leader steps down (it cannot persist its own log);
        ENOSPC additionally degrades the node to a read-only follower
        that keeps serving reads/heartbeats but rejects proposals."""
        self.storage_errors += 1
        batch, self._ready_entries = self._ready_entries, []
        keep = batch[0].index - self.first_index
        if keep >= 0:
            self.log = self.log[:keep]
        self.commit_index = max(self.last_applied,
                                min(self.commit_index, self._last_index()))
        err = f"raft storage append failed: {exc}"
        for e in batch:
            cb = self._waits.pop(e.request_id, None) if e.request_id else None
            if cb is not None:
                try:
                    cb(False, err)
                except Exception:
                    log.exception("raft-%d: wait callback failed", self.id)
        self._out_msgs.clear()
        self._append_dirty.clear()
        log.warning("raft-%d: WAL append of %d entries failed: %s",
                    self.id, len(batch), exc)
        self._maybe_degrade(exc)
        if self.role == LEADER:
            # a leader that cannot persist its log must not keep
            # accepting work; let a disk-healthy peer take over
            self._become_follower(self.term, None)

    def _maybe_degrade(self, exc):
        import errno as _errno

        # a WEDGED storage (failed batch whose rollback also failed)
        # must degrade too: probe() is the only un-wedge path and it
        # only runs from the degradation loop
        wedged = self.storage is not None \
            and getattr(self.storage, "_wedged", False)
        if getattr(exc, "errno", None) != _errno.ENOSPC and not wedged:
            return
        if not self.storage_degraded:
            self.storage_degraded = True
            self._degraded_elapsed = 0
            log.warning("raft-%d: WAL %s; degrading to read-only "
                        "follower", self.id,
                        "wedged" if wedged else "out of space")
        if self.role == LEADER:
            self._become_follower(self.term, None)

    def _dispatch(self, item):
        self.ready_items += 1
        kind = item[0]
        if kind == "msg":
            self._step(item[1])
        elif kind == "tick":
            self._on_tick()
        elif kind == "propose":
            self._on_propose(item[1], item[2], item[3],
                             item[4] if len(item) > 4 else None)
        elif kind == "conf":
            self._on_conf_change(item[1], item[2], item[3])
        elif kind == "campaign":
            self._campaign()
        elif kind == "transfer":
            self._on_transfer()
        elif kind == "removed":
            self._handle_self_removed()

    # ----------------------------------------------------------------- ticks
    def _next_timeout(self) -> int:
        return self.election_tick + self._rng.randrange(self.election_tick)

    def _on_tick(self):
        if self._transfer_cooldown > 0:
            self._transfer_cooldown -= 1
        if self.storage_degraded:
            # read-only degradation: probe the disk at election_tick
            # cadence; a writable disk lifts the degradation (the
            # follower append path also lifts it on its first durable
            # batch)
            self._degraded_elapsed += 1
            if self._degraded_elapsed >= self.election_tick:
                self._degraded_elapsed = 0
                if self.storage is not None and self.storage.probe():
                    self.storage_degraded = False
                    log.info("raft-%d: storage probe succeeded; leaving "
                             "read-only degradation", self.id)
        if self.role == LEADER:
            self.heartbeat_elapsed += 1
            if self.heartbeat_elapsed >= self.heartbeat_tick:
                self.heartbeat_elapsed = 0
                self._mark_broadcast()
            # expire paused streamed snapshots so lost chunks get
            # re-sent — clock-deadline based (FakeClock-deterministic),
            # and a resume re-sends ONLY the suffix past the follower's
            # acked contiguous prefix, never the whole blob
            if self._snap_pending:
                now = self.clock.monotonic()
                for peer_id, pending in list(self._snap_pending.items()):
                    if now >= pending.deadline:
                        self._resend_snapshot_suffix(peer_id, pending, now)
            if self.check_quorum:
                self._quorum_elapsed += 1
                if self._quorum_elapsed >= self.election_tick:
                    self._quorum_elapsed = 0
                    active = {self.id} | (
                        self._recent_active & set(self.members))
                    self._recent_active = set()
                    if not self._quorum(len(active)):
                        # partitioned leader: step down rather than keep
                        # accepting work a real quorum will supersede
                        # (reference raft.go CheckQuorum behavior)
                        log.info(
                            "raft-%d: leader lost quorum contact "
                            "(%d/%d active); stepping down",
                            self.id, len(active), len(self.members))
                        self._become_follower(self.term, None)
        else:
            self.election_elapsed += 1
            if self.election_elapsed >= self._randomized_timeout:
                self._campaign()

    # -------------------------------------------------------------- election
    def _campaign(self, transfer: bool = False):
        if self.id not in self.members:
            # removed members must not start elections, and a freshly joined
            # node that has not yet learned the membership (empty config)
            # must not self-elect as a quorum-of-one
            return
        if self.pre_vote and not transfer:
            # poll first; only a pre-quorum bumps the term (_real_campaign)
            self._pre_campaign()
            return
        self._real_campaign(transfer=transfer)

    def _enter_candidacy(self):
        self.role = CANDIDATE
        self.leader_id = None
        self.election_elapsed = 0
        self._randomized_timeout = self._next_timeout()
        # stale real votes from a PRIOR campaign at this term must not
        # survive into a pre-campaign: a delayed VoteResponse grant
        # passes the non-pre vote_resp gate (role==CANDIDATE, term
        # match) and could elect a pre-candidate without any pre-quorum
        # — leadership is only reachable via _real_campaign, which
        # re-seeds votes with the self-vote (ADVICE r5)
        self.votes = set()

    def _pre_campaign(self):
        self._enter_candidacy()
        # NO term bump, NO voted_for, NO persist — a pre-candidate that
        # cannot reach a quorum leaves no trace (raft §9.6)
        self._pre_votes = {self.id}
        if self._quorum(len(self._pre_votes)):
            self._real_campaign()
            return
        for peer_id in self.members:
            if peer_id == self.id:
                continue
            self._send(VoteRequest(
                frm=self.id, to=peer_id, term=self.term + 1,
                last_log_index=self._last_index(),
                last_log_term=self._last_term(),
                pre=True,
            ))

    def _real_campaign(self, transfer: bool = False):
        self._pre_votes = None
        self._enter_candidacy()
        self.term += 1
        self.voted_for = self.id
        self.votes = {self.id}
        self._persist_hard_state()
        if self._quorum(len(self.votes)):
            self._become_leader()
            return
        for peer_id in self.members:
            if peer_id == self.id:
                continue
            self._send(VoteRequest(
                frm=self.id, to=peer_id, term=self.term,
                last_log_index=self._last_index(),
                last_log_term=self._last_term(),
                transfer=transfer,
            ))

    def _quorum(self, n: int) -> bool:
        voters = len(self.members) or 1
        return n >= voters // 2 + 1

    def _become_leader(self):
        self._pre_votes = None
        self.role = LEADER
        self.leader_id = self.id
        self.heartbeat_elapsed = 0
        self._quorum_elapsed = 0
        self._recent_active = set()
        # a quorum just voted for us: that IS quorum contact
        self._lease_quorum_contact = self.clock.monotonic()
        self._lease_acked = set()
        self._snap_pending = {}
        self._inflight = {}
        last = self._last_index()
        self.next_index = {p: last + 1 for p in self.members if p != self.id}
        self.match_index = {p: 0 for p in self.members if p != self.id}
        # commit a no-op entry from the new term so earlier-term entries can
        # commit (raft §5.4.2 safety rule); leadership is signalled only
        # once this barrier applies (_apply_committed)
        self._signalled = False
        self._barrier_index = last + 1
        self._append_local(Entry(term=self.term, index=last + 1,
                                 kind=ENTRY_NORMAL, data=None))
        self._mark_broadcast()
        # commit advance (single-node clusters commit the barrier at once)
        # happens at this batch's flush, after the entry is durable

    def _become_follower(self, term: int, leader_id: int | None):
        was_leader = self.role == LEADER
        was_signalled = self._signalled
        self._signalled = False
        self._pre_votes = None
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_hard_state()
            # a partial snapshot stream from a deposed leader is dead;
            # drop its reassembly buffers (and their ack watermarks)
            self._snap_chunks.clear()
            self._snap_contig.clear()
        self.role = FOLLOWER
        self.leader_id = leader_id
        self.election_elapsed = 0
        self._randomized_timeout = self._next_timeout()
        if was_leader:
            self._drop_waits(ERR_LEADERSHIP_LOST)
            if was_signalled:
                self._notify_leadership(False)

    def _notify_leadership(self, is_leader: bool):
        try:
            self.on_leadership(is_leader)
        except Exception:
            log.exception("raft-%d: leadership callback failed", self.id)

    # ------------------------------------------------------------------ step
    def _step(self, msg):
        if (msg.kind == "vote_req" and self.check_quorum
                and not getattr(msg, "transfer", False)
                and self.leader_id is not None
                and self.election_elapsed < self.election_tick):
            # Leader lease (the vote-withholding half of etcd CheckQuorum,
            # which the reference gets from raft.Config CheckQuorum=true —
            # manager/state/raft/raft.go:492): a node that heard from a
            # live leader within the minimum election timeout IGNORES
            # disruptive campaigns entirely — no term bump, no response.
            # Without this, one starved/partition-returned node waking up
            # with an inflated term deposes a healthy leader and churns
            # elections under load. Applies to pre-votes and real votes
            # alike; leadership transfers bypass the lease via the
            # transfer flag (etcd campaignTransfer).
            return
        if msg.term > self.term:
            if msg.kind == "vote_req" and getattr(msg, "pre", False):
                # a pre-vote poll at a PROSPECTIVE term changes no state
                # here; _on_vote_request answers it without granting a
                # real vote (etcd: "Never change our term in response to
                # a PreVote")
                pass
            elif msg.kind == "vote_resp" and getattr(msg, "pre", False) \
                    and msg.granted:
                # a granted pre-vote echoes OUR prospective term back;
                # adopting it would double-bump the real campaign's term
                pass
            else:
                self._become_follower(msg.term, getattr(msg, "frm", None)
                                      if msg.kind == "append" else None)
        handler = {
            "vote_req": self._on_vote_request,
            "vote_resp": self._on_vote_response,
            "append": self._on_append,
            "append_resp": self._on_append_response,
            "snapshot": self._on_install_snapshot,
            "snap_chunk": self._on_snapshot_chunk,
            "snap_ack": self._on_snapshot_ack,
            "timeout_now": self._on_timeout_now,
        }.get(msg.kind)
        if handler:
            handler(msg)

    def _on_timeout_now(self, msg):
        """Leadership-transfer target: campaign immediately (raft §3.10).
        Gated on the CURRENT term's leader — a delayed/replayed transfer
        from a deposed leader must not disrupt a healthy one (etcd gates
        MsgTimeoutNow the same way)."""
        if self.id in self.members and msg.term == self.term \
                and msg.frm == self.leader_id:
            self._campaign(transfer=True)

    def _on_transfer(self):
        from .messages import TimeoutNow

        if self.role != LEADER:
            return
        # rate limit (reference raft.go:569-604: 1/min): the wedge monitor
        # may fire repeatedly while the store stays stuck, and back-to-back
        # transfers churn elections instead of letting the new leader
        # settle. Tick-counted so the fake-clock harness can drive expiry.
        if self._transfer_cooldown > 0:
            log.info("raft-%d: leadership transfer suppressed (rate limit)",
                     self.id)
            return
        peers = [p for p in self.members if p != self.id]
        if not peers:
            return
        self._transfer_cooldown = self.transfer_min_ticks
        target = max(peers, key=lambda p: self.match_index.get(p, 0))
        self._send(TimeoutNow(frm=self.id, to=target, term=self.term))

    def _on_vote_request(self, msg: VoteRequest):
        up_to_date = (msg.last_log_term, msg.last_log_index) >= (
            self._last_term(), self._last_index())
        if getattr(msg, "pre", False):
            # pre-vote: would we vote for this log at that future term?
            # Granting records NOTHING (no voted_for, no timer reset) —
            # many nodes may grant the same pre-term to different
            # pre-candidates; only real votes are exclusive
            grant = msg.term > self.term and up_to_date
            self._send(VoteResponse(
                frm=self.id, to=msg.frm,
                term=msg.term if grant else self.term,
                granted=grant, pre=True))
            return
        grant = False
        if msg.term >= self.term:
            not_voted = self.voted_for in (None, msg.frm)
            if up_to_date and not_voted and msg.term == self.term:
                grant = True
                self.voted_for = msg.frm
                self.election_elapsed = 0
                self._persist_hard_state()
        self._send(VoteResponse(frm=self.id, to=msg.frm, term=self.term,
                                granted=grant))

    def _on_vote_response(self, msg: VoteResponse):
        if getattr(msg, "pre", False):
            if (self.role != CANDIDATE or self._pre_votes is None
                    or not msg.granted or msg.term != self.term + 1):
                # rejections with a HIGHER real term already demoted us in
                # _step; stale or duplicate grants are ignored
                return
            self._pre_votes.add(msg.frm)
            if self._quorum(len(self._pre_votes)):
                self._real_campaign()
            return
        if self.role != CANDIDATE or msg.term != self.term:
            return
        if msg.granted:
            self.votes.add(msg.frm)
            if self._quorum(len(self.votes)):
                self._become_leader()

    def _on_append(self, msg: AppendEntries):
        if msg.term < self.term:
            self._send(AppendResponse(frm=self.id, to=msg.frm, term=self.term,
                                      success=False, match_index=0))
            return
        self.role = FOLLOWER
        self.leader_id = msg.frm
        self.election_elapsed = 0

        if getattr(msg, "lease_ttl", 0.0) > 0.0:
            # read-lease grant from the current-term leader: the
            # follower trusts it only DISCOUNTED by the skew margin, and
            # grants never shrink an existing deadline (out-of-order
            # delivery). A term change invalidates the previous term's
            # grants wholesale — a deposed leader's lease must not let
            # this follower serve past the new leader's writes for
            # longer than the old leader's own guarantee window.
            # WRITE ORDER is load-bearing for lock-free RPC readers
            # (read_ok): the deadline is zeroed FIRST on a term change
            # and written LAST on a grant, AFTER the index it gates —
            # a torn read can only look like an expired lease or an
            # over-strict index, never a live lease with a stale index.
            if self._read_lease_term != self.term:
                self._read_lease_until = 0.0
                self._read_lease_index = 0
                self._read_lease_term = self.term
            self._read_lease_index = max(self._read_lease_index,
                                         msg.leader_commit)
            self._read_lease_until = max(
                self._read_lease_until,
                self.clock.monotonic()
                + msg.lease_ttl * (1.0 - READ_LEASE_SKEW))

        # prev entry check
        if msg.prev_log_index > 0:
            if msg.prev_log_index < self.snapshot_index:
                # already compacted; our snapshot covers it
                pass
            elif msg.prev_log_index > self._last_index() or (
                    self._term_at(msg.prev_log_index) != msg.prev_log_term):
                self._send(AppendResponse(
                    frm=self.id, to=msg.frm, term=self.term, success=False,
                    match_index=min(self._last_index(), msg.prev_log_index - 1)))
                return

        for e in msg.entries:
            if e.index <= self.snapshot_index:
                continue
            if e.index <= self._last_index():
                if self._term_at(e.index) != e.term:
                    # conflict: truncate from here
                    self.log = self.log[: e.index - self.first_index]
                    self._append_entry_storage_truncate(e.index)
                    self.log.append(e)
                    self._persist_entry(e)
            else:
                self.log.append(e)
                self._persist_entry(e)

        if msg.leader_commit > self.commit_index:
            # the apply (and the hardstate save recording the advance)
            # happens at this batch's flush, AFTER the entries above are
            # durably appended
            self.commit_index = min(msg.leader_commit, self._last_index())

        if self._snap_chunks:
            # appends caught us up past a partially-streamed snapshot
            # (its sender died mid-stream): the buffers are garbage now
            self._prune_snap_buffers(self._last_index())

        self._send(AppendResponse(frm=self.id, to=msg.frm, term=self.term,
                                  success=True,
                                  match_index=self._last_index()))

    def _on_append_response(self, msg: AppendResponse):
        if self.role != LEADER or msg.term != self.term:
            return
        self._recent_active.add(msg.frm)  # CheckQuorum lease contact
        # read-lease anchor: once responses from a quorum accumulate,
        # re-anchor the grant window and start collecting afresh (the
        # set is reset on every quorum so the anchor tracks ROUNDS of
        # quorum contact, not a window that one chatty peer keeps warm)
        self._lease_acked.add(msg.frm)
        if self._quorum(len(self._lease_acked | {self.id})):
            self._lease_quorum_contact = self.clock.monotonic()
            self._lease_acked.clear()
        if msg.success:
            # one ack drains one window slot (heartbeat acks merely decay
            # the counter faster, floored at zero)
            self._inflight[msg.frm] = max(
                0, self._inflight.get(msg.frm, 0) - 1)
            self.match_index[msg.frm] = max(
                self.match_index.get(msg.frm, 0), msg.match_index)
            # pipelined sends advanced next_index optimistically past
            # match+1 — never regress it on an (out-of-order) ack
            self.next_index[msg.frm] = max(
                self.next_index.get(msg.frm, 1),
                self.match_index[msg.frm] + 1)
            pending = self._snap_pending.get(msg.frm)
            if pending is not None and msg.match_index >= pending.snap_idx:
                self._snap_pending.pop(msg.frm, None)  # install acked
            # commit advance runs once at the flush, over the whole
            # batch of acks; refill the pipeline window opened by this
            # ack with ONE coalesced append per peer per flush
            self._mark_append(msg.frm, allow_empty=False)
        else:
            if msg.frm in self._snap_pending:
                # mid-install heartbeat mismatch is expected; the streamed
                # snapshot (or its TTL expiry) resolves it
                return
            # follower hinted how far behind it is; with a pipeline in
            # flight, stale rejections of already-superseded probes carry
            # hints >= next — only a genuinely lower hint rewinds
            self._inflight[msg.frm] = 0  # everything in flight is moot
            new_next = max(1, msg.match_index + 1)
            if new_next < self.next_index.get(msg.frm,
                                              self._last_index() + 1):
                self.next_index[msg.frm] = new_next
                self._mark_append(msg.frm, allow_empty=False)

    def _on_install_snapshot(self, msg: InstallSnapshot):
        if msg.term < self.term:
            return
        self.role = FOLLOWER
        self.leader_id = msg.frm
        self.election_elapsed = 0
        self._install_snapshot(msg.frm, msg.snapshot_index,
                               msg.snapshot_term, msg.members, msg.data,
                               removed=msg.removed)

    def _on_snapshot_chunk(self, msg):
        """Reassemble a streamed snapshot; apply when complete. Every chunk
        counts as leader contact (the follower must not campaign while a
        multi-second install is in flight). Resumable (ISSUE 18): chunks
        are byte-identical per snapshot_index (leader-side _snap_blob
        cache), so the buffer is filled idempotently — dup/reorder are
        no-ops, a suffix resend fills holes without losing the prefix —
        and every chunk is answered with a SnapshotAck carrying the
        highest CONTIGUOUS seq held."""
        if msg.term < self.term:
            return
        if failpoints.fp_value("raft.snap.chunk_drop", False):
            # injected chunk loss (docs/fault_injection.md): the chunk
            # never existed as far as this follower is concerned
            return
        self.role = FOLLOWER
        self.leader_id = msg.frm
        self.election_elapsed = 0
        if msg.snapshot_index <= self.snapshot_index:
            # already have it (dup/late chunks): ack so the leader unpauses
            self._send(AppendResponse(
                frm=self.id, to=msg.frm, term=self.term, success=True,
                match_index=self._last_index()))
            return
        if (msg.total <= 0 or not 0 <= msg.seq < msg.total
                or len(msg.chunk) > SNAPSHOT_CHUNK_BYTES
                or msg.total * SNAPSHOT_CHUNK_BYTES
                > self.snap_stream_max_bytes):
            # reassembly cap / malformed framing: a buggy or deposed
            # leader must not balloon this follower's memory
            self.snap_chunks_rejected += 1
            _snap_events.inc(("chunk_rejected",))
            return
        key = (msg.frm, msg.snapshot_index)
        if key not in self._snap_chunks:
            for k in [k for k in self._snap_chunks if k[0] == msg.frm]:
                if k[1] > msg.snapshot_index:
                    # a late chunk of a stream this sender already
                    # abandoned for a newer snapshot: ignore it
                    return
                # eager orphan eviction: at most ONE live buffer per
                # sender — the newer stream supersedes the older one
                self._snap_chunks.pop(k, None)
                self._snap_contig.pop(k, None)
        buf = self._snap_chunks.setdefault(key, {})
        buf[msg.seq] = msg.chunk
        c = self._snap_contig.get(key, -1)
        while c + 1 in buf:
            c += 1
        self._snap_contig[key] = c
        # progress report: the leader re-arms its resend deadline on
        # advance and, on expiry, re-sends only chunks past `acked`
        self._send(SnapshotAck(
            frm=self.id, to=msg.frm, term=self.term,
            snapshot_index=msg.snapshot_index, acked=c))
        if len(buf) < msg.total:
            return
        from ..rpc import codec

        data = codec.loads(b"".join(buf[i] for i in range(msg.total)))
        # drop every reassembly buffer for this or older snapshots
        self._prune_snap_buffers(msg.snapshot_index)
        self._install_snapshot(msg.frm, msg.snapshot_index,
                               msg.snapshot_term, msg.members, data,
                               removed=msg.removed)

    def _on_snapshot_ack(self, msg):
        """Leader side of the resumable stream: record the follower's
        contiguous-prefix watermark and push the resend deadline out —
        a live, progressing stream is never re-blasted."""
        if self.role != LEADER or msg.term != self.term:
            return
        self._recent_active.add(msg.frm)  # CheckQuorum lease contact
        pending = self._snap_pending.get(msg.frm)
        if pending is None or pending.snap_idx != msg.snapshot_index:
            return
        if msg.acked > pending.acked:
            pending.acked = msg.acked
            pending.deadline = (self.clock.monotonic()
                                + self.snapshot_resend_seconds)

    def _resend_snapshot_suffix(self, peer_id: int, pending: _SnapPending,
                                now: float):
        """Resend deadline expired: re-send ONLY the chunks past the
        follower's acked contiguous prefix. If the snapshot advanced (or
        the blob cache no longer covers it) the stream is abandoned and
        the ordinary append path starts a fresh one."""
        if pending.snap_idx != self.snapshot_index \
                or self._snap_blob is None \
                or self._snap_blob[0] != pending.snap_idx:
            self._snap_pending.pop(peer_id, None)
            self._mark_append(peer_id, allow_empty=False)
            return
        blob = self._snap_blob[1]
        chunks = [blob[i:i + SNAPSHOT_CHUNK_BYTES]
                  for i in range(0, len(blob), SNAPSHOT_CHUNK_BYTES)] or [b""]
        # the min(..., total-1) floor guarantees at least one chunk goes
        # out even when every chunk was acked — that re-ack carries the
        # AppendResponse a lost install-ack deprived us of
        start = min(pending.acked + 1, len(chunks) - 1)
        members = {rid: (p.node_id, p.addr)
                   for rid, p in self.members.items()}
        removed = sorted(self.removed_ids)
        for seq in range(start, len(chunks)):
            self._send(SnapshotChunk(
                frm=self.id, to=peer_id, term=self.term,
                snapshot_index=pending.snap_idx,
                snapshot_term=self.snapshot_term,
                members=members, removed=removed,
                seq=seq, total=len(chunks), chunk=chunks[seq],
            ))
        resent = len(chunks) - start
        self.snap_chunks_resent += resent
        self.snap_resume_suffix += 1
        _snap_events.inc(("chunk_resent",), resent)
        _snap_events.inc(("suffix_resume",))
        pending.deadline = now + self.snapshot_resend_seconds

    def _prune_snap_buffers(self, upto_index: int):
        """Drop reassembly buffers (and their ack watermarks) for
        snapshots at or below `upto_index` — they are covered by state
        this node already holds."""
        self._snap_chunks = {
            k: v for k, v in self._snap_chunks.items() if k[1] > upto_index}
        self._snap_contig = {
            k: v for k, v in self._snap_contig.items() if k[1] > upto_index}

    def _install_snapshot(self, frm: int, snapshot_index: int,
                          snapshot_term: int, members, data, removed=()):
        if snapshot_index <= self.snapshot_index:
            return
        _t0 = time.perf_counter()
        self.snapshot_index = snapshot_index
        self.snapshot_term = snapshot_term
        self.log = []
        self.first_index = snapshot_index + 1
        # entries staged for this flush are covered (or superseded) by the
        # snapshot — and so is any divergent persisted tail BEYOND it,
        # which a later restart would otherwise splice after the snapshot
        # (the install replaced the whole log, the WAL must follow).
        # ORDER is crash-safety: the WAL truncate runs BEFORE the new
        # snapshot is saved, so a crash anywhere in the window leaves
        # old-snapshot + a (possibly truncated) consistent prefix — never
        # new-snapshot + a divergent old tail. The failpoint below sits
        # mid-window so tests can pin exactly that.
        self._ready_entries = [e for e in self._ready_entries
                               if e.index > snapshot_index]
        if self.storage is not None:
            self.storage.truncate_from(snapshot_index + 1)
        failpoints.fp("raft.snap.install")
        self.commit_index = max(self.commit_index, snapshot_index)
        self.last_applied = snapshot_index
        self.members = {
            rid: Peer(rid, nid, addr)
            for rid, (nid, addr) in members.items()
        }
        # merge, don't replace: removals this node saw that the leader's
        # snapshot predates must survive too
        self.removed_ids |= set(removed)
        self.restore_state(data)
        if self.storage is not None:
            self.storage.save_snapshot(
                snapshot_index, snapshot_term, data, self.members,
                removed=self.removed_ids)
            # keep membership.json in step: load() prefers it over the
            # snapshot's member list, so a stale file would resurrect a
            # pre-snapshot membership on restart
            self.storage.save_membership(self.members, self.removed_ids)
        self.snap_installs += 1
        self.snap_install_seconds += time.perf_counter() - _t0
        _snap_events.inc(("install",))
        self._send(AppendResponse(frm=self.id, to=frm, term=self.term,
                                  success=True, match_index=snapshot_index))

    # ------------------------------------------------------------- proposing
    def _on_propose(self, data, request_id, callback, trace_ctx=None):
        if self.storage_degraded:
            # read-only: reads/heartbeats keep flowing, writes bounce
            callback(False, "storage degraded (read-only): out of disk "
                            "space; proposal rejected")
            return
        if self.role != LEADER or not self._signalled:
            # an unsignalled leader has unapplied earlier-term entries;
            # accepting a proposal now deadlocks the applier against the
            # proposer's store lock (raft.go processInternalRaftRequest
            # fails on !signalledLeadership for the same reason)
            callback(False, f"{ERR_NOT_LEADER}; leader is {self.leader_id}")
            return
        self._waits[request_id] = callback
        e = Entry(term=self.term, index=self._last_index() + 1,
                  kind=ENTRY_NORMAL, data=data, request_id=request_id,
                  trace=trace_ctx)
        if trace_ctx is not None and trace.enabled():
            trace.event("raft.stage", parent=trace_ctx,
                        node=self.id, index=e.index)
        self._append_local(e)
        self._mark_broadcast()
        # the batch flush persists (one fsync for ALL proposals in the
        # batch), replicates (one coalesced append per peer) and advances
        # the commit (single-node clusters commit right at the flush)

    def _on_conf_change(self, cc: ConfChange, request_id, callback):
        if self.storage_degraded:
            callback(False, "storage degraded (read-only): out of disk "
                            "space; conf change rejected")
            return
        if self.role != LEADER or not self._signalled:
            callback(False, f"{ERR_NOT_LEADER}; leader is {self.leader_id}")
            return
        if cc.action == "remove" and not self._can_remove(cc.raft_id):
            callback(False, "removal would break quorum of reachable members")
            return
        self._waits[request_id] = callback
        e = Entry(term=self.term, index=self._last_index() + 1,
                  kind=ENTRY_CONF_CHANGE, data=cc, request_id=request_id)
        self._append_local(e)
        self._mark_broadcast()

    def _can_remove(self, raft_id: int) -> bool:
        """reference raft.go:1170-1193 CanRemoveMember: removal must leave a
        reachable quorum."""
        remaining = [p for p in self.members if p != raft_id]
        if not remaining:
            return False
        reachable = sum(
            1 for p in remaining
            if p == self.id or self.transport.active(p))
        return reachable >= len(remaining) // 2 + 1

    def _drop_waits(self, reason: str):
        waits, self._waits = self._waits, {}
        for cb in waits.values():
            try:
                cb(False, reason)
            except Exception:
                pass

    # ------------------------------------------------------------ replication
    def _append_local(self, e: Entry):
        self.log.append(e)
        self._persist_entry(e)
        if self.role == LEADER:
            self._maybe_snapshot()

    def _mark_append(self, peer_id: int, allow_empty: bool = True):
        """Note that `peer_id` is owed an AppendEntries; the batch flush
        coalesces every mark into ONE _send_append_to per peer."""
        self._append_dirty[peer_id] = (self._append_dirty.get(peer_id, False)
                                       or allow_empty)

    def _mark_broadcast(self):
        for peer_id in self.members:
            if peer_id != self.id:
                self._mark_append(peer_id)

    def _send_append_to(self, peer_id: int, allow_empty: bool = True):
        """Ship log entries to one peer, pipelined: batches are sent
        optimistically (next_index advances without waiting for acks) up
        to an in-flight window of MAX_INFLIGHT_APPENDS unacked messages,
        so catch-up throughput is window-bound instead of
        one-batch-per-RTT (reference MaxInflightMsgs). Before the first
        ack establishes `match`, the peer is in probe mode: one
        NON-advancing batch at a time (etcd ProgressStateProbe) — blasting
        optimistic batches at a possibly-mismatched log would bounce
        entirely."""
        next_idx = self.next_index.get(peer_id, self._last_index() + 1)
        if peer_id not in self._snap_pending and \
                next_idx <= self.snapshot_index:
            self._send_snapshot_to(peer_id)
            return
        lease_ttl = self._lease_ttl()
        match = self.match_index.get(peer_id, 0)
        paused = peer_id in self._snap_pending
        sent = 0
        while not paused:
            if self._inflight.get(peer_id, 0) >= MAX_INFLIGHT_APPENDS:
                break  # window full: heartbeat only until acks drain it
            next_idx = self.next_index.get(peer_id, self._last_index() + 1)
            start = next_idx - self.first_index
            entries = self.log[start:start + MAX_ENTRIES_PER_APPEND]
            if not entries:
                break
            prev_index = next_idx - 1
            prev_term = self._term_at(prev_index) if prev_index > 0 else 0
            self._send(AppendEntries(
                frm=self.id, to=peer_id, term=self.term,
                prev_log_index=prev_index, prev_log_term=prev_term,
                entries=list(entries), leader_commit=self.commit_index,
                lease_ttl=lease_ttl,
            ))
            self._inflight[peer_id] = self._inflight.get(peer_id, 0) + 1
            if match <= 0:
                return  # probe mode: do not advance next, await the ack
            self.next_index[peer_id] = next_idx + len(entries)
            sent += 1
        if sent == 0 and allow_empty:
            # heartbeat / commit-index propagation; also flows to paused
            # (snapshot-installing) peers so they neither campaign nor
            # starve the CheckQuorum lease of their responses
            prev_index = next_idx - 1
            prev_term = self._term_at(prev_index) if prev_index > 0 else 0
            self._send(AppendEntries(
                frm=self.id, to=peer_id, term=self.term,
                prev_log_index=prev_index, prev_log_term=prev_term,
                entries=[], leader_commit=self.commit_index,
                lease_ttl=lease_ttl,
            ))

    def _send_snapshot_to(self, peer_id: int):
        """Stream the current snapshot in chunks and pause the peer until
        it acks (or the TTL expires and we re-send)."""
        if peer_id in self._snap_pending:
            return
        from ..rpc import codec

        # serialize once per snapshot_index: snapshot_state() reads the
        # LIVE store, so a re-stream after new commits would otherwise
        # produce different bytes under the same snapshot_index
        if self._snap_blob is None or \
                self._snap_blob[0] != self.snapshot_index:
            self._snap_blob = (self.snapshot_index,
                               codec.dumps(self.snapshot_state()))
        blob = self._snap_blob[1]
        chunks = [blob[i:i + SNAPSHOT_CHUNK_BYTES]
                  for i in range(0, len(blob), SNAPSHOT_CHUNK_BYTES)] or [b""]
        members = {rid: (p.node_id, p.addr)
                   for rid, p in self.members.items()}
        removed = sorted(self.removed_ids)
        for seq, part in enumerate(chunks):
            self._send(SnapshotChunk(
                frm=self.id, to=peer_id, term=self.term,
                snapshot_index=self.snapshot_index,
                snapshot_term=self.snapshot_term,
                members=members, removed=removed,
                seq=seq, total=len(chunks), chunk=part,
            ))
        self.snap_chunks_sent += len(chunks)
        _snap_events.inc(("chunk_sent",), len(chunks))
        self._snap_pending[peer_id] = _SnapPending(
            snap_idx=self.snapshot_index,
            deadline=self.clock.monotonic() + self.snapshot_resend_seconds)
        self.next_index[peer_id] = self.snapshot_index + 1

    def _maybe_advance_commit(self):
        if self.role != LEADER:
            return
        matches = sorted(
            [self._last_index()]
            + [self.match_index.get(p, 0) for p in self.members if p != self.id],
            reverse=True,
        )
        voters = len(self.members) or 1
        quorum_match = matches[voters // 2] if voters > 1 else matches[0]
        # only commit entries from the current term directly (raft §5.4.2)
        if quorum_match > self.commit_index and \
                self._term_at(quorum_match) == self.term:
            self.commit_index = quorum_match
            self._mark_broadcast()  # propagate the new commit index
            # the flush applies right after this (batched apply pass)

    def _apply_committed(self):
        # disarmed cost on this hot loop: one truthiness test up front,
        # one `and`-short-circuited attribute read per entry
        traced = trace.enabled()
        if self.last_applied < self.commit_index:
            # persist the advanced commit (etcd HardState semantics: term,
            # vote and commit survive restarts together)
            self._persist_hard_state()
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            idx = self.last_applied - self.first_index
            if idx < 0:
                continue  # covered by snapshot
            if idx >= len(self.log):
                # commit raced ahead of a truncated log; stop rather than crash
                self.last_applied -= 1
                break
            e = self.log[idx]
            self.commits_applied += 1
            _t0 = None
            if traced and e.trace is not None:
                # commit event + apply span join the proposal's trace —
                # on the leader AND on followers (the ctx rode the
                # replicated entry), which is what makes the causal
                # propose→fsync→commit→apply chain cross node boundaries
                trace.event("raft.commit", parent=e.trace,
                            node=self.id, index=e.index)
                _t0 = time.perf_counter()
            if e.kind == ENTRY_CONF_CHANGE:
                self._apply_conf_change(e)
            elif e.data is not None:
                try:
                    self.apply_entry(e)
                except Exception:
                    log.exception("raft-%d: apply failed at %d", self.id, e.index)
            if _t0 is not None:
                # _t0 is non-None ONLY under the `traced` guard above —
                # same armed-only shape the lint's enabled() pattern
                # recognizes, one hop removed  # lint: allow(span-in-loop)
                trace.rec("raft.apply", time.perf_counter() - _t0,
                          parent=e.trace, node=self.id, index=e.index)
            cb = self._waits.pop(e.request_id, None) if e.request_id else None
            if cb is not None:
                try:
                    cb(True, "")
                except Exception:
                    log.exception("raft-%d: wait callback failed", self.id)
        if self.role == LEADER and not self._signalled \
                and self.last_applied >= self._barrier_index:
            # the new-term barrier (and everything before it) is applied:
            # leadership is now usable (raft.go:644-670 ordering)
            self._signalled = True
            self._notify_leadership(True)
        self._maybe_snapshot()

    def _apply_conf_change(self, e: Entry):
        # membership is updated copy-on-write: cross-thread readers (role
        # manager via member_by_node_id/can_remove_member) snapshot the dict
        # reference and iterate safely without locks
        cc: ConfChange = e.data
        if cc.action == "add":
            members = dict(self.members)
            members[cc.raft_id] = Peer(cc.raft_id, cc.node_id, cc.addr)
            self.members = members
            if self.role == LEADER and cc.raft_id != self.id:
                self.next_index.setdefault(cc.raft_id, self._last_index() + 1)
                self.match_index.setdefault(cc.raft_id, 0)
        elif cc.action == "remove":
            members = dict(self.members)
            members.pop(cc.raft_id, None)
            self.members = members
            self.removed_ids.add(cc.raft_id)
            self.next_index.pop(cc.raft_id, None)
            self.match_index.pop(cc.raft_id, None)
            if cc.raft_id == self.id and not self._self_removed:
                self._self_removed = True
                self._become_follower(self.term, None)
                if self.on_removed is not None:
                    # off-thread: the apply loop must not run teardown
                    threading.Thread(target=self.on_removed, daemon=True,
                                     name="raft-removed").start()
        if self.storage is not None:
            self.storage.save_membership(self.members, self.removed_ids)

    def _handle_self_removed(self):
        """Worker-thread handler for notify_removed: same consequences as
        applying our own removal conf change, minus a log entry we will
        never receive (peers stopped replicating to us)."""
        if self._self_removed:
            return
        self._self_removed = True
        members = dict(self.members)
        members.pop(self.id, None)
        self.members = members          # also stops further elections
        self.removed_ids.add(self.id)
        self._become_follower(self.term, None)
        if self.storage is not None:
            self.storage.save_membership(self.members, self.removed_ids)
        if self.on_removed is not None:
            threading.Thread(target=self.on_removed, daemon=True,
                             name="raft-removed").start()

    # -------------------------------------------------------------- snapshots
    def _maybe_snapshot(self):
        applied_in_log = self.last_applied - self.snapshot_index
        if applied_in_log < self.snapshot_interval:
            return
        data = self.snapshot_state()
        self.snapshot_term = self._term_at(self.last_applied)
        self.snapshot_index = self.last_applied
        keep_from = self.last_applied + 1 - self.first_index
        self.log = self.log[keep_from:]
        self.first_index = self.last_applied + 1
        if self.storage is not None:
            self.storage.save_snapshot(
                self.snapshot_index, self.snapshot_term, data, self.members,
                removed=self.removed_ids)
            self.storage.compact(self.first_index)

    # ------------------------------------------------------------ persistence
    def _persist_hard_state(self):
        """Mark term/vote/commit dirty; the batch flush writes hardstate at
        most once, and always before any buffered message leaves."""
        self._hs_dirty = True

    def _persist_entry(self, e: Entry):
        """Stage an entry for the batch flush's single group-commit WAL
        append (one write + one fsync for the whole batch)."""
        self._ready_entries.append(e)

    def _append_entry_storage_truncate(self, from_index: int):
        # conflict truncation: drop staged-but-unpersisted entries in the
        # truncated range too, then truncate the durable log
        self._ready_entries = [e for e in self._ready_entries
                               if e.index < from_index]
        if self.storage is not None:
            self.storage.truncate_from(from_index)

    def _restore_from_storage(self):
        state = self.storage.load()
        if state is None:
            return
        self.term = state.term
        self.voted_for = state.voted_for
        self.snapshot_index = state.snapshot_index
        self.snapshot_term = state.snapshot_term
        self.first_index = state.snapshot_index + 1
        self.log = list(state.entries)
        self.members = dict(state.members)
        self.removed_ids = set(state.removed)
        # a torn WAL tail (or undecryptable entries) can leave the persisted
        # commit ahead of the recovered log; cap it so replay can't index
        # past the entries we actually have
        self.commit_index = min(max(state.commit_index, state.snapshot_index),
                                self._last_index())
        self.last_applied = self.snapshot_index
        if state.snapshot_data is not None:
            self.restore_state(state.snapshot_data)
        self._apply_committed()

    # ----------------------------------------------------------------- helpers
    def _last_index(self) -> int:
        return self.first_index + len(self.log) - 1 if self.log else self.snapshot_index

    def _last_term(self) -> int:
        return self.log[-1].term if self.log else self.snapshot_term

    def _term_at(self, index: int) -> int:
        if index == self.snapshot_index:
            return self.snapshot_term
        i = index - self.first_index
        if 0 <= i < len(self.log):
            return self.log[i].term
        return -1

    def _send(self, msg):
        """Buffer an outgoing message; the batch flush releases it to the
        transport only AFTER the flush's WAL append + hardstate save, so
        no message ever claims state that is not yet durable."""
        self._out_msgs.append(msg)

    # -------------------------------------------------------------- lease
    def _lease_ttl(self) -> float:
        """Seconds of read lease this node may grant right now (0.0 =
        none). Only a SIGNALLED leader running CheckQuorum grants, and
        the grant is ANCHORED at the last observed quorum contact: it
        shrinks as that contact ages and hits zero after lease_duration
        of quorum silence — so a leader partitioned with a minority
        stops extending follower leases immediately, long before its
        CheckQuorum step-down, instead of stretching a stale follower's
        window past a new leader's election. The vote-withholding half
        (followers ignore campaigns for election_tick after leader
        contact) is what makes the window itself sound; like etcd's
        clock-based lease reads, the anchor assumes response delay is
        small against the window (arbitrarily delayed acks could
        freshen it — the strict alternative is ReadIndex round-trips)."""
        if not (self.lease_duration > 0.0 and self.role == LEADER
                and self._signalled and self.check_quorum):
            return 0.0
        remaining = self.lease_duration \
            - (self.clock.monotonic() - self._lease_quorum_contact)
        return max(0.0, min(remaining, self.lease_duration))

    def read_ok(self) -> bool:
        """May this node serve a lease-gated read right now? The leader
        always may. A follower may only while (a) it holds a live,
        skew-discounted lease from the CURRENT term's leader and (b) it
        has APPLIED at least the leader's commit index from the grant —
        the served snapshot is then no older than the leader's commit
        frontier at grant time (bounded staleness, not linearizability;
        writes stay leader-only). Thread-safe for RPC-thread callers."""
        if self.is_leader:
            return True
        if self.role != FOLLOWER or self._read_lease_term != self.term:
            return False
        if self.clock.monotonic() >= self._read_lease_until:
            return False
        return self.last_applied >= self._read_lease_index

    def read_lease(self) -> dict:
        """Introspection for status()/tests: the current lease triple
        plus the live verdict."""
        return {
            "ok": self.read_ok(),
            "until": self._read_lease_until,
            "index": self._read_lease_index,
            "term": self._read_lease_term,
            "applied": self.last_applied,
        }

    # ------------------------------------------------------------- introspect
    @property
    def is_leader(self) -> bool:
        """Usable leadership: elected AND the new-term barrier has applied
        (proposals before that point are rejected)."""
        return self.role == LEADER and self._signalled

    def status(self) -> dict:
        return {
            "id": self.id,
            "role": self.role,
            "term": self.term,
            "leader": self.leader_id,
            "commit": self.commit_index,
            "applied": self.last_applied,
            "last_index": self._last_index(),
            "members": {p.raft_id: p.addr for p in self.members.values()},
            # group-commit plane observability: amortized cost per commit
            # is wal_fsyncs / commits_applied when storage is attached
            "ready_flushes": self.ready_flushes,
            "ready_items": self.ready_items,
            "commits_applied": self.commits_applied,
            # fault plane: read-only degradation + append/hardstate
            # failures observed (tests and the operator surface read it)
            "storage_degraded": self.storage_degraded,
            "storage_errors": self.storage_errors,
            # read-lease plane (ISSUE 13): may this node serve
            # lease-gated reads, and under which grant
            "read_lease": self.read_lease(),
            # recovery plane (ISSUE 18): streamed-snapshot progress —
            # resent/resume stay near zero on a healthy network; installs
            # and their wall time size the catch-up path
            "snap_chunks_sent": self.snap_chunks_sent,
            "snap_chunks_resent": self.snap_chunks_resent,
            "snap_resume_suffix": self.snap_resume_suffix,
            "snap_chunks_rejected": self.snap_chunks_rejected,
            "snap_installs": self.snap_installs,
            "snap_install_seconds": self.snap_install_seconds,
        }
