"""Network raft transport: per-peer mTLS RPC with health tracking.

Re-derivation of manager/state/raft/transport/{transport.go:47-402,
peer.go:26-142}: the raft core hands messages to a transport that owns one
connection per peer, sends asynchronously (the consensus loop must never
block on the network), tracks per-peer health for CanRemoveMember quorum
checks, and resolves peer addresses from the replicated membership (conf
changes carry addresses; ResolveAddress repairs stale ones).

Wire: unary `raft.step` RPCs over the shared RPC substrate (the reference
streams raftpb messages over gRPC; our frames are already length-prefixed
and multiplexed, so a stream adds nothing at this message rate).
"""
from __future__ import annotations

import logging
import queue
import random
import threading

from ..analysis.lockgraph import make_lock
from ..rpc.client import RPCClient
from ..utils import failpoints
from ..utils.backoff import Backoff
from .messages import MemberRemovedError

log = logging.getLogger("swarmkit_tpu.raft.transport")

OUTBOX_LIMIT = 1024          # per-peer; raft retransmits, drops are safe
HEALTH_WINDOW = 10.0         # seconds: a peer is active if a send succeeded
SEND_TIMEOUT = 5.0
# reconnect pacing: exponential-jitter per peer (utils/backoff.py), reset
# on the first successful send — replaces the old fixed 1 s pause, which
# thundered every peer's redial in lockstep after a leader restart
RECONNECT_POLICY = Backoff(base=0.2, factor=2.0, max_delay=2.0,
                           max_attempts=1 << 30)
# sender-side coalescing: a backlogged outbox drains up to this many
# messages into ONE raft.step_many RPC instead of one round trip each
# (the wire half of the group-commit plane; single messages still ride
# the plain raft.step)
SEND_BATCH = 64


class NetworkTransport:
    """Implements the RaftNode transport seam (send/active) over RPC."""

    def __init__(self, security, local_raft_id: int = 0, clock=None,
                 reconnect_policy: Backoff = RECONNECT_POLICY):
        from ..utils.clock import REAL_CLOCK

        self.security = security
        self.local_raft_id = local_raft_id
        self.node = None  # RaftNode, attached via set_node
        self.clock = clock or REAL_CLOCK
        self.reconnect_policy = reconnect_policy
        self._rng = random.Random()
        self._lock = make_lock('raft.transport.lock')
        self._outboxes: dict[int, queue.Queue] = {}
        self._threads: dict[int, threading.Thread] = {}
        self._clients: dict[int, RPCClient] = {}
        self._addr_overrides: dict[int, str] = {}
        self._last_ok: dict[int, float] = {}
        self._last_try: dict[int, float] = {}
        self._stopped = threading.Event()

    def set_node(self, node):
        self.node = node

    # -- RaftNode seam -----------------------------------------------------
    def send(self, msg) -> None:
        """Queue a message for async delivery; never blocks the raft loop."""
        if self._stopped.is_set():
            return
        box = self._outbox(msg.to)
        try:
            box.put_nowait(msg)
        except queue.Full:
            # drop-oldest: newer raft state supersedes older messages
            try:
                box.get_nowait()
            except queue.Empty:
                pass
            try:
                box.put_nowait(msg)
            except queue.Full:
                pass

    def active(self, peer_id: int) -> bool:
        """Peer health for quorum-safety checks (transport.go Active)."""
        with self._lock:
            last_ok = self._last_ok.get(peer_id)
            last_try = self._last_try.get(peer_id)
        if last_ok is not None and \
                self.clock.monotonic() - last_ok < HEALTH_WINDOW:
            return True
        # never attempted yet: optimistic (a fresh member hasn't been dialed)
        return last_try is None

    # -- peer management ---------------------------------------------------
    def update_peer_addr(self, raft_id: int, addr: str):
        with self._lock:
            self._addr_overrides[raft_id] = addr
            client = self._clients.pop(raft_id, None)
        if client is not None:
            client.close()

    def stop(self):
        self._stopped.set()
        with self._lock:
            threads = list(self._threads.values())
            clients = list(self._clients.values())
            boxes = list(self._outboxes.values())
        for b in boxes:
            try:
                b.put_nowait(None)  # wake senders
            except queue.Full:
                pass
        for c in clients:
            c.close()
        for t in threads:
            t.join(timeout=2)

    # -- internals ---------------------------------------------------------
    def _outbox(self, peer_id: int) -> queue.Queue:
        with self._lock:
            box = self._outboxes.get(peer_id)
            if box is None:
                box = queue.Queue(maxsize=OUTBOX_LIMIT)
                self._outboxes[peer_id] = box
                t = threading.Thread(target=self._sender_loop,
                                     args=(peer_id, box), daemon=True,
                                     name=f"raft-send-{peer_id}")
                self._threads[peer_id] = t
                t.start()
            return box

    def _peer_addr(self, peer_id: int) -> str | None:
        with self._lock:
            override = self._addr_overrides.get(peer_id)
        if override:
            return override
        node = self.node
        if node is not None:
            peer = node.members.get(peer_id)
            if peer is not None and peer.addr and not peer.addr.startswith("mem://"):
                return peer.addr
        return None

    def _client(self, peer_id: int) -> RPCClient | None:
        with self._lock:
            client = self._clients.get(peer_id)
        if client is not None and client.alive:
            return client
        addr = self._peer_addr(peer_id)
        if addr is None:
            return None
        try:
            client = RPCClient(addr, security=self.security,
                               connect_timeout=SEND_TIMEOUT)
        except OSError as exc:
            log.debug("raft transport: dial %s failed: %s", addr, exc)
            return None
        with self._lock:
            old = self._clients.get(peer_id)
            self._clients[peer_id] = client
        if old is not None:
            old.close()
        return client

    def _sender_loop(self, peer_id: int, box: queue.Queue):
        backoff_until = 0.0
        failures = 0    # consecutive failures; indexes the backoff policy
        stop_after_batch = False

        def pace():
            # exponential-jitter pause before the next attempt at this
            # peer; failures reset on the first successful send
            nonlocal backoff_until, failures
            backoff_until = self.clock.monotonic() + \
                self.reconnect_policy.delay(failures, self._rng)
            failures += 1

        while not self._stopped.is_set() and not stop_after_batch:
            try:
                msg = box.get(timeout=0.5)
            except queue.Empty:
                continue
            if msg is None:
                return
            # coalesce a backlog into one RPC: under the node's batched
            # Ready flush a whole wave of appends/responses lands in the
            # outbox at once, and per-message round trips would serialize
            # it again at one RTT each
            msgs = [msg]
            while len(msgs) < SEND_BATCH:
                try:
                    nxt = box.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    stop_after_batch = True  # deliver, then exit
                    break
                msgs.append(nxt)
            now = self.clock.monotonic()
            with self._lock:
                self._last_try[peer_id] = now
            if now < backoff_until:
                continue  # drop while the peer is unreachable; raft resends
            client = self._client(peer_id)
            if client is None:
                pace()
                continue
            try:
                # failpoint `raft.transport.send`: error = the peer link
                # drops this batch (raft retransmits); delay = a latency
                # spike on the peer link
                failpoints.fp("raft.transport.send")
                if len(msgs) == 1:
                    client.call("raft.step", msgs[0], timeout=SEND_TIMEOUT)
                else:
                    client.call("raft.step_many", msgs, timeout=SEND_TIMEOUT)
                with self._lock:
                    self._last_ok[peer_id] = self.clock.monotonic()
                backoff_until = 0.0
                failures = 0
            except Exception as exc:
                if isinstance(exc, MemberRemovedError):
                    # the peer answered with the TYPED removed marker: WE
                    # are no longer part of this cluster (demoted while
                    # down — reference ErrMemberRemoved in node.go). Typed
                    # match only (ADVICE r03): a substring in some peer's
                    # unrelated error text must never self-demote a node.
                    node = self.node
                    if node is not None \
                            and getattr(msg, "frm", None) == node.id:
                        log.info("raft transport: peer %d says we were "
                                 "removed from the cluster", peer_id)
                        node.notify_removed()
                    pace()
                    continue
                log.debug("raft transport: send to %d failed: %s",
                          peer_id, exc)
                client.close()
                pace()
