"""RaftProposer: bridges MemoryStore transactions onto raft consensus.

The reference's write path (SURVEY.md §3.4): store.update collects a
changelist → proposer.ProposeValue blocks until the entry commits → the
registered wait triggers the in-memory commit on the leader; followers (and
restart replay) apply the same actions via ApplyStoreActions. Object
versions are stamped with the raft entry index on every replica, so
version-checked updates behave identically cluster-wide.
"""
from __future__ import annotations

import bisect
import threading
from typing import Callable

import time

from ..analysis.lockgraph import make_lock
from ..api.objects import Version
from ..utils import trace
from ..utils.identity import new_id
from ..utils.metrics import histogram
from .messages import ERR_LEADERSHIP_LOST, ERR_NOT_LEADER, Entry
from .node import RaftNode

PROPOSE_TIMEOUT = 30.0

# reference: swarm_raft_transaction_latency (raft.go:204-209)
_propose_latency = histogram(
    "swarm_raft_transaction_latency_seconds",
    "raft proposal submit→commit duration")


class ProposeError(Exception):
    pass


class LeadershipLost(ProposeError):
    """The proposal failed because this node is not (or stopped being) the
    raft leader — distinct from transient failures like a quorum-loss
    timeout, which may resolve while still leading. Leader-only component
    threads treat this as a clean-shutdown signal
    (utils/leadership.leadership_lost)."""


# the demotion markers RaftNode builds its propose-callback errors from
# (messages.ERR_*); matched HERE only, so callers get a structured
# exception and a rewording can't desynchronize producer and classifier
_NOT_LEADER_MARKERS = (ERR_NOT_LEADER, ERR_LEADERSHIP_LOST)


def _classify(err: str) -> type[ProposeError]:
    return (LeadershipLost
            if any(m in err for m in _NOT_LEADER_MARKERS) else ProposeError)


class PendingProposal:
    """Handle for a pipelined proposal (propose_async): the caller may keep
    up to depth-K of these in flight; raft commits them in log order and
    resolves each handle from the worker thread. `wait()`/`result()` give
    the blocking API its exact semantics back."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._done = threading.Event()
        self._ok = False
        self._err = ""
        self._started = time.monotonic()

    def _resolve(self, ok: bool, err: str):
        self._ok = ok
        self._err = err
        self._done.set()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: float = PROPOSE_TIMEOUT) -> None:
        """Block until commit; raise the same typed errors propose_value
        raises (ProposeError / LeadershipLost)."""
        if not self._done.wait(timeout):
            raise ProposeError("proposal timed out")
        _propose_latency.observe(time.monotonic() - self._started)
        if not self._ok:
            err = self._err or "proposal dropped"
            raise _classify(err)(err)


class RaftProposer:
    def __init__(self, node: RaftNode, store=None):
        self.node = node
        self.store = store
        self._pending: dict[str, Callable[[int], None]] = {}
        self._lock = make_lock('raft.proposer.lock')
        node.apply_entry = self._apply_entry
        node.snapshot_state = self._snapshot_state
        node.restore_state = self._restore_state

    def attach_store(self, store):
        """Wire the store, then replay any persisted raft state into it —
        construct the node with auto_recover=False for this to work."""
        self.store = store
        self.node.recover()

    def _snapshot_state(self):
        return self.store.save() if self.store is not None else None

    def _restore_state(self, snap):
        if self.store is not None and snap is not None:
            self.store.restore(snap)

    # ------------------------------------------------------ Proposer protocol
    def propose_async(self, actions,
                      commit_cb: Callable[..., None]) -> PendingProposal:
        """Non-blocking propose: returns a PendingProposal immediately so
        the store can pipeline transactions at depth K against the raft
        group-commit plane (K proposals share one WAL fsync + one
        replication flush instead of paying one each). On commit the
        registered commit_cb runs on the raft worker thread, in log
        order; failure resolves the handle without running commit_cb."""
        req_id = new_id()
        handle = PendingProposal(req_id)
        with self._lock:
            self._pending[req_id] = commit_cb

        # trace plane: the proposal's root span — submit→commit-resolve.
        # Its ctx rides the staged Entry (and therefore replication and
        # the WAL), so every replica's fsync/commit/apply spans join this
        # trace. None when disarmed: zero allocation on the propose path.
        sp = trace.start("raft.propose")

        def on_result(ok: bool, err: str):
            if not ok:
                with self._lock:
                    self._pending.pop(req_id, None)
            if sp is not None:
                sp.end(ok=ok)
            handle._resolve(ok, err)

        self.node.propose(list(actions), req_id, on_result,
                          trace_ctx=sp.ctx() if sp is not None else None)
        return handle

    def propose_value(self, actions, commit_cb: Callable[..., None]) -> None:
        handle = self.propose_async(actions, commit_cb)
        try:
            handle.result(PROPOSE_TIMEOUT)
        except ProposeError:
            with self._lock:
                self._pending.pop(handle.request_id, None)
            raise

    def get_version(self) -> Version:
        return Version(self.node.commit_index)

    def changes_between(self, from_v: Version, to_v: Version) -> list:
        node = self.node
        # grab the list reference once: the raft worker thread replaces it
        # wholesale on truncation/compaction (our reference stays a
        # consistent prefix) and only ever appends in place
        entries = node.log
        first = entries[0].index if entries else node.first_index
        if from_v.index + 1 < first:
            # entries below `first` were compacted into a snapshot; a partial
            # answer would silently diverge the replaying watcher
            raise ProposeError(
                f"changes from {from_v.index} compacted (log starts at {first})")
        # entry indexes are sorted and dense: bisect to the requested
        # window instead of scanning the whole log per watcher resync
        lo = bisect.bisect_right(entries, from_v.index,
                                 key=lambda e: e.index)
        hi = bisect.bisect_right(entries, to_v.index, key=lambda e: e.index)
        return [e.data for e in entries[lo:hi]
                if e.data is not None and e.kind == 0]

    # --------------------------------------------------------------- applying
    def _apply_entry(self, entry: Entry) -> None:
        """Runs on every replica in commit order (raft worker thread)."""
        cb = None
        if entry.request_id:
            with self._lock:
                cb = self._pending.pop(entry.request_id, None)
        if cb is not None:
            # leader fast path: the waiting transaction commits its own
            # buffered writes, stamped with this entry's index
            cb(version_index=entry.index)
        elif self.store is not None and entry.data is not None:
            # follower / replay path
            self.store.apply_store_actions(entry.data,
                                           version_index=entry.index)
