"""Deterministic multi-node raft test harness.

The reference's tier-2 strategy (SURVEY.md §4): fake clock + in-process
cluster + partitionable transport (manager/state/raft/testutils). Here the
transport is an in-memory router whose links can be cut to simulate
partitions; the clock is manual ticks; `settle` pumps messages until
quiescent so tests are deterministic without sleeps.
"""
from __future__ import annotations

import random
import threading
from collections import defaultdict

from ..analysis.lockgraph import make_lock
from ..utils.clock import FakeClock
from .node import Peer, RaftNode


class MemoryTransport:
    """Router delivering messages synchronously into peer inboxes; links can
    be severed per (src, dst) pair (WrappedListener partition analogue)."""

    def __init__(self):
        self.nodes: dict[int, RaftNode] = {}
        self.cut: set[tuple[int, int]] = set()
        self.dropped = 0
        self._lock = make_lock('raft.testutils.lock')

    def register(self, node: RaftNode):
        self.nodes[node.id] = node

    def for_node(self, raft_id: int) -> "TransportHandle":
        return TransportHandle(self, raft_id)

    def send(self, frm: int, msg):
        with self._lock:
            blocked = (frm, msg.to) in self.cut or msg.to not in self.nodes
        if blocked:
            self.dropped += 1
            return
        self.nodes[msg.to].step(msg)

    def active(self, frm: int, to: int) -> bool:
        return (frm, to) not in self.cut and to in self.nodes

    # ---- partition control -------------------------------------------------
    def isolate(self, raft_id: int):
        with self._lock:
            for other in self.nodes:
                if other != raft_id:
                    self.cut.add((raft_id, other))
                    self.cut.add((other, raft_id))

    def heal(self, raft_id: int | None = None):
        with self._lock:
            if raft_id is None:
                self.cut.clear()
            else:
                self.cut = {
                    (a, b) for (a, b) in self.cut
                    if a != raft_id and b != raft_id
                }


class TransportHandle:
    def __init__(self, router: MemoryTransport, raft_id: int):
        self.router = router
        self.raft_id = raft_id

    def send(self, msg):
        self.router.send(self.raft_id, msg)

    def active(self, peer_id: int) -> bool:
        return self.router.active(self.raft_id, peer_id)


class RaftCluster:
    """N in-process raft nodes on a memory transport with a manual clock."""

    # seconds of fake time one tick_all round represents — the daemon's
    # tick cadence, so clock-deadline behavior (snapshot resend TTLs)
    # expires after the same tick counts the old tick-counted code did
    TICK_SECONDS = 0.2

    def __init__(self, n: int, storages: dict[int, object] | None = None,
                 apply_cbs: dict[int, object] | None = None,
                 snapshot_interval: int = 1000, seed: int = 7,
                 lease_duration: float = 0.0, clock=None):
        self.router = MemoryTransport()
        self.nodes: dict[int, RaftNode] = {}
        # one SHARED fake clock, advanced by tick_all: every clock-based
        # deadline in the node (snapshot resend, lease anchors) is then
        # seed-deterministic — no wall-time dependence in the harness
        self.clock = clock if clock is not None else FakeClock()
        peers = [Peer(i, f"node-{i}", f"mem://{i}") for i in range(1, n + 1)]
        for i in range(1, n + 1):
            node = RaftNode(
                raft_id=i,
                transport=self.router.for_node(i),
                storage=(storages or {}).get(i),
                apply_entry=(apply_cbs or {}).get(i, lambda e: None),
                snapshot_interval=snapshot_interval,
                rng=random.Random(seed + i),
                lease_duration=lease_duration,
                clock=self.clock,
            )
            node.bootstrap(peers)
            self.router.register(node)
            self.nodes[i] = node

    # ---- deterministic pumping --------------------------------------------
    def settle(self, rounds: int = 50):
        """Process every queued event until the cluster goes quiet. A
        node's batched Ready flush delivers messages AFTER its dispatch
        pass, so quiescence is only real when every inbox is still empty
        at the end of a whole round."""
        for _ in range(rounds):
            busy = False
            for node in self.nodes.values():
                if not node._inbox.empty():
                    busy = True
                node.process_all()
            if not busy and all(n._inbox.empty()
                                for n in self.nodes.values()):
                return

    def tick_all(self, n: int = 1):
        for _ in range(n):
            # advance the shared fake clock in step with the tick so
            # clock-deadline expiries (snapshot resends) stay aligned
            # with tick counts; an externally supplied clock without
            # advance() (e.g. REAL_CLOCK) is left alone
            advance = getattr(self.clock, "advance", None)
            if advance is not None:
                advance(self.TICK_SECONDS)
            for node in self.nodes.values():
                node.tick()
            self.settle()

    def elect(self, raft_id: int) -> RaftNode:
        self.nodes[raft_id].campaign()
        self.settle()
        assert self.nodes[raft_id].is_leader, self.status()
        return self.nodes[raft_id]

    def leader(self) -> RaftNode | None:
        """The acting leader: highest term wins (an isolated stale leader
        keeps believing until it observes the newer term)."""
        leaders = [n for n in self.nodes.values() if n.is_leader]
        return max(leaders, key=lambda n: n.term) if leaders else None

    def _leader_has_quorum(self, node: RaftNode) -> bool:
        members = node.members or {node.id: None}
        reachable = sum(
            1 for p in members
            if p == node.id or self.router.active(node.id, p))
        return reachable >= len(members) // 2 + 1

    def tick_until_leader(self, max_ticks: int = 200) -> RaftNode:
        """Tick until a leader that can actually reach a quorum exists (a
        stale isolated leader keeps its role but cannot commit)."""
        for _ in range(max_ticks):
            self.tick_all()
            candidates = [n for n in self.nodes.values()
                          if n.is_leader and self._leader_has_quorum(n)]
            if candidates:
                return max(candidates, key=lambda n: n.term)
        raise AssertionError(f"no leader after {max_ticks} ticks: {self.status()}")

    def propose(self, data, request_id: str = None) -> bool:
        from ..utils.identity import new_id
        leader = self.leader()
        assert leader is not None
        result = {}
        leader.propose(data, request_id or new_id(),
                       lambda ok, err: result.update(ok=ok, err=err))
        self.settle()
        return result.get("ok", False)

    def status(self):
        return {i: n.status() for i, n in self.nodes.items()}
