"""Raft durable storage: WAL + snapshots, encrypted at rest.

Re-derivation of the reference's encrypted raft storage
(manager/state/raft/storage/: walwrap.go, snapwrap.go, EncryptedRaftLogger):
every appended entry and every snapshot is sealed with a data-encryption key
(DEK) before hitting disk; the DEK can be rotated (re-encrypting the current
snapshot + tail of the WAL). We use Fernet (AES128-CBC + HMAC) from the
`cryptography` package — the stand-in for the reference's NaCl secretbox /
fernet encoders (manager/encryption/).

Layout under `dir`:  wal.jsonl (one sealed record per line), snapshot.bin,
hardstate.json, membership.json.
"""
from __future__ import annotations

import base64
import binascii
import json
import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Any

from cryptography.fernet import Fernet, InvalidToken

from ..rpc import codec
from .messages import ConfChange, Entry
from .node import Peer


log = logging.getLogger("swarmkit_tpu.raft.storage")


class RaftStorageError(Exception):
    """Persisted raft state exists but cannot be decoded (wrong DEK or
    incompatible on-disk format) — distinct from an empty state dir."""


def new_dek() -> bytes:
    return Fernet.generate_key()


class Sealer:
    """Encrypt/decrypt with a current DEK plus optional pending DEK
    (MultiDecrypter semantics from manager/encryption/encryption.go).
    The cipher comes from manager/encryption.py: ChaCha20-Poly1305 by
    default, fernet under FIPS; records written by either (or by the
    pre-framing fernet format) always decrypt."""

    def __init__(self, dek: bytes | None, fips: bool | None = None):
        from ..manager import encryption as enc

        self._enc_mod = enc
        self._fips = fips
        self._encrypter = None
        self._decrypter = enc.MultiDecrypter([])
        if dek:
            self._encrypter, self._decrypter = enc.defaults(dek, fips)

    def add_key(self, dek: bytes):
        enc = self._enc_mod
        encrypter, _ = enc.defaults(dek, self._fips)
        self._encrypter = encrypter
        self._decrypter.add_key(dek, first=True)

    def seal(self, raw: bytes) -> bytes:
        if self._encrypter is None:
            return base64.b64encode(raw)
        return self._enc_mod.seal(self._encrypter, raw)

    def unseal(self, blob: bytes) -> bytes:
        if self._encrypter is None:
            return base64.b64decode(blob)
        try:
            return self._decrypter.unseal(blob)
        except self._enc_mod.DecryptError as exc:
            raise InvalidToken(str(exc)) from exc


@dataclass
class LoadedState:
    term: int = 0
    voted_for: int | None = None
    commit_index: int = 0
    snapshot_index: int = 0
    snapshot_term: int = 0
    snapshot_data: Any = None
    entries: list[Entry] = field(default_factory=list)
    members: dict[int, Peer] = field(default_factory=dict)
    removed: set = field(default_factory=set)


class RaftStorage:
    def __init__(self, dir: str, dek: bytes | None = None):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        self.sealer = Sealer(dek)
        self._lock = threading.Lock()
        self._wal_path = os.path.join(dir, "wal.jsonl")
        self._snap_path = os.path.join(dir, "snapshot.bin")
        self._hs_path = os.path.join(dir, "hardstate.json")
        self._members_path = os.path.join(dir, "membership.json")
        self._wal_file = None

    # ----------------------------------------------------------------- write
    def append_entries(self, entries: list[Entry]):
        with self._lock:
            if self._wal_file is None:
                self._wal_file = open(self._wal_path, "ab")
            for e in entries:
                raw = codec.dumps(e)
                self._wal_file.write(self.sealer.seal(raw) + b"\n")
            self._wal_file.flush()
            os.fsync(self._wal_file.fileno())

    def truncate_from(self, index: int):
        """Drop WAL entries at or after `index` (conflict truncation)."""
        with self._lock:
            self._close_wal()
            kept = []
            for e in self._read_wal():
                if e.index < index:
                    kept.append(e)
            self._rewrite_wal(kept)

    def compact(self, first_index: int):
        """Drop WAL entries below first_index (they live in the snapshot)."""
        with self._lock:
            self._close_wal()
            kept = [e for e in self._read_wal() if e.index >= first_index]
            self._rewrite_wal(kept)

    def save_hard_state(self, term: int, voted_for: int | None, commit: int):
        with self._lock:
            tmp = self._hs_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"term": term, "voted_for": voted_for,
                           "commit": commit}, f)
            os.replace(tmp, self._hs_path)

    def save_membership(self, members: dict[int, Peer],
                        removed: set | None = None):
        """Persist the member map plus the ids of REMOVED members — peers
        keep answering a removed member's messages with the removed
        marker (reference membership.go ErrMemberRemoved), which must
        survive restarts or a rebooted peer would happily talk to it."""
        with self._lock:
            tmp = self._members_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({
                    "members": {str(rid): [p.node_id, p.addr]
                                for rid, p in members.items()},
                    "removed": sorted(removed or ()),
                }, f)
            os.replace(tmp, self._members_path)

    def save_snapshot(self, index: int, term: int, data: Any,
                      members: dict[int, Peer], removed: set | None = None):
        with self._lock:
            payload = codec.dumps({
                "index": index, "term": term, "data": data,
                "members": {rid: (p.node_id, p.addr)
                            for rid, p in members.items()},
                "removed": sorted(removed or ()),
            })
            tmp = self._snap_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(self.sealer.seal(payload))
            os.replace(tmp, self._snap_path)

    # --------------------------------------------------------------- rotation
    def rotate_dek(self, new_key: bytes):
        """Re-seal snapshot + WAL under a new DEK (reference DEK rotation
        handshake, raft.go:730-742)."""
        with self._lock:
            self._close_wal()
            entries = self._read_wal()
            snap = self._read_snapshot()
            old = self.sealer
            self.sealer = Sealer(new_key)
            # still able to read records the OLD keys sealed
            self.sealer._decrypter.merge(old._decrypter)
            self._rewrite_wal(entries)
            if snap is not None:
                payload = codec.dumps(snap)
                tmp = self._snap_path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(self.sealer.seal(payload))
                os.replace(tmp, self._snap_path)

    # ------------------------------------------------------------------ read
    def load(self) -> LoadedState | None:
        with self._lock:
            if not (os.path.exists(self._wal_path)
                    or os.path.exists(self._snap_path)
                    or os.path.exists(self._hs_path)):
                return None
            st = LoadedState()
            snap = self._read_snapshot()
            if snap is not None:
                st.snapshot_index = snap["index"]
                st.snapshot_term = snap["term"]
                st.snapshot_data = snap["data"]
                st.members = {rid: Peer(rid, nid, addr)
                              for rid, (nid, addr) in snap["members"].items()}
                st.removed = {int(r) for r in snap.get("removed", ())}
            if os.path.exists(self._hs_path):
                with open(self._hs_path) as f:
                    hs = json.load(f)
                st.term = hs["term"]
                st.voted_for = hs["voted_for"]
                st.commit_index = hs["commit"]
            if os.path.exists(self._members_path):
                with open(self._members_path) as f:
                    raw = json.load(f)
                if "members" in raw:
                    flat = raw["members"]
                    st.removed = {int(r) for r in raw.get("removed", ())}
                else:            # legacy flat format (pre removed-ids)
                    flat = raw
                st.members = {
                    int(rid): Peer(int(rid), nid, addr)
                    for rid, (nid, addr) in flat.items()
                }
            st.entries = [e for e in self._read_wal()
                          if e.index > st.snapshot_index]
            return st

    # -------------------------------------------------------------- internals
    def _read_wal(self) -> list[Entry]:
        if not os.path.exists(self._wal_path):
            return []
        out = []
        with open(self._wal_path, "rb") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(codec.loads(self.sealer.unseal(line)))
                except (InvalidToken, codec.WireDecodeError, EOFError,
                        binascii.Error) as exc:
                    if not out:
                        # the FIRST record failing to decode is not a torn
                        # tail — it is the wrong DEK or an incompatible WAL
                        # format; silently returning [] would discard the
                        # entire persisted raft state
                        raise RaftStorageError(
                            f"cannot decode WAL {self._wal_path}: {exc}"
                        ) from exc
                    log.warning("raft WAL %s: torn tail after %d records (%s)",
                                self._wal_path, len(out), exc)
                    break  # torn tail write: stop at first bad record
        return out

    def _read_snapshot(self):
        if not os.path.exists(self._snap_path):
            return None
        with open(self._snap_path, "rb") as f:
            blob = f.read()
        try:
            return codec.loads(self.sealer.unseal(blob))
        except (InvalidToken, codec.WireDecodeError, EOFError,
                binascii.Error) as exc:
            # snapshots are written atomically (tmp + rename), so a decode
            # failure means wrong DEK or incompatible format, not a torn
            # write — fail loudly rather than restart from empty state
            raise RaftStorageError(
                f"cannot decode snapshot {self._snap_path}: {exc}") from exc

    def _rewrite_wal(self, entries: list[Entry]):
        tmp = self._wal_path + ".tmp"
        with open(tmp, "wb") as f:
            for e in entries:
                f.write(self.sealer.seal(codec.dumps(e)) + b"\n")
        os.replace(tmp, self._wal_path)

    def _close_wal(self):
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
