"""Raft durable storage: segmented WAL + snapshots, encrypted at rest.

Re-derivation of the reference's encrypted raft storage
(manager/state/raft/storage/: walwrap.go, snapwrap.go, EncryptedRaftLogger):
every appended entry and every snapshot is sealed with a data-encryption key
(DEK) before hitting disk; the DEK can be rotated (re-encrypting the current
snapshot + tail of the WAL). We use Fernet (AES128-CBC + HMAC) from the
`cryptography` package — the stand-in for the reference's NaCl secretbox /
fernet encoders (manager/encryption/). Without the wheel, plaintext
(base64-framed) storage still works; only DEK-sealed storage is disabled.

Layout under `dir`:  wal-<seq>.jsonl segments (one sealed record per line;
a legacy single-file wal.jsonl is read as the oldest segment), snapshot.bin,
hardstate.json, membership.json.

Group commit: `append_entries` writes its whole batch with ONE write + ONE
fsync (the etcd WAL SaveEntries shape); `compact`/`truncate_from` drop whole
sealed segments instead of rewriting the entire log under the lock on the
raft worker thread. A torn tail found while reading is REPAIRED (the segment
is truncated at the tear and later segments dropped, reference
ReadRepairWAL) so post-recovery appends can never land after a corrupt
record and get silently discarded by the next reload.
"""
from __future__ import annotations

import base64
import binascii
import glob
import json
import logging
import os
import re
import threading
from dataclasses import dataclass, field
from typing import Any

try:
    from cryptography.fernet import Fernet, InvalidToken
except ImportError:                      # container without the wheel:
    Fernet = None                        # plaintext storage still works

    class InvalidToken(Exception):       # type: ignore[no-redef]
        pass

from ..analysis.lockgraph import make_lock
from ..rpc import codec
from ..utils import failpoints
from ..utils.metrics import counter_family
from .messages import ConfChange, Entry
from .node import Peer


log = logging.getLogger("swarmkit_tpu.raft.storage")

# seal the active WAL segment once it grows past this; sealed segments are
# immutable and compaction/truncation drop them whole
SEGMENT_MAX_BYTES = 1 << 20

_SEG_RE = re.compile(r"wal-(\d{8})\.jsonl$")

# group-commit observability: tests and the bench row assert coalescing
# actually happened (amortized fsyncs-per-commit < 1 under load)
_fsyncs = counter_family(
    "swarm_raft_storage_fsync_total",
    "fsync calls by the raft storage layer", ("kind",))


class RaftStorageError(Exception):
    """Persisted raft state exists but cannot be decoded (wrong DEK or
    incompatible on-disk format) — distinct from an empty state dir."""


def new_dek() -> bytes:
    if Fernet is None:
        raise RuntimeError(
            "encrypted raft storage needs the `cryptography` package")
    return Fernet.generate_key()


def _fsync_dir(path: str):
    """Make a create/rename in `path` durable (fsync the directory)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class Sealer:
    """Encrypt/decrypt with a current DEK plus optional pending DEK
    (MultiDecrypter semantics from manager/encryption/encryption.go).
    The cipher comes from manager/encryption.py: ChaCha20-Poly1305 by
    default, fernet under FIPS; records written by either (or by the
    pre-framing fernet format) always decrypt. With no DEK the payload is
    base64-framed plaintext and the encryption module is never imported
    (it needs the optional `cryptography` wheel)."""

    def __init__(self, dek: bytes | None, fips: bool | None = None):
        self._fips = fips
        self._enc_mod = None
        self._encrypter = None
        self._decrypter = None
        if dek:
            self._load_enc()
            self._encrypter, self._decrypter = \
                self._enc_mod.defaults(dek, fips)

    def _load_enc(self):
        if self._enc_mod is None:
            from ..manager import encryption as enc

            self._enc_mod = enc
            if self._decrypter is None:
                self._decrypter = enc.MultiDecrypter([])

    def add_key(self, dek: bytes):
        self._load_enc()
        encrypter, _ = self._enc_mod.defaults(dek, self._fips)
        self._encrypter = encrypter
        self._decrypter.add_key(dek, first=True)

    def seal(self, raw: bytes) -> bytes:
        if self._encrypter is None:
            return base64.b64encode(raw)
        return self._enc_mod.seal(self._encrypter, raw)

    def unseal(self, blob: bytes) -> bytes:
        if self._encrypter is None:
            return base64.b64decode(blob)
        try:
            return self._decrypter.unseal(blob)
        except self._enc_mod.DecryptError as exc:
            raise InvalidToken(str(exc)) from exc


@dataclass
class LoadedState:
    term: int = 0
    voted_for: int | None = None
    commit_index: int = 0
    snapshot_index: int = 0
    snapshot_term: int = 0
    snapshot_data: Any = None
    entries: list[Entry] = field(default_factory=list)
    members: dict[int, Peer] = field(default_factory=dict)
    removed: set = field(default_factory=set)


class RaftStorage:
    def __init__(self, dir: str, dek: bytes | None = None,
                 segment_bytes: int = SEGMENT_MAX_BYTES):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        self.sealer = Sealer(dek)
        self._lock = make_lock('raft.storage.lock')
        self._legacy_wal_path = os.path.join(dir, "wal.jsonl")
        self._snap_path = os.path.join(dir, "snapshot.bin")
        self._hs_path = os.path.join(dir, "hardstate.json")
        self._members_path = os.path.join(dir, "membership.json")
        self._segment_bytes = segment_bytes
        self._wal_file = None            # handle to the ACTIVE segment
        self._active_seq: int | None = None
        self._active_bytes = 0
        # seq -> (first_index, last_index); learned on read or append, used
        # to drop/keep whole segments without re-reading them
        self._bounds: dict[int, tuple[int, int]] = {}
        # group-commit metrics (plain ints: written under self._lock; the
        # bench row and the coalescing tests read them)
        self.wal_fsyncs = 0              # one per append_entries batch
        self.meta_fsyncs = 0             # hardstate/membership/snapshot/dir
        self.append_batches = 0
        self.entries_appended = 0
        # set when a failed batch could not be rolled back: the active
        # segment may carry a torn tail, so further appends would land
        # AFTER it and be dropped by the next load's ReadRepair —
        # refuse them until probe() confirms writability (it repairs)
        self._wedged = False
        self._torn_boundary: tuple[str, int] | None = None

    # ------------------------------------------------------------- segments
    def _seg_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"wal-{seq:08d}.jsonl")

    def _segments(self) -> list[tuple[int, str]]:
        """All WAL segments in read order. The legacy single-file layout
        (wal.jsonl) reads as segment 0; new writes never extend it."""
        segs = []
        if os.path.exists(self._legacy_wal_path):
            segs.append((0, self._legacy_wal_path))
        for path in glob.glob(os.path.join(self.dir, "wal-*.jsonl")):
            m = _SEG_RE.search(path)
            if m:
                segs.append((int(m.group(1)), path))
        segs.sort()
        return segs

    def _open_active(self):
        if self._wal_file is None:
            segs = self._segments()
            seq = (segs[-1][0] + 1) if segs else 1
            path = self._seg_path(seq)
            self._wal_file = open(path, "ab")
            self._active_seq = seq
            self._active_bytes = 0
            _fsync_dir(self.dir)         # the new dirent must be durable
            self.meta_fsyncs += 1
            _fsyncs.inc(("dir",))
        return self._wal_file

    def _seal_active(self):
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
            self._active_seq = None
            self._active_bytes = 0

    # ----------------------------------------------------------------- write
    def append_entries(self, entries: list[Entry]):
        """Group commit: the whole batch is one buffered write + ONE fsync
        (the raft worker's Ready flush calls this once per batch, not once
        per proposal).

        Failure contract: the batch is ATOMIC. Any write/fsync error
        rolls the active segment back to its pre-batch length — so a
        torn short-write never leaves a tail that load-time ReadRepair
        would heal by DROPPING later segments (post-failure appends must
        survive the next reload) — and re-raises to the caller, which
        owns failing the staged proposals. If even the rollback fails,
        the storage wedges and refuses appends until `probe()` confirms
        the disk is writable again."""
        if not entries:
            return
        with self._lock:
            if self._wedged:
                raise OSError(
                    "raft WAL wedged after a failed rollback; "
                    "probe() must confirm writability first")
            f = self._open_active()
            buf = b"".join(self.sealer.seal(codec.dumps(e)) + b"\n"
                           for e in entries)
            try:
                # failpoint `raft.wal.write`: error before any byte lands
                failpoints.fp("raft.wal.write")
                # failpoint `raft.wal.torn_write` (value = fraction): a
                # SHORT write reaches disk, then the device errors — the
                # torn-tail shape a crash mid-batch leaves behind
                torn = failpoints.fp_value("raft.wal.torn_write")
                if torn is not None:
                    cut = max(1, min(len(buf) - 1,
                                     int(len(buf) * float(torn))))
                    f.write(buf[:cut])
                    f.flush()
                    os.fsync(f.fileno())
                    raise OSError("injected torn write")
                f.write(buf)
                f.flush()
                # failpoint `raft.wal.fsync`: fsync error — arm with
                # failpoints.enospc for the read-only degradation path
                failpoints.fp("raft.wal.fsync")
                os.fsync(f.fileno())
            except OSError:
                self._rollback_active(f)
                raise
            self.wal_fsyncs += 1
            self.append_batches += 1
            self.entries_appended += len(entries)
            _fsyncs.inc(("wal",))
            self._active_bytes += len(buf)
            seq = self._active_seq
            first, last = entries[0].index, entries[-1].index
            old = self._bounds.get(seq)
            self._bounds[seq] = ((min(old[0], first), last) if old
                                 else (first, last))
            if self._active_bytes >= self._segment_bytes:
                self._seal_active()

    def _rollback_active(self, f):
        """Restore the active segment to its pre-batch length after a
        failed group append (called under self._lock). `_active_bytes`
        is the last known-good boundary, so truncating back to it makes
        the failed batch atomic on disk. If the rollback itself fails,
        the storage wedges: the sealed segment's byte boundary is
        remembered so probe() can finish the repair once the disk
        recovers."""
        try:
            f.truncate(self._active_bytes)
            f.flush()
            os.fsync(f.fileno())
            self.meta_fsyncs += 1
            _fsyncs.inc(("meta",))
        except OSError:
            log.exception("raft WAL: rollback of a failed batch failed; "
                          "wedging storage until a successful probe")
            self._wedged = True
            self._torn_boundary = (self._seg_path(self._active_seq),
                                   self._active_bytes)
            self._seal_active()

    def probe(self) -> bool:
        """Writability probe for the read-only degradation loop: True
        when the disk accepts a small durable write again. Goes through
        the same `raft.wal.fsync` failpoint as the group append, so
        injected ENOSPC keeps the caller degraded until disarmed. A
        successful probe also completes the deferred torn-tail repair of
        a wedged storage (truncate back to the last good boundary)."""
        path = os.path.join(self.dir, ".probe")
        with self._lock:
            try:
                failpoints.fp("raft.wal.fsync")
                with open(path, "wb") as f:
                    f.write(b"ok")
                    f.flush()
                    os.fsync(f.fileno())
                os.unlink(path)
            except OSError:
                return False
            if self._wedged:
                if self._torn_boundary is not None:
                    try:
                        seg_path, good = self._torn_boundary
                        if os.path.exists(seg_path):
                            with open(seg_path, "rb+") as f:
                                f.truncate(good)
                                f.flush()
                                os.fsync(f.fileno())
                    except OSError:
                        return False
                self._torn_boundary = None
                self._wedged = False
            return True

    def truncate_from(self, index: int):
        """Drop WAL entries at or after `index` (conflict truncation).
        Whole segments past the boundary are unlinked; only the boundary
        segment is rewritten."""
        with self._lock:
            self._seal_active()
            for seq, path in reversed(self._segments()):
                bounds = self._seg_bounds(seq, path)
                if bounds is None:
                    continue
                first, last = bounds
                if last < index:
                    continue
                if first >= index:
                    os.unlink(path)
                    self._bounds.pop(seq, None)
                else:
                    entries, _ = self._read_segment(path)
                    kept = [e for e in entries if e.index < index]
                    self._rewrite_segment(seq, path, kept)
            _fsync_dir(self.dir)
            self.meta_fsyncs += 1
            _fsyncs.inc(("dir",))

    def compact(self, first_index: int):
        """Drop WAL segments fully below first_index (they live in the
        snapshot). Segment-granular: the boundary segment is kept whole —
        its below-snapshot records are filtered at load — so compaction
        never rewrites data on the worker thread."""
        with self._lock:
            self._seal_active()
            dropped = False
            for seq, path in self._segments():
                bounds = self._seg_bounds(seq, path)
                if bounds is None or bounds[1] < first_index:
                    os.unlink(path)
                    self._bounds.pop(seq, None)
                    dropped = True
            if dropped:
                _fsync_dir(self.dir)
                self.meta_fsyncs += 1
                _fsyncs.inc(("dir",))

    def save_hard_state(self, term: int, voted_for: int | None, commit: int):
        with self._lock:
            self._atomic_write(
                self._hs_path,
                json.dumps({"term": term, "voted_for": voted_for,
                            "commit": commit}).encode())

    def save_membership(self, members: dict[int, Peer],
                        removed: set | None = None):
        """Persist the member map plus the ids of REMOVED members — peers
        keep answering a removed member's messages with the removed
        marker (reference membership.go ErrMemberRemoved), which must
        survive restarts or a rebooted peer would happily talk to it."""
        with self._lock:
            self._atomic_write(
                self._members_path,
                json.dumps({
                    "members": {str(rid): [p.node_id, p.addr]
                                for rid, p in members.items()},
                    "removed": sorted(removed or ()),
                }).encode())

    def save_snapshot(self, index: int, term: int, data: Any,
                      members: dict[int, Peer], removed: set | None = None):
        with self._lock:
            payload = codec.dumps({
                "index": index, "term": term, "data": data,
                "members": {rid: (p.node_id, p.addr)
                            for rid, p in members.items()},
                "removed": sorted(removed or ()),
            })
            self._atomic_write(self._snap_path, self.sealer.seal(payload))

    def _atomic_write(self, path: str, data: bytes):
        """tmp + fsync + rename + dir fsync: a crash after the rename must
        never surface an empty or stale file (the pre-fsync version could —
        the rename could reach disk before the tmp file's data blocks)."""
        tmp = path + ".tmp"
        # failpoint `raft.meta.write`: hardstate/membership/snapshot
        # write failures (incl. ENOSPC); atomicity means the old file
        # survives intact
        failpoints.fp("raft.meta.write")
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.dir)
        self.meta_fsyncs += 2
        _fsyncs.inc(("meta",), 2)

    # --------------------------------------------------------------- rotation
    def rotate_dek(self, new_key: bytes):
        """Re-seal snapshot + WAL under a new DEK (reference DEK rotation
        handshake, raft.go:730-742). The re-sealed log lands in a single
        fresh segment; old segments are unlinked only after it is durable,
        and the read-side supersede rule makes a crash between the two
        steps recoverable (the new segment's records win)."""
        with self._lock:
            self._seal_active()
            old_segs = self._segments()
            entries = self._read_wal()
            snap = self._read_snapshot()
            old = self.sealer
            self.sealer = Sealer(new_key)
            # still able to read records the OLD keys sealed
            if old._decrypter is not None:
                self.sealer._decrypter.merge(old._decrypter)
            new_seq = (old_segs[-1][0] + 1) if old_segs else 1
            self._rewrite_segment(new_seq, self._seg_path(new_seq), entries)
            for _seq, path in old_segs:
                os.unlink(path)
            self._bounds = {k: v for k, v in self._bounds.items()
                            if k == new_seq}
            _fsync_dir(self.dir)
            self.meta_fsyncs += 1
            _fsyncs.inc(("dir",))
            if snap is not None:
                payload = codec.dumps(snap)
                self._atomic_write(self._snap_path, self.sealer.seal(payload))

    # ------------------------------------------------------------------ read
    def load(self) -> LoadedState | None:
        with self._lock:
            if not (self._segments()
                    or os.path.exists(self._snap_path)
                    or os.path.exists(self._hs_path)):
                return None
            st = LoadedState()
            snap = self._read_snapshot()
            if snap is not None:
                st.snapshot_index = snap["index"]
                st.snapshot_term = snap["term"]
                st.snapshot_data = snap["data"]
                st.members = {rid: Peer(rid, nid, addr)
                              for rid, (nid, addr) in snap["members"].items()}
                st.removed = {int(r) for r in snap.get("removed", ())}
            if os.path.exists(self._hs_path):
                with open(self._hs_path) as f:
                    hs = json.load(f)
                st.term = hs["term"]
                st.voted_for = hs["voted_for"]
                st.commit_index = hs["commit"]
            if os.path.exists(self._members_path):
                with open(self._members_path) as f:
                    raw = json.load(f)
                if "members" in raw:
                    flat = raw["members"]
                    st.removed = {int(r) for r in raw.get("removed", ())}
                else:            # legacy flat format (pre removed-ids)
                    flat = raw
                st.members = {
                    int(rid): Peer(int(rid), nid, addr)
                    for rid, (nid, addr) in flat.items()
                }
            st.entries = [e for e in self._read_wal(repair=True)
                          if e.index > st.snapshot_index]
            return st

    # -------------------------------------------------------------- internals
    def _seg_bounds(self, seq: int, path: str) -> tuple[int, int] | None:
        """(first_index, last_index) of a segment, reading it once if this
        process has not seen it yet. None for an empty segment."""
        bounds = self._bounds.get(seq)
        if bounds is None:
            entries, _ = self._read_segment(path)
            if not entries:
                return None
            bounds = (entries[0].index, entries[-1].index)
            self._bounds[seq] = bounds
        return bounds

    def _read_segment(self, path: str,
                      first_of_wal: bool = False) -> tuple[list[Entry],
                                                           int | None]:
        """Decode one segment. Returns (entries, torn_offset): torn_offset
        is the byte offset of the first undecodable record (None when the
        segment is clean). A failure on the very first record of the whole
        WAL is a wrong DEK / incompatible format, not a torn tail."""
        if not os.path.exists(path):
            return [], None
        out: list[Entry] = []
        offset = 0
        with open(path, "rb") as f:
            for line in f:
                stripped = line.strip()
                if not stripped:
                    offset += len(line)
                    continue
                try:
                    out.append(codec.loads(self.sealer.unseal(stripped)))
                except (InvalidToken, codec.WireDecodeError, EOFError,
                        binascii.Error) as exc:
                    if first_of_wal and not out:
                        # the FIRST record of the whole WAL failing to
                        # decode is not a torn tail — it is the wrong DEK
                        # or an incompatible format; silently returning []
                        # would discard the entire persisted raft state
                        raise RaftStorageError(
                            f"cannot decode WAL {path}: {exc}") from exc
                    log.warning(
                        "raft WAL %s: torn record after %d entries (%s)",
                        path, len(out), exc)
                    return out, offset
                offset += len(line)
        return out, None

    def _read_wal(self, repair: bool = False) -> list[Entry]:
        """All WAL entries across segments in append order. A record whose
        index is <= its predecessor's SUPERSEDES the tail back to that
        index (the replay rule that makes a crashed truncation/rotation
        rewrite recoverable: the re-written records win). With repair=True
        a torn tail is truncated on disk and later segments dropped
        (reference ReadRepairWAL) — records after a tear may predate a
        truncate_from rewrite, and resurrecting them forks raft history,
        while leaving the tear in place would silently discard every
        record appended after it on the NEXT reload."""
        out: list[Entry] = []
        segs = self._segments()
        for pos, (seq, path) in enumerate(segs):
            entries, torn_offset = self._read_segment(
                path, first_of_wal=(pos == 0))
            for e in entries:
                while out and out[-1].index >= e.index:
                    out.pop()
                out.append(e)
            if torn_offset is not None:
                if repair:
                    self._repair(segs[pos:], torn_offset)
                break
        return out

    def _repair(self, torn_segs: list[tuple[int, str]], torn_offset: int):
        seq, path = torn_segs[0]
        log.warning("raft WAL: repairing torn tail — truncating %s at "
                    "byte %d, dropping %d later segment(s)",
                    path, torn_offset, len(torn_segs) - 1)
        if torn_offset == 0:
            os.unlink(path)
            self._bounds.pop(seq, None)
        else:
            with open(path, "rb+") as f:
                f.truncate(torn_offset)
                f.flush()
                os.fsync(f.fileno())
            self.meta_fsyncs += 1
            _fsyncs.inc(("meta",))
            self._bounds.pop(seq, None)   # re-learned on next touch
        for later_seq, later_path in torn_segs[1:]:
            os.unlink(later_path)
            self._bounds.pop(later_seq, None)
        _fsync_dir(self.dir)
        self.meta_fsyncs += 1
        _fsyncs.inc(("dir",))

    def _read_snapshot(self):
        if not os.path.exists(self._snap_path):
            return None
        with open(self._snap_path, "rb") as f:
            blob = f.read()
        try:
            return codec.loads(self.sealer.unseal(blob))
        except (InvalidToken, codec.WireDecodeError, EOFError,
                binascii.Error) as exc:
            # snapshots are written atomically (tmp + rename), so a decode
            # failure means wrong DEK or incompatible format, not a torn
            # write — fail loudly rather than restart from empty state
            raise RaftStorageError(
                f"cannot decode snapshot {self._snap_path}: {exc}") from exc

    def _rewrite_segment(self, seq: int, path: str, entries: list[Entry]):
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            for e in entries:
                f.write(self.sealer.seal(codec.dumps(e)) + b"\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self.meta_fsyncs += 1
        _fsyncs.inc(("meta",))
        if entries:
            self._bounds[seq] = (entries[0].index, entries[-1].index)
        else:
            os.unlink(path)
            self._bounds.pop(seq, None)

    def _close_wal(self):
        with self._lock:
            self._seal_active()
