"""Broadcast watch queue (reference: watch/watch.go:20-186, watch/queue/queue.go).

Components subscribe with an optional matcher; `publish` fans events out to
per-subscriber unbounded deques guarded by one condition variable. A bounded
`limit` mirrors the reference's LimitQueue: a slow subscriber whose queue
exceeds the limit is closed rather than blocking publishers.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Iterable

Matcher = Callable[[Any], bool]


class ChannelClosed(Exception):
    pass


class Channel:
    """One subscriber's event stream."""

    def __init__(self, matcher: Matcher | None, limit: int | None):
        self._matcher = matcher
        self._limit = limit
        self._events: deque[Any] = deque()
        self._cond = threading.Condition()
        self._closed = False

    def _offer(self, event: Any) -> None:
        if self._matcher is not None and not self._matcher(event):
            return
        with self._cond:
            if self._closed:
                return
            if self._limit is not None and len(self._events) >= self._limit:
                # Slow-subscriber protection (watch/queue/queue.go LimitQueue).
                self._closed = True
                self._cond.notify_all()
                return
            self._events.append(event)
            self._cond.notify_all()

    def get(self, timeout: float | None = None) -> Any:
        with self._cond:
            if not self._cond.wait_for(lambda: self._events or self._closed, timeout):
                raise TimeoutError("no event within timeout")
            if self._events:
                return self._events.popleft()
            raise ChannelClosed()

    def try_get(self) -> Any | None:
        with self._cond:
            if self._events:
                return self._events.popleft()
            if self._closed:
                raise ChannelClosed()
            return None

    def drain(self) -> list[Any]:
        with self._cond:
            out = list(self._events)
            self._events.clear()
            return out

    def wait_any(self, timeout: float | None = None) -> bool:
        """Block until at least one event is queued (or closed). True if events."""
        with self._cond:
            self._cond.wait_for(lambda: self._events or self._closed, timeout)
            if self._events:
                return True
            if self._closed:
                raise ChannelClosed()
            return False

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    def __iter__(self):
        while True:
            try:
                yield self.get()
            except ChannelClosed:
                return


class WatchQueue:
    """Fan-out publisher (reference: watch/watch.go Queue)."""

    def __init__(self, default_limit: int | None = 10000):
        self._subs: list[Channel] = []
        self._lock = threading.Lock()
        self._default_limit = default_limit
        self._closed = False

    def watch(self, matcher: Matcher | None = None, limit: int | None = -1) -> Channel:
        if limit == -1:
            limit = self._default_limit
        ch = Channel(matcher, limit)
        with self._lock:
            if self._closed:
                ch.close()
            else:
                self._subs.append(ch)
        return ch

    def callback_watch(self, cb: Callable[[Any], None], matcher: Matcher | None = None):
        """Synchronous-callback subscription (watch/watch.go CallbackWatch)."""

        class _CallbackChannel(Channel):
            def _offer(self, event):
                if matcher is not None and not matcher(event):
                    return
                cb(event)

        ch = _CallbackChannel(None, None)
        with self._lock:
            self._subs.append(ch)
        return ch

    def publish(self, event: Any) -> None:
        with self._lock:
            subs = list(self._subs)
        for ch in subs:
            ch._offer(event)

    def publish_all(self, events: Iterable[Any]) -> None:
        for e in events:
            self.publish(e)

    def stop_watch(self, ch: Channel) -> None:
        ch.close()
        with self._lock:
            try:
                self._subs.remove(ch)
            except ValueError:
                pass

    def close(self) -> None:
        with self._lock:
            self._closed = True
            subs = list(self._subs)
            self._subs.clear()
        for ch in subs:
            ch.close()


def match_events(*predicates: Matcher) -> Matcher:
    """OR-combination matcher, mirroring state.Matcher(specifiers...)."""

    def matcher(event: Any) -> bool:
        return any(p(event) for p in predicates)

    return matcher
