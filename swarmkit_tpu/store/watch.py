"""Broadcast watch queue (reference: watch/watch.go:20-186, watch/queue/queue.go).

Components subscribe with an optional matcher; `publish` fans events out to
per-subscriber unbounded deques guarded by one condition variable. A bounded
`limit` mirrors the reference's LimitQueue: a slow subscriber whose queue
exceeds the limit is closed rather than blocking publishers.
"""
from __future__ import annotations

import os
import threading
from ..analysis.lockgraph import make_lock, make_rlock
from collections import deque
from typing import Any, Callable, Iterable

Matcher = Callable[[Any], bool]


class ChannelClosed(Exception):
    pass


class Channel:
    """One subscriber's event stream."""

    def __init__(self, matcher: Matcher | None, limit: int | None):
        self._matcher = matcher
        self._limit = limit
        self._events: deque[Any] = deque()
        self._cond = threading.Condition(make_rlock("store.watch.cond"))
        self._closed = False
        self._error: Exception | None = None

    def _raise_closed(self):
        exc = ChannelClosed(str(self._error) if self._error else "")
        exc.error = self._error   # cause, when the closer supplied one
        raise exc

    def _offer(self, event: Any) -> bool:
        """Returns False when the channel could not take the event — it
        was already closed, or this offer tripped the slow-subscriber
        limit. Publishers that track per-subscriber delivery state (the
        dispatcher's known-assignment maps) key off this so a shed
        subscriber's bookkeeping is never advanced past what it saw."""
        if self._matcher is not None and not self._matcher(event):
            return True            # filtered out, not a delivery failure
        with self._cond:
            if self._closed:
                return False
            if self._limit is not None and len(self._events) >= self._limit:
                # Slow-subscriber protection (watch/queue/queue.go LimitQueue).
                self._closed = True
                self._cond.notify_all()
                return False
            self._events.append(event)
            self._cond.notify_all()
            return True

    def _offer_many(self, events: list) -> None:
        """Batched fan-out: one matcher pass, ONE lock acquisition and ONE
        notify for the whole batch (the store publishes each commit's
        events as a batch, so this is the per-commit delivery path).
        Observable behavior matches per-event _offer calls, including the
        slow-subscriber close after exactly `limit` queued events."""
        m = self._matcher
        if m is not None:
            events = [e for e in events if m(e)]
            if not events:
                return
        with self._cond:
            if self._closed:
                return
            if self._limit is not None:
                room = self._limit - len(self._events)
                if len(events) > room:
                    self._events.extend(events[:room])
                    self._closed = True
                    self._cond.notify_all()
                    return
            self._events.extend(events)
            self._cond.notify_all()

    def get(self, timeout: float | None = None) -> Any:
        with self._cond:
            if not self._cond.wait_for(lambda: self._events or self._closed, timeout):
                raise TimeoutError("no event within timeout")
            if self._events:
                return self._events.popleft()
            self._raise_closed()

    def try_get(self) -> Any | None:
        with self._cond:
            if self._events:
                return self._events.popleft()
            if self._closed:
                self._raise_closed()
            return None

    def drain(self) -> list[Any]:
        with self._cond:
            out = list(self._events)
            self._events.clear()
            return out

    def wait_any(self, timeout: float | None = None) -> bool:
        """Block until at least one event is queued (or closed). True if events."""
        with self._cond:
            self._cond.wait_for(lambda: self._events or self._closed, timeout)
            if self._events:
                return True
            if self._closed:
                self._raise_closed()
            return False

    def close(self, error: Exception | None = None) -> None:
        """Close the stream; `error` (e.g. a server ERR on an RPC stream)
        is carried to consumers on the ChannelClosed they receive."""
        with self._cond:
            if error is not None and self._error is None:
                self._error = error
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    def __iter__(self):
        while True:
            try:
                yield self.get()
            except ChannelClosed:
                return


class WatchQueue:
    """Fan-out publisher (reference: watch/watch.go Queue).

    The subscriber list is copy-on-write (a tuple swapped under `_lock`):
    `publish`/`publish_all` read one immutable snapshot with NO lock or
    copy on the hot path — at 10k subscribers the old list-copy-per-event
    dominated publish cost (round-2 bench: 1.4M deliveries/s; the
    reference benches this exact fan-out, watch/watch_test.go:153-216)."""

    def __init__(self, default_limit: int | None = 10000):
        self._subs: tuple[Channel, ...] = ()
        self._lock = make_lock('store.watch.lock')
        self._default_limit = default_limit
        self._closed = False

    def watch(self, matcher: Matcher | None = None, limit: int | None = -1) -> Channel:
        if limit == -1:
            limit = self._default_limit
        ch = Channel(matcher, limit)
        with self._lock:
            if self._closed:
                ch.close()
            else:
                self._subs = self._subs + (ch,)
        return ch

    def callback_watch(self, cb: Callable[[Any], None], matcher: Matcher | None = None):
        """Synchronous-callback subscription (watch/watch.go CallbackWatch)."""

        class _CallbackChannel(Channel):
            def _offer(self, event):
                if matcher is not None and not matcher(event):
                    return True
                cb(event)
                return True

            def _offer_many(self, events):
                for event in events:
                    self._offer(event)

        ch = _CallbackChannel(None, None)
        # synchronous-callback contract: the cb runs on the PUBLISHING
        # thread — the sharded queue must never move it onto a pump
        ch._inline = True
        with self._lock:
            self._subs = self._subs + (ch,)
        return ch

    def has_watchers(self) -> bool:
        """True when any subscriber would see a published event — the
        gate for the store's lazy (event-silent) columnar wave path."""
        return bool(self._subs)

    def publish(self, event: Any) -> None:
        for ch in self._subs:
            ch._offer(event)

    def publish_all(self, events: Iterable[Any]) -> None:
        """Batched publish — what the store uses per commit: each
        subscriber pays one lock/notify per BATCH, not per event."""
        events = events if isinstance(events, list) else list(events)
        if not events:
            return
        for ch in self._subs:
            ch._offer_many(events)

    def stop_watch(self, ch: Channel) -> None:
        ch.close()
        with self._lock:
            if ch in self._subs:
                self._subs = tuple(c for c in self._subs if c is not ch)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            subs = self._subs
            self._subs = ()
        for ch in subs:
            ch.close()


# -- sharded fan-out (ISSUE 20) ---------------------------------------------
#
# One publish loop serializes 100k watchers: the queue walks every
# subscriber channel on the publishing (store-commit) thread. The sharded
# queue stripes the copy-on-write subscriber tuple across a small shared
# pump pool — per-subscriber delivery order is preserved because each
# publish barriers on its stripes before returning and store commits
# already serialize publishes. Callback channels (synchronous-cb
# contract) and small fan-outs stay on the caller thread, so the plain
# queue remains the exact behavioral oracle.

_PUMP_POOL = None
_PUMP_POOL_LOCK = make_lock("store.watch.pump_pool")


def default_watch_shards() -> int:
    """Stripe count for sharded watch fan-out (the log plane's shape):
    min(4, cores), overridable via SWARMKIT_TPU_LOGBROKER_SHARDS."""
    env = os.environ.get("SWARMKIT_TPU_LOGBROKER_SHARDS", "")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, min(4, os.cpu_count() or 1))


def _pump_pool():
    """Lazy PROCESS-GLOBAL pool: stores have no stop lifecycle, so
    per-queue pump threads would leak one set per store (the test suite
    builds thousands). Daemon workers, shared by every sharded queue."""
    global _PUMP_POOL
    with _PUMP_POOL_LOCK:
        if _PUMP_POOL is None:
            from concurrent.futures import ThreadPoolExecutor
            _PUMP_POOL = ThreadPoolExecutor(
                max_workers=max(2, default_watch_shards()),
                thread_name_prefix="watch-pump")
        return _PUMP_POOL


class ShardedWatchQueue(WatchQueue):
    """WatchQueue with striped parallel fan-out (ISSUE 20).

    Observable behavior is identical to the serial queue — same channels,
    same per-subscriber event order, same slow-subscriber close — only
    the fan-out walk is partitioned. Publishes below MIN_PARALLEL
    subscribers take the serial oracle path (pool dispatch costs more
    than the walk)."""

    MIN_PARALLEL = 64

    def __init__(self, default_limit: int | None = 10000,
                 shards: int | None = None):
        super().__init__(default_limit)
        self.shards = max(1, int(shards if shards is not None
                                 else default_watch_shards()))

    def publish(self, event: Any) -> None:
        self.publish_all([event])

    def publish_all(self, events: Iterable[Any]) -> None:
        events = events if isinstance(events, list) else list(events)
        if not events:
            return
        subs = self._subs          # immutable snapshot (copy-on-write)
        if self.shards <= 1 or len(subs) < self.MIN_PARALLEL:
            for ch in subs:
                ch._offer_many(events)
            return
        work = []
        for ch in subs:
            if getattr(ch, "_inline", False):
                ch._offer_many(events)   # callback cbs stay on this thread
            else:
                work.append(ch)
        if len(work) < self.MIN_PARALLEL:
            for ch in work:
                ch._offer_many(events)
            return
        pool = _pump_pool()
        futs = [pool.submit(self._offer_stripe, work[i::self.shards], events)
                for i in range(self.shards)]
        # synchronous barrier: per-subscriber ordering depends on this
        # publish finishing before the store's next commit publishes;
        # result() also re-raises a stripe's failure on the publish path
        # exactly where the serial walk would have raised
        for f in futs:
            f.result()

    @staticmethod
    def _offer_stripe(chans, events):
        for ch in chans:
            ch._offer_many(events)


def make_watch_queue(default_limit: int | None = 10000) -> WatchQueue:
    """The store's constructor: sharded fan-out unless the log-plane kill
    switch (SWARMKIT_TPU_NO_SHARDED_LOGS=1) selects the serial oracle."""
    if os.environ.get("SWARMKIT_TPU_NO_SHARDED_LOGS", ""):
        return WatchQueue(default_limit)
    return ShardedWatchQueue(default_limit)


def match_events(*predicates: Matcher) -> Matcher:
    """OR-combination matcher, mirroring state.Matcher(specifiers...)."""

    def matcher(event: Any) -> bool:
        return any(p(event) for p in predicates)

    return matcher
