"""Find selectors (reference: manager/state/store/by.go, 246 lines).

A selector is a small object with `match(obj)` and optionally an index hint
(`index_key()`), which the store uses to narrow the candidate set before
exact matching — the analogue of memdb's secondary-index iterators
(memory.go:663-824 findIterators).
"""
from __future__ import annotations

from typing import Any


class By:
    def match(self, obj) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def index_key(self) -> tuple[str, Any] | None:
        return None


class All(By):
    def match(self, obj) -> bool:
        return True


class ByID(By):
    def __init__(self, id: str):
        self.id = id

    def match(self, obj) -> bool:
        return obj.id == self.id


class ByIDPrefix(By):
    def __init__(self, prefix: str):
        self.prefix = prefix

    def match(self, obj) -> bool:
        return obj.id.startswith(self.prefix)


def _name_of(obj) -> str:
    spec = getattr(obj, "spec", None)
    ann = getattr(spec, "annotations", None) or getattr(obj, "annotations", None)
    return getattr(ann, "name", "") if ann is not None else ""


class ByName(By):
    def __init__(self, name: str):
        self.name = name.lower()

    def match(self, obj) -> bool:
        return _name_of(obj).lower() == self.name

    def index_key(self):
        return ("name", self.name)


class ByNamePrefix(By):
    def __init__(self, prefix: str):
        self.prefix = prefix.lower()

    def match(self, obj) -> bool:
        return _name_of(obj).lower().startswith(self.prefix)


class ByServiceID(By):
    def __init__(self, service_id: str):
        self.service_id = service_id

    def match(self, obj) -> bool:
        return getattr(obj, "service_id", None) == self.service_id

    def index_key(self):
        return ("service", self.service_id)


class ByNodeID(By):
    def __init__(self, node_id: str):
        self.node_id = node_id

    def match(self, obj) -> bool:
        return getattr(obj, "node_id", None) == self.node_id

    def index_key(self):
        return ("node", self.node_id)


class BySlot(By):
    def __init__(self, service_id: str, slot: int):
        self.service_id = service_id
        self.slot = slot

    def match(self, obj) -> bool:
        return (getattr(obj, "service_id", None) == self.service_id
                and getattr(obj, "slot", None) == self.slot)

    def index_key(self):
        return ("slot", (self.service_id, self.slot))


class ByDesiredState(By):
    def __init__(self, state):
        self.state = int(state)

    def match(self, obj) -> bool:
        return int(getattr(obj, "desired_state", -1)) == self.state

    def index_key(self):
        return ("desired_state", self.state)


class ByTaskState(By):
    def __init__(self, state):
        self.state = int(state)

    def match(self, obj) -> bool:
        status = getattr(obj, "status", None)
        return status is not None and int(status.state) == self.state

    def index_key(self):
        return ("task_state", self.state)


class ByRole(By):
    def __init__(self, role):
        self.role = int(role)

    def match(self, obj) -> bool:
        return int(getattr(obj, "role", -1)) == self.role

    def index_key(self):
        return ("role", self.role)


class ByMembership(By):
    def __init__(self, membership):
        self.membership = int(membership)

    def match(self, obj) -> bool:
        spec = getattr(obj, "spec", None)
        return spec is not None and int(getattr(spec, "membership", -1)) == self.membership

    def index_key(self):
        return ("membership", self.membership)


class ByVolumeGroup(By):
    def __init__(self, group: str):
        self.group = group

    def match(self, obj) -> bool:
        return getattr(obj.spec, "group", None) == self.group

    def index_key(self):
        return ("group", self.group)


class ByDriver(By):
    def __init__(self, driver: str):
        self.driver = driver

    def match(self, obj) -> bool:
        return getattr(obj.spec, "driver", None) == self.driver

    def index_key(self):
        return ("driver", self.driver)


class ByKind(By):
    def __init__(self, kind: str):
        self.kind = kind

    def match(self, obj) -> bool:
        return getattr(obj, "kind", None) == self.kind

    def index_key(self):
        return ("kind", self.kind)


class ByReferencedSecretID(By):
    def __init__(self, secret_id: str):
        self.secret_id = secret_id

    def match(self, obj) -> bool:
        spec = getattr(obj, "spec", None)
        task_spec = getattr(spec, "task", spec)
        runtime = getattr(task_spec, "runtime", None)
        if runtime is None:
            return False
        return any(ref.secret_id == self.secret_id for ref in runtime.secrets)


class ByReferencedConfigID(By):
    def __init__(self, config_id: str):
        self.config_id = config_id

    def match(self, obj) -> bool:
        spec = getattr(obj, "spec", None)
        task_spec = getattr(spec, "task", spec)
        runtime = getattr(task_spec, "runtime", None)
        if runtime is None:
            return False
        return any(ref.config_id == self.config_id for ref in runtime.configs)


def _indices_of(obj) -> dict:
    spec = getattr(obj, "spec", None)
    ann = getattr(spec, "annotations", None) or getattr(obj, "annotations", None)
    return getattr(ann, "indices", None) or {}


class ByCustom(By):
    """Search a custom index (Annotations.indices) for an exact value
    (reference: by.go:198-214 ByCustom)."""

    def __init__(self, index: str, value: str):
        self.index = index
        self.value = value

    def match(self, obj) -> bool:
        return _indices_of(obj).get(self.index) == self.value

    def index_key(self):
        return ("custom", (self.index, self.value))


class ByCustomPrefix(By):
    """Custom-index prefix search (reference: by.go:216-232)."""

    def __init__(self, index: str, prefix: str):
        self.index = index
        self.prefix = prefix

    def match(self, obj) -> bool:
        v = _indices_of(obj).get(self.index)
        return v is not None and v.startswith(self.prefix)


class Or(By):
    def __init__(self, *selectors: By):
        self.selectors = selectors

    def match(self, obj) -> bool:
        return any(s.match(obj) for s in self.selectors)


class And(By):
    def __init__(self, *selectors: By):
        self.selectors = selectors

    def match(self, obj) -> bool:
        return all(s.match(obj) for s in self.selectors)


def matches(obj, selectors) -> bool:
    """Multiple top-level selectors OR together (reference store.FindTasks(by.Or...))
    — a single selector list behaves like Or, matching the reference's Find."""
    if not selectors:
        return True
    return any(s.match(obj) for s in selectors)


def candidate_ids(indexes, selectors) -> set[str] | None:
    """Use index hints to narrow candidates; None means full scan."""
    if not selectors:
        return None
    out: set[str] = set()
    for s in selectors:
        hint = s.index_key() if isinstance(s, By) else None
        if hint is None:
            return None  # at least one selector needs a full scan
        idx, key = hint
        out |= indexes.get(idx, {}).get(key, set())
    return out
