"""Transactional in-memory cluster-state store.

Re-derivation of the reference MemoryStore (manager/state/store/memory.go):
`view` / `update` / `batch` transactions over per-type tables with secondary
indexes, a changelist turned into events on commit, an optional raft Proposer
on the write path, and whole-store Save/Restore snapshots.

Where the reference rides hashicorp/go-memdb radix trees, we use plain dict
tables plus maintained secondary-index dicts — the TPU build's hot queries are
answered from the scheduler's own dense arrays, so the store optimizes for
transactional correctness and event fidelity, not pointer-walk speed.
"""
from __future__ import annotations

import copy as _copy
import os
import threading
import time
from collections import Counter, defaultdict
from typing import Any, Callable, Iterable

import numpy as np

from ..analysis import lockgraph as _lockgraph
from ..analysis.lockgraph import make_lock, make_rlock
from ..api.objects import (
    ALL_TABLES,
    Cluster,
    Config,
    EventCommit,
    EventCreate,
    EventDelete,
    EventUpdate,
    Extension,
    Meta,
    Network,
    Node,
    Resource,
    Secret,
    Service,
    StoreObject,
    Task,
    Version,
    Volume,
)
from ..api.types import NodeStatusState, TaskState
from . import by as by_mod
from ..utils.metrics import histogram
from .columnar import ColumnarTasks
from .watch import Channel, WatchQueue, make_watch_queue

# store tx latency + lock-hold timers (memory.go:99-112)
_read_tx_latency = histogram(
    "swarm_store_read_tx_latency_seconds", "read transaction duration")
_write_tx_latency = histogram(
    "swarm_store_write_tx_latency_seconds", "write transaction duration")
_lock_hold = histogram(
    "swarm_store_lock_hold_seconds", "update-lock hold duration")

# Batch limits (reference: manager/state/store/memory.go:47-51).
MAX_CHANGES_PER_TRANSACTION = 200
MAX_TRANSACTION_BYTES = 1.5 * 1024 * 1024

# Wedge detection (memory.go:80-81): update lock held longer than this is a bug.
WEDGE_TIMEOUT = 30.0

# assign_wave per-task outcome codes (the wave commit's in-tx
# re-validation verdicts, vectorized against the columnar mirror)
ASSIGN_OK = 0
ASSIGN_MISSING = 1          # task gone / dead / already torn down -> drop
ASSIGN_NOT_PENDING = 2      # raced to assigned elsewhere / not PENDING -> drop
ASSIGN_NODE_NOT_READY = 3   # in-tx node check failed -> conflict, retry


class _LazyWave:
    """Per-wave record backing lazily-materialized task views: the
    columns hold state/node/version (the heal reads THOSE — latest
    value wins across waves); this holds only the wave-constant rest."""

    __slots__ = ("message", "wall")

    def __init__(self, message: str, wall: float):
        self.message = message
        self.wall = wall


def _patch_assign(old, node_id: str, state, message: str, wall: float):
    """Cheap wave-commit patch: a SHALLOW task copy with fresh meta and
    status — spec/annotations/networks stay shared with the previous
    version, which is legal under the store's immutability contract
    (objects are never mutated in place; `.copy()` forks shared subtrees
    — docs/store.md). This replaces the object path's two full tree
    copies per task."""
    new = _copy.copy(old)
    new.meta = Meta(version=Version(old.meta.version.index),
                    created_at=old.meta.created_at,
                    updated_at=old.meta.updated_at)
    st = _copy.copy(old.status)
    st.state = state
    st.message = message
    st.timestamp = wall
    new.status = st
    new.node_id = node_id
    return new


class SequenceConflict(Exception):
    """Version-checked update failed (reference ErrSequenceConflict)."""


class ExistError(Exception):
    pass


class NotExistError(Exception):
    pass


class StoreAction:
    """One element of a raft-replicated changelist (api/raft.pb.go StoreAction)."""

    CREATE, UPDATE, DELETE = "create", "update", "delete"

    def __init__(self, kind: str, obj: StoreObject):
        self.kind = kind
        self.obj = obj

    def __repr__(self):
        return f"StoreAction({self.kind}, {self.obj.TABLE}:{self.obj.id})"


class ReadTx:
    """Consistent read view. Objects returned are live references owned by the
    store — callers must treat them as immutable and `copy()` before mutating
    (same contract as the reference's returned protos)."""

    def __init__(self, store: "MemoryStore"):
        self._s = store

    def get(self, cls: type[StoreObject], id: str) -> StoreObject | None:
        s = self._s
        if cls.TABLE == "task" and s._stale_tasks:
            # a lazy columnar wave deferred these object views: the API
            # surface is now asking, so materialize (docs/store.md)
            s._heal_stale_tasks()
        return s._tables[cls.TABLE].get(id)

    def find(self, cls: type[StoreObject], *selectors) -> list[StoreObject]:
        return self._s._find(cls, selectors)

    # Typed convenience accessors (reference: tasks.go, nodes.go, ...).
    def get_task(self, id): return self.get(Task, id)
    def get_node(self, id): return self.get(Node, id)
    def get_service(self, id): return self.get(Service, id)
    def get_cluster(self, id): return self.get(Cluster, id)
    def get_secret(self, id): return self.get(Secret, id)
    def get_config(self, id): return self.get(Config, id)
    def get_network(self, id): return self.get(Network, id)
    def get_volume(self, id): return self.get(Volume, id)
    def get_extension(self, id): return self.get(Extension, id)
    def get_resource(self, id): return self.get(Resource, id)

    def find_tasks(self, *sel): return self.find(Task, *sel)
    def find_nodes(self, *sel): return self.find(Node, *sel)
    def find_services(self, *sel): return self.find(Service, *sel)
    def find_clusters(self, *sel): return self.find(Cluster, *sel)
    def find_secrets(self, *sel): return self.find(Secret, *sel)
    def find_configs(self, *sel): return self.find(Config, *sel)
    def find_networks(self, *sel): return self.find(Network, *sel)
    def find_volumes(self, *sel): return self.find(Volume, *sel)
    def find_extensions(self, *sel): return self.find(Extension, *sel)
    def find_resources(self, *sel): return self.find(Resource, *sel)


class WriteTx(ReadTx):
    """Buffered write transaction. Reads see the transaction's own writes."""

    def __init__(self, store: "MemoryStore"):
        super().__init__(store)
        self._writes: dict[tuple[str, str], StoreObject | None] = {}
        self._changelist: list[StoreAction] = []
        # (table, lower-name) -> id for names claimed by buffered writes:
        # the uniqueness checks in create/update stay O(1) instead of
        # rescanning every buffered write per call (a 10k-create tx would
        # otherwise be O(n^2) — bench_host_micro's store row caught this)
        self._buffered_names: dict[tuple[str, str], str] = {}

    def _name_in_use(self, cls, name: str, exclude_id: str) -> bool:
        """Name-uniqueness check: buffered claims via the tx-local map,
        committed objects via the store's name index — each O(1)."""
        lower = name.lower()
        owner = self._buffered_names.get((cls.TABLE, lower))
        if owner is not None and owner != exclude_id:
            return True
        for o in super().find(cls, by_mod.ByName(name)):
            if o.id == exclude_id:
                continue
            key = (cls.TABLE, o.id)
            if key in self._writes:
                cur = self._writes[key]
                if cur is None or (_name_of(cur) or "").lower() != lower:
                    continue  # deleted or renamed away within this tx
            return True
        return False

    def _claim_name(self, obj: StoreObject, old: StoreObject | None) -> None:
        if old is not None:
            old_name = (_name_of(old) or "").lower()
            if old_name:
                key = (obj.TABLE, old_name)
                if self._buffered_names.get(key) == obj.id:
                    del self._buffered_names[key]
        name = (_name_of(obj) or "").lower()
        if name:
            self._buffered_names[(obj.TABLE, name)] = obj.id

    # -- reads see buffered writes -----------------------------------------
    def get(self, cls: type[StoreObject], id: str) -> StoreObject | None:
        key = (cls.TABLE, id)
        if key in self._writes:
            return self._writes[key]
        return super().get(cls, id)

    def find(self, cls: type[StoreObject], *selectors) -> list[StoreObject]:
        base = {o.id: o for o in super().find(cls, *selectors)}
        # Overlay buffered writes: re-filter them, drop deletes.
        for (table, id), obj in self._writes.items():
            if table != cls.TABLE:
                continue
            base.pop(id, None)
            if obj is not None and by_mod.matches(obj, selectors):
                base[id] = obj
        return sorted(base.values(), key=lambda o: o.id)

    # -- mutations ----------------------------------------------------------
    def create(self, obj: StoreObject) -> None:
        if self.get(type(obj), obj.id) is not None:
            raise ExistError(f"{obj.TABLE} {obj.id} already exists")
        name = _name_of(obj)
        if obj.TABLE in ("service", "node") and name:
            if self._name_in_use(type(obj), name, exclude_id=obj.id):
                raise ExistError(f"{obj.TABLE} name {name!r} is in use")
        obj = obj.copy()
        self._writes[(obj.TABLE, obj.id)] = obj
        self._claim_name(obj, None)
        self._changelist.append(StoreAction(StoreAction.CREATE, obj))

    def update(self, obj: StoreObject) -> None:
        old = self.get(type(obj), obj.id)
        if old is None:
            raise NotExistError(f"{obj.TABLE} {obj.id} does not exist")
        if obj.meta.version.index != old.meta.version.index:
            raise SequenceConflict(
                f"{obj.TABLE} {obj.id}: update at version "
                f"{obj.meta.version.index}, store at {old.meta.version.index}"
            )
        new_name = _name_of(obj)
        if obj.TABLE in ("service", "node") and new_name \
                and new_name.lower() != _name_of(old).lower():
            # renames must keep names unique (reference services.go:98-104
            # ErrNameConflict)
            if self._name_in_use(type(obj), new_name, exclude_id=obj.id):
                raise ExistError(f"{obj.TABLE} name {new_name!r} is in use")
        obj = obj.copy()
        self._writes[(obj.TABLE, obj.id)] = obj
        self._claim_name(obj, old)
        self._changelist.append(StoreAction(StoreAction.UPDATE, obj))

    def delete(self, cls: type[StoreObject], id: str) -> None:
        old = self.get(cls, id)
        if old is None:
            raise NotExistError(f"{cls.TABLE} {id} does not exist")
        self._writes[(cls.TABLE, id)] = None
        old_name = (_name_of(old) or "").lower()
        if old_name and self._buffered_names.get(
                (cls.TABLE, old_name)) == id:
            del self._buffered_names[(cls.TABLE, old_name)]
        self._changelist.append(StoreAction(StoreAction.DELETE, old))


# single source of truth for object naming lives with the selectors
_name_of = by_mod._name_of


def _tracked_view(cb, tx):
    """Run a view callback inside the lockgraph hazard window: acquiring
    the dispatcher lock in here is the PR 4 inversion the armed detector
    reports (docs/static_analysis.md). Disarmed cost: one module-global
    truthiness test. The ONE bracket both read paths (view,
    view_and_watch) share — the hazard window must cover every
    callback-under-store-lock path identically."""
    if _lockgraph._STATE is None:
        return cb(tx)
    _lockgraph.view_enter()
    try:
        return cb(tx)
    finally:
        _lockgraph.view_exit()


class MemoryStore:
    """reference: manager/state/store/memory.go:150-158."""

    def __init__(self, proposer=None):
        self._tables: dict[str, dict[str, StoreObject]] = {t: {} for t in ALL_TABLES}
        # secondary indexes: table -> index name -> key -> set[id]
        self._indexes: dict[str, dict[str, dict[Any, set[str]]]] = {
            t: defaultdict(lambda: defaultdict(set)) for t in ALL_TABLES
        }
        self._lock = make_rlock('store.memory.lock')          # guards table reads
        self._update_lock = make_lock('store.memory.update_lock')    # serializes writers (memory.go updateLock)
        self._update_lock_held_since: float | None = None
        self.wedge_timeout = WEDGE_TIMEOUT      # per-store override for tests
        self.proposer = proposer
        self.queue = make_watch_queue()
        self._version = Version(0)  # commit version when no proposer drives it
        # Operation counters (test/bench observability — the dispatcher's
        # op-count regression guard asserts transactions-per-flush and
        # table-scan counts here instead of wall-clock timings, which are
        # meaningless on a contended 1-core host). Keys: "view_tx",
        # "update_tx", "find_<table>". Maintained under the locks the
        # counted operations already hold.
        self.op_counts: Counter = Counter()
        # Columnar mirror of the hot task table (store/columnar.py):
        # kept in lockstep by _commit; the wave write-back's bulk path
        # (assign_wave) and objectless hot queries ride it.
        # SWARMKIT_TPU_NO_COLUMNAR=1 disables it (debug escape hatch;
        # consumers fall back to the object path).
        self.columnar: ColumnarTasks | None = (
            None if os.environ.get("SWARMKIT_TPU_NO_COLUMNAR")
            else ColumnarTasks())
        # task id -> _LazyWave for rows whose object view is OWED after
        # a lazy columnar wave; materialized by _heal_stale_tasks on the
        # first object read (or any write transaction)
        self._stale_tasks: dict[str, _LazyWave] = {}

    # ------------------------------------------------------------------ reads
    def view(self, cb: Callable[[ReadTx], Any] | None = None):
        tx = ReadTx(self)
        if cb is None:
            return tx
        start = time.monotonic()
        try:
            with self._lock:
                self.op_counts["view_tx"] += 1
                return _tracked_view(cb, tx)
        finally:
            _read_tx_latency.observe(time.monotonic() - start)

    # ----------------------------------------------------------------- writes
    def update(self, cb: Callable[[WriteTx], Any]) -> Any:
        """Run a write transaction; commit through the proposer when present
        (memory.go:321-388)."""
        if self._stale_tasks:
            self._heal_stale_tasks()
        start = time.monotonic()
        with self._update_lock:
            self._update_lock_held_since = held = time.monotonic()
            self.op_counts["update_tx"] += 1
            try:
                tx = WriteTx(self)
                cb(tx)
                if not tx._changelist:
                    return None
                if self.proposer is not None:
                    actions = list(tx._changelist)
                    committed = threading.Event()

                    def commit_cb(version_index: int | None = None):
                        self._commit(tx, version_index=version_index)
                        committed.set()

                    self.proposer.propose_value(actions, commit_cb)
                    if not committed.is_set():
                        # Proposer accepted asynchronously; the commit callback
                        # must run before propose_value returns in-process
                        # implementations. Raft returns only after commit.
                        raise RuntimeError("proposer returned before commit")
                else:
                    self._commit(tx)
                return None
            finally:
                self._update_lock_held_since = None
                now = time.monotonic()
                _lock_hold.observe(now - held)
                _write_tx_latency.observe(now - start)

    def _commit(self, tx: WriteTx, version_index: int | None = None) -> None:
        now = time.time()
        with self._lock:
            # the mirror handle is read UNDER the lock: restore() swaps
            # self.columnar while holding it, and a pipelined commit
            # callback (raft worker, no update lock) racing a snapshot
            # install must scatter into the LIVE mirror, not the
            # discarded one
            col = self.columnar
            task_actions: list[StoreAction] | None = \
                [] if col is not None else None
            service_actions: list[StoreAction] = []
            node_actions: list[StoreAction] = []
            secret_actions: list[StoreAction] = []
            config_actions: list[StoreAction] = []
            if version_index is not None:
                # replicated commits carry the raft entry index so object
                # versions agree on every replica
                self._version.index = max(self._version.index, version_index)
            else:
                self._version.index += 1
            version = Version(self._version.index)
            events: list[Any] = []
            for action in tx._changelist:
                obj = action.obj
                table = obj.TABLE
                if task_actions is not None and table == "task":
                    # columnar lockstep: mirrored AFTER the loop in one
                    # batched scatter per commit (touchMeta has stamped
                    # the version by then for creates/updates)
                    task_actions.append(action)
                elif task_actions is not None and table == "service":
                    service_actions.append(action)
                elif task_actions is not None and table == "node":
                    node_actions.append(action)
                elif task_actions is not None and table == "secret":
                    secret_actions.append(action)
                elif task_actions is not None and table == "config":
                    config_actions.append(action)
                if action.kind == StoreAction.DELETE:
                    stored = self._tables[table].pop(obj.id, None)
                    if stored is not None:
                        self._unindex(table, stored)
                    events.append(EventDelete(obj))
                    continue
                old = self._tables[table].get(obj.id)
                # touchMeta (memory.go:998-1020): stamp version + timestamps.
                obj.meta.version = Version(version.index)
                if action.kind == StoreAction.CREATE:
                    obj.meta.created_at = now
                obj.meta.updated_at = now
                if old is not None:
                    self._unindex(table, old)
                self._tables[table][obj.id] = obj
                self._index(table, obj)
                if action.kind == StoreAction.CREATE:
                    events.append(EventCreate(obj))
                else:
                    events.append(EventUpdate(obj, old=old))
            if task_actions:
                col.apply_actions(task_actions)
            if service_actions:
                col.apply_service_actions(service_actions)
            if node_actions:
                col.apply_node_actions(node_actions)
            if secret_actions:
                col.apply_secret_actions(secret_actions)
            if config_actions:
                col.apply_config_actions(config_actions)
            events.append(EventCommit(version))
        self.queue.publish_all(events)

    def apply_store_actions(self, actions: Iterable[StoreAction],
                            version_index: int | None = None) -> None:
        """Raft follower/replay apply path (memory.go:280-308): applies a
        committed changelist without consulting the proposer."""
        if self._stale_tasks:
            self._heal_stale_tasks()
        with self._update_lock:
            tx = WriteTx(self)
            for a in actions:
                if a.kind == StoreAction.CREATE:
                    tx.create(a.obj)
                elif a.kind == StoreAction.UPDATE:
                    # Replay trusts the leader's version; bypass conflict check.
                    cur = tx.get(type(a.obj), a.obj.id)
                    obj = a.obj.copy()
                    if cur is not None:
                        obj.meta.version = Version(cur.meta.version.index)
                        tx.update(obj)
                    else:
                        tx.create(obj)
                else:
                    try:
                        tx.delete(type(a.obj), a.obj.id)
                    except NotExistError:
                        pass
            self._commit(tx, version_index=version_index)

    def batch(self, cb: Callable[["Batch"], Any],
              pipeline_depth: int | None = None) -> None:
        """Split a large write into transactions of at most
        MAX_CHANGES_PER_TRANSACTION changes (memory.go:399-549).

        With `pipeline_depth` and a proposer that offers propose_async,
        sub-transactions are PIPELINED: up to depth proposals ride raft
        concurrently and share the group-commit plane's batched WAL
        fsync + replication flush, instead of paying one quorum RTT +
        fsync each. Commit callbacks still run in raft log order. Only
        safe when the sub-transactions touch disjoint objects (the bulk
        create/update shape Batch exists for): a later sub-transaction
        reads store state that does not yet include an in-flight one."""
        b = Batch(self, pipeline_depth=pipeline_depth)
        cb(b)
        b._flush()
        b._drain()

    # ----------------------------------------------------------------- events
    def watch_queue(self) -> WatchQueue:
        return self.queue

    def watch_from(self, version_index: int, matcher=None,
                   limit: int | None = -1) -> Channel:
        """Subscribe with version replay (memory.go:923-994 WatchFrom):
        committed changes after `version_index` are re-delivered as events
        ahead of the live stream. Requires a proposer that retains history
        (raft log); delivery is at-least-once across the replay/live seam.
        """
        if self._stale_tasks:
            self._heal_stale_tasks()
        with self._lock:
            cur = self._version.index
            replay: list[Any] = []
            if version_index < cur:
                if self.proposer is None or \
                        not hasattr(self.proposer, "changes_between"):
                    raise ValueError(
                        "watch_from needs a history-retaining proposer")
                try:
                    entry_changes = self.proposer.changes_between(
                        Version(version_index), Version(cur))
                except Exception as e:
                    # e.g. the range was compacted into a snapshot — signal
                    # "full resync required" uniformly, not a raft-internal
                    # error type
                    raise ValueError(f"cannot replay from {version_index}: {e}")
                for actions in entry_changes:
                    for sa in actions:
                        if sa.kind == StoreAction.CREATE:
                            replay.append(EventCreate(sa.obj))
                        elif sa.kind == StoreAction.UPDATE:
                            replay.append(EventUpdate(sa.obj))
                        else:
                            replay.append(EventDelete(sa.obj))
                replay.append(EventCommit(Version(cur)))
            ch = self.queue.watch(matcher, limit=limit)
            for ev in replay:
                ch._offer(ev)
        return ch

    def view_and_watch(self, cb: Callable[[ReadTx], Any] | None = None,
                       matcher=None, limit: int | None = -1) -> tuple[Any, Channel]:
        """Atomic snapshot-then-subscribe (memory.go:892-909): no event that
        post-dates the snapshot is missed, none that pre-dates it is delivered.
        limit=None subscribes unbounded (for trusted in-process control loops
        that must never be shed as slow subscribers)."""
        if self._stale_tasks:
            self._heal_stale_tasks()
        with self._lock:
            result = _tracked_view(cb, ReadTx(self)) if cb is not None \
                else None
            ch = self.queue.watch(matcher, limit=limit)
        return result, ch

    # -------------------------------------------------------------- snapshots
    def save(self) -> dict[str, list[StoreObject]]:
        """Marshal the whole store (memory.go:857-879 / api/snapshot.proto).

        When the columnar plane is on, the snapshot additionally carries
        a versioned `__columnar__` dense-column section (ISSUE 18) so a
        restoring store can rebuild the hot mirrors by array adoption
        instead of the O(objects) rebuild walk. The section is advisory:
        restore() validates it against the object tables and silently
        falls back to rebuild() on any mismatch, and loaders without the
        plane (SWARMKIT_TPU_NO_COLUMNAR=1, older builds) skip the key."""
        with self._lock:
            # heal UNDER the lock: save reads the tables directly (no
            # heal-aware accessor), so a lazy wave landing between an
            # outside-the-lock check and the marshal would be silently
            # missing from the snapshot
            if self._stale_tasks:
                self._heal_stale_locked(False)
            snap = {t: [o.copy() for o in objs.values()]
                    for t, objs in self._tables.items()}
            if self.columnar is not None:
                snap["__columnar__"] = self.columnar.to_snapshot_section()
                self.op_counts["save_columnar_section"] += 1
            return snap

    def restore(self, snapshot: dict[str, list[StoreObject]]) -> None:
        # NEVER mutate the caller's snapshot dict: raft holds it (the
        # leader's _snap_blob cache / recovered snapshot_data) and may
        # restore it again
        section = snapshot.get("__columnar__")
        with self._update_lock, self._lock:
            for t in self._tables:
                self._tables[t].clear()
                self._indexes[t].clear()
            max_index = 0
            for t, objs in snapshot.items():
                if t == "__columnar__":
                    continue
                for o in objs:
                    o = o.copy()
                    self._tables[t][o.id] = o
                    self._index(t, o)
                    max_index = max(max_index, o.meta.version.index)
            self._version.index = max(self._version.index, max_index)
            self._stale_tasks.clear()
            if self.columnar is not None:
                tables = self._tables
                adopted = None
                if section is not None:
                    adopted = ColumnarTasks.adopt(
                        section,
                        list(tables["task"].values()),
                        services=list(tables["service"].values()),
                        nodes=list(tables["node"].values()),
                        secrets=list(tables["secret"].values()),
                        configs=list(tables["config"].values()))
                if adopted is not None:
                    self.columnar = adopted
                    self.op_counts["restore_columnar_adopted"] += 1
                else:
                    self.columnar = ColumnarTasks.rebuild(
                        list(tables["task"].values()),
                        services=list(tables["service"].values()),
                        nodes=list(tables["node"].values()),
                        secrets=list(tables["secret"].values()),
                        configs=list(tables["config"].values()))
                    self.op_counts["restore_columnar_rebuilt"] += 1

    # ------------------------------------------------- columnar wave plane
    def assign_wave(self, assignments: list[tuple[str, str]], *,
                    state=TaskState.ASSIGNED,
                    message: str = "scheduler assigned task to node",
                    lazy: bool = False,
                    pipeline_depth: int | None = None,
                    ) -> tuple[list[int], list[Any]]:
        """Bulk wave write-back (ISSUE 11): commit a whole scheduler
        wave of (task_id, node_id) assignments with the in-tx
        re-validation the object path performed per task — task still
        PENDING/alive/unassigned (vectorized against the columnar
        mirror) and node READY (per distinct node) — but with ONE cheap
        shallow patch per task instead of two tree copies, and ONE
        update transaction on a plain store (chunked at
        MAX_CHANGES_PER_TRANSACTION and pipelined through propose_async
        when raft-backed, exactly like Batch.update_many).

        Returns (codes, tasks): codes[i] is an ASSIGN_* verdict, and
        tasks[i] the committed object for ASSIGN_OK rows (None on the
        lazy path, where object views are materialized only on demand).

        lazy=True additionally engages the EVENT-SILENT deferral path
        when legal (plain store, zero watchers): columns take the wave
        as one array scatter, object views and index updates are owed
        until the first object read (docs/store.md lazy-view rules).
        """
        col = self.columnar
        if col is None:
            raise RuntimeError(
                "assign_wave needs the columnar plane "
                "(disabled via SWARMKIT_TPU_NO_COLUMNAR)")
        n = len(assignments)
        if not n:
            return [], []
        if self._stale_tasks:
            self._heal_stale_tasks()
        if lazy and self.proposer is None and not self.queue.has_watchers():
            out = self._assign_wave_lazy(assignments, state, message)
            if out is not None:
                return out
            # a watcher subscribed between the gate and the locks:
            # fall through to the eager (event-publishing) path
        codes: list[int] = [ASSIGN_MISSING] * n
        tasks: list[Any] = [None] * n
        step = MAX_CHANGES_PER_TRANSACTION if self.proposer is not None \
            else n
        b = Batch(self, pipeline_depth=pipeline_depth)
        for off in range(0, n, step):
            chunk = assignments[off:off + step]

            def run_chunk(tx, chunk=chunk, off=off):
                self._assign_in_tx(tx, chunk, off, codes, tasks, state,
                                   message)

            b.update_many(run_chunk, len(chunk))
        b._flush()
        b._drain()
        self.op_counts["columnar_wave_tx"] += 1
        return codes, tasks

    def _wave_verdicts(self, chunk, off: int, codes, on_ok) -> int:
        """THE wave-commit validation (shared by the eager and lazy
        paths so the verdict logic cannot drift): vectorized column
        checks + a per-distinct-node READY overlay; `on_ok(j, tid, nid,
        row)` fires for each passing item. Returns the OK count.

        Mirror-registry pair "assign-wave" (analysis/mirror.py): both
        callers' call sequences around this helper are table-pinned —
        a path abandoning it (or the shared _patch_assign) fails
        tier-1 until consciously re-recorded."""
        rows, vcodes = self.columnar.wave_codes([t for t, _ in chunk])
        ready: dict[str, bool] = {}
        ntab = self._tables["node"]
        ok = 0
        for j, (tid, nid) in enumerate(chunk):
            c = int(vcodes[j])
            if c:
                codes[off + j] = ASSIGN_MISSING if c == 1 \
                    else ASSIGN_NOT_PENDING
                continue
            node_ok = ready.get(nid)
            if node_ok is None:
                node = ntab.get(nid)
                node_ok = ready[nid] = (
                    node is not None
                    and node.status.state == NodeStatusState.READY)
            if not node_ok:
                codes[off + j] = ASSIGN_NODE_NOT_READY
                continue
            codes[off + j] = ASSIGN_OK
            on_ok(j, tid, nid, int(rows[j]))
            ok += 1
        return ok

    def _assign_in_tx(self, tx: WriteTx, chunk, off: int, codes, tasks,
                      state, message: str) -> None:
        """One chunk's eager wave commit: validate against the columns
        (current for everything committed; in-flight pipelined chunks
        are disjoint by the wave contract), patch shallow copies, and
        buffer them straight into the transaction — the ordinary commit
        loop then owns table swap, index delta, events, and the columnar
        lockstep scatter."""
        wall = time.time()
        ttab = self._tables["task"]
        missed = [0]

        def buffer_patch(j, tid, nid, _row):
            old = ttab.get(tid)
            if old is None:
                # a pipelined delete's commit (held only _lock) landed
                # between wave_codes and here: drop, like the object
                # path's `cur is None` gate — never crash the wave
                codes[off + j] = ASSIGN_MISSING
                missed[0] += 1
                return
            new = _patch_assign(old, nid, state, message, wall)
            tx._writes[("task", tid)] = new
            tx._changelist.append(StoreAction(StoreAction.UPDATE, new))
            tasks[off + j] = new

        ok = self._wave_verdicts(chunk, off, codes, buffer_patch)
        self.op_counts["columnar_assign_rows"] += ok - missed[0]

    def _assign_wave_lazy(self, assignments, state, message: str,
                          ) -> tuple[list[int], list[Any]] | None:
        """The deferral path: with no watcher to observe events and no
        raft log to feed, the wave is ONE scatter into the columns plus
        per-row stale marks; object views, secondary-index updates and
        events are owed to _heal_stale_tasks (events become moot — no
        subscriber existed at publish time, matching an empty
        publish_all). Returns None when a watcher subscribed between
        the caller's gate and the locks (subscription happens under
        _lock, so the re-check here is race-free) — the caller falls
        back to the eager path."""
        wall = time.time()
        n = len(assignments)
        codes: list[int] = [ASSIGN_MISSING] * n
        emit_batch: list[Any] = []
        with self._update_lock:
            self._update_lock_held_since = held = time.monotonic()
            try:
                with self._lock:
                    if self.queue.has_watchers():
                        # raced a view_and_watch/watch_from subscriber
                        # (those register under this lock): go eager
                        return None
                    self.op_counts["update_tx"] += 1
                    col = self.columnar
                    ok_rows: list[int] = []
                    ok_nodes: list[int] = []
                    wave = _LazyWave(message, wall)

                    def mark_stale(_j, tid, nid, row):
                        ok_rows.append(row)
                        ok_nodes.append(col.nodes.intern(nid))
                        self._stale_tasks[tid] = wave

                    self._wave_verdicts(assignments, 0, codes, mark_stale)
                    if ok_rows:
                        self._version.index += 1
                        col.assign_rows(np.asarray(ok_rows, np.int64),
                                        np.asarray(ok_nodes, np.int32),
                                        int(state), self._version.index)
                        self.op_counts["columnar_lazy_waves"] += 1
                        self.op_counts["columnar_assign_rows"] += \
                            len(ok_rows)
                    if ok_rows and self.queue.has_watchers():
                        # a RAW queue.watch() registered mid-wave (that
                        # path takes only the watch lock — the gate
                        # above can't see it). Its watch() may have
                        # returned before an eager wave's publish would
                        # have run, so it is entitled to these events:
                        # heal NOW, under the same lock hold (a
                        # concurrent no-event heal can't pre-empt and
                        # swallow the batch), publish after the locks.
                        emit_batch = self._heal_stale_locked(True)
            finally:
                self._update_lock_held_since = None
                _lock_hold.observe(time.monotonic() - held)
        if emit_batch:
            self.queue.publish_all(emit_batch)
        return codes, [None] * n

    def _heal_stale_tasks(self, emit_events: bool = False) -> None:
        """Materialize every owed object view (lock + publish wrapper
        around _heal_stale_locked)."""
        with self._lock:
            events = self._heal_stale_locked(emit_events)
        if events:
            self.queue.publish_all(events)

    def _heal_stale_locked(self, emit_events: bool) -> list[Any]:
        """The heal body — CALLER HOLDS _lock: shallow patch from the
        columns + wave record, index delta, table swap, at most once
        per lazy wave regardless of reader count (the dict swap makes
        concurrent healers idempotent). emit_events=True returns the
        eager-equivalent EventUpdate batch + EventCommit for the caller
        to publish AFTER its lock drops (mirroring _commit's publish
        ordering)."""
        stale = self._stale_tasks
        if not stale:
            return []
        self._stale_tasks = {}
        events: list[Any] = []
        col = self.columnar
        table = self._tables["task"]
        for tid, wave in stale.items():
            old = table.get(tid)
            row = col.row_of(tid)
            if old is None or row < 0:
                continue
            new = _patch_assign(
                old, col.nodes.name(int(col.node_idx[row])),
                TaskState(int(col.state[row])), wave.message, wave.wall)
            new.meta.version = Version(int(col.version[row]))
            new.meta.updated_at = wave.wall
            self._unindex("task", old)
            table[tid] = new
            self._index("task", new)
            if emit_events:
                events.append(EventUpdate(new, old=old))
        self.op_counts["columnar_materializations"] += len(stale)
        if emit_events and events:
            events.append(EventCommit(Version(self._version.index)))
        return events

    @property
    def version(self) -> Version:
        return Version(self._version.index)

    def wedged(self) -> bool:
        """Wedge detector (memory.go:1024-1031)."""
        since = self._update_lock_held_since
        return since is not None and \
            time.monotonic() - since > self.wedge_timeout

    # ---------------------------------------------------------------- indexes
    def _index_entries(self, obj: StoreObject) -> list[tuple[str, Any]]:
        entries: list[tuple[str, Any]] = []
        name = _name_of(obj)
        if name:
            entries.append(("name", name.lower()))
        # custom indexes (reference by.go ByCustom: application-defined
        # secondary keys in Annotations.indices) — the extraction rule is
        # shared with the ByCustom matchers so index writer and reader
        # can never diverge
        for k, v in by_mod._indices_of(obj).items():
            entries.append(("custom", (k, v)))
        if isinstance(obj, Task):
            if obj.service_id:
                entries.append(("service", obj.service_id))
            if obj.node_id:
                entries.append(("node", obj.node_id))
            entries.append(("slot", (obj.service_id, obj.slot)))
            entries.append(("desired_state", int(obj.desired_state)))
            entries.append(("task_state", int(obj.status.state)))
        elif isinstance(obj, Node):
            entries.append(("role", int(obj.role)))
            entries.append(("membership", int(obj.spec.membership)))
        elif isinstance(obj, Volume):
            if obj.spec.group:
                entries.append(("group", obj.spec.group))
            if obj.spec.driver:
                entries.append(("driver", obj.spec.driver))
        elif isinstance(obj, Resource):
            if obj.kind:
                entries.append(("kind", obj.kind))
        return entries

    def _index(self, table: str, obj: StoreObject) -> None:
        for idx, key in self._index_entries(obj):
            self._indexes[table][idx][key].add(obj.id)

    def _unindex(self, table: str, obj: StoreObject) -> None:
        for idx, key in self._index_entries(obj):
            self._indexes[table][idx][key].discard(obj.id)

    def _find(self, cls: type[StoreObject], selectors) -> list[StoreObject]:
        if cls.TABLE == "task" and self._stale_tasks:
            self._heal_stale_tasks()
        with self._lock:
            self.op_counts[f"find_{cls.TABLE}"] += 1
            table = self._tables[cls.TABLE]
            ids = by_mod.candidate_ids(self._indexes[cls.TABLE], selectors)
            objs = table.values() if ids is None else (
                table[i] for i in ids if i in table)
            return sorted(
                (o for o in objs if by_mod.matches(o, selectors)),
                key=lambda o: o.id,
            )


class Batch:
    """reference: memory.go Batch — accumulates updates, flushing every
    MAX_CHANGES_PER_TRANSACTION changes as an independent transaction.
    With pipeline_depth set (and an async-capable proposer), flushed
    sub-transactions become in-flight raft proposals up to that depth."""

    def __init__(self, store: MemoryStore, pipeline_depth: int | None = None):
        self._store = store
        self._pending: list[Callable[[WriteTx], Any]] = []
        self._pending_changes = 0
        self._depth = pipeline_depth
        self._handles: list = []
        self.applied = 0
        self.committed = 0

    def update(self, cb: Callable[[WriteTx], Any]) -> None:
        self._pending.append(cb)
        self._pending_changes += 1
        self.applied += 1
        if self._pending_changes >= MAX_CHANGES_PER_TRANSACTION:
            self._flush()

    def update_many(self, cb: Callable[[WriteTx], Any], changes: int) -> None:
        """Grouped write: `cb(tx)` performs up to `changes` store writes
        in ONE callback — the scheduler's batched wave write-back rides
        this instead of one closure + one Batch entry per task.

        Flush semantics: with NO proposer, grouped callbacks coalesce
        into a single transaction regardless of size (nothing bounds an
        in-memory transaction but raft entry limits, and one commit =
        one table swap + one event batch — the op-count guard asserts
        exactly one update-tx per wave). With a proposer, flush
        boundaries respect MAX_CHANGES_PER_TRANSACTION like update(),
        so no raft entry exceeds the reference's bound — a grouped
        callback that would push the pending sub-transaction past the
        limit flushes the accumulated work FIRST (the caller still sizes
        `cb` chunks at or below the limit; an oversized single chunk is
        the caller's contract violation and ships alone)."""
        if self._store.proposer is not None and self._pending and \
                self._pending_changes + changes > MAX_CHANGES_PER_TRANSACTION:
            self._flush()
        self._pending.append(cb)
        self._pending_changes += changes
        self.applied += changes
        if self._store.proposer is not None and \
                self._pending_changes >= MAX_CHANGES_PER_TRANSACTION:
            self._flush()

    def _pipelined(self) -> bool:
        return bool(self._depth and self._depth > 1
                    and self._store.proposer is not None
                    and hasattr(self._store.proposer, "propose_async"))

    def _flush(self) -> None:
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        changes, self._pending_changes = self._pending_changes, 0

        def run_all(tx: WriteTx):
            for cb in pending:
                cb(tx)

        if self._pipelined():
            self._flush_async(run_all)
        else:
            self._store.update(run_all)
        self.committed += changes

    def _flush_async(self, run_all: Callable[[WriteTx], Any]) -> None:
        """Build the sub-transaction under the update lock, hand the
        changelist to propose_async, and release the lock WITHOUT waiting
        for the commit — the raft worker's group-commit flush batches the
        in-flight window's WAL write + replication. The commit callback
        (table write-back + events) runs on the raft worker in log order,
        exactly like a propose_value commit does."""
        store = self._store
        with store._update_lock:
            tx = WriteTx(store)
            run_all(tx)
            if not tx._changelist:
                return
            actions = list(tx._changelist)

            def commit_cb(version_index: int | None = None):
                store._commit(tx, version_index=version_index)

            handle = store.proposer.propose_async(actions, commit_cb)
        self._handles.append(handle)
        while len(self._handles) >= (self._depth or 1):
            self._handles.pop(0).result()

    def _drain(self) -> None:
        """Wait out every in-flight pipelined proposal; raise the first
        failure (same typed errors a blocking update would raise)."""
        handles, self._handles = self._handles, []
        first_err = None
        for h in handles:
            try:
                h.result()
            except Exception as exc:
                if first_err is None:
                    first_err = exc
        if first_err is not None:
            raise first_err
