"""Columnar mirror of the hot task table (ISSUE 11 tentpole).

BENCH_r05 put the ceiling at the Python-object store: at 1M tasks the
tick is 21x the oracle but e2e only 6x, because every wave write-back
pays two tree copies plus full re-index per task. This module keeps the
scheduler-hot half of every Task as dense numpy columns — state /
desired-state / version / node-idx / service-idx / slot, keyed by an
interned task-id vocabulary that mirrors `IncrementalEncoder`'s node
vocab (insert on first sight, rows recycled through a free list on
delete) — so bulk wave write-back and hot queries become array ops.

Contract (docs/store.md): the OBJECT table remains the replicated
source of record; the columns are DERIVED TRUTH kept in lockstep by the
commit path (`MemoryStore._commit` feeds every committed task action
through `apply_actions`). The one legal divergence window is a LAZY
wave (`MemoryStore.assign_wave(lazy=True)` on a watcher-free plain
store): columns advance first and the object views are materialized
only when the API surface asks for a task the columns can't answer —
`MemoryStore._heal_stale_tasks` owns that materialization. Nothing
outside store/columnar.py, store/memory.py, allocator/batched.py and
ops/alloc.py may write these arrays (lint rule `columnar-mutate`).
"""
from __future__ import annotations

from collections import Counter

import numpy as np

from ..api.types import TaskState


class IdVocab:
    """String interner with reverse lookup. id 0 is reserved for the
    empty string (an unassigned node / service-less task interns to 0),
    mirroring the encoder Vocab convention."""

    def __init__(self):
        self.names: list[str] = [""]
        self._ids: dict[str, int] = {"": 0}

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            i = len(self.names)
            self._ids[s] = i
            self.names.append(s)
        return i

    def lookup(self, s: str) -> int:
        """-1 when unseen (groups LOOK UP, writers INSERT)."""
        return self._ids.get(s, -1)

    def name(self, i: int) -> str:
        return self.names[i]

    def __len__(self):
        return len(self.names)


_GROW = 1024
COLUMNS = ("state", "desired", "version", "node_idx", "service_idx", "slot",
           "spec_version")

# version tag of the optional dense-column snapshot section (ISSUE 18):
# adopt() refuses anything else and the restore path falls back to
# rebuild(), so old snapshots (no section) and future formats both load
COLUMNAR_SECTION_VERSION = 1


def _enc(arr: np.ndarray) -> dict:
    """Codec-safe dense array: dtype string + raw bytes (the rpc codec
    has no numpy handler, and raw bytes round-trip cheaper anyway)."""
    return {"d": arr.dtype.str, "b": arr.tobytes()}


def _dec(obj, want_dtype, want_len: int):
    """Decode an _enc payload; None unless it is exactly the dtype and
    length the adopting mirror requires (adopt() treats None as a
    malformed section and falls back to rebuild)."""
    if not isinstance(obj, dict) or "d" not in obj or "b" not in obj:
        return None
    try:
        arr = np.frombuffer(obj["b"], dtype=np.dtype(obj["d"]))
    except (TypeError, ValueError):
        return None
    if arr.dtype != np.dtype(want_dtype) or arr.shape[0] != want_len:
        return None
    return arr.copy()  # frombuffer is read-only; columns must be writable


def _revocab(names) -> "IdVocab | None":
    """Rebuild an IdVocab from its serialized name list (id 0 must be
    the reserved empty string; duplicates would corrupt lookups)."""
    if not isinstance(names, list) or not names or names[0] != "":
        return None
    v = IdVocab()
    for s in names[1:]:
        v.intern(s)
    if len(v) != len(names):
        return None  # duplicate names collapsed: section is corrupt
    return v


def _grow_columns(owner, cols, need: int) -> None:
    """Shared capacity growth for every column mirror: double (or step
    by _GROW) until `need` rows fit, zero-filling the tail. One policy
    for ColumnarTasks and both hot sub-mirrors — change it here only."""
    cap = getattr(owner, cols[0]).shape[0]
    if need <= cap:
        return
    new_cap = cap
    while new_cap < need:
        new_cap = max(new_cap * 2, new_cap + _GROW)
    for name in cols:
        arr = getattr(owner, name)
        grown = np.zeros(new_cap, arr.dtype)
        grown[:cap] = arr
        setattr(owner, name, grown)


class ColumnarServices:
    """Hot-column mirror of the SERVICE table (ISSUE 14): replicas /
    spec-version / replicated-mode / pending-delete, indexed by the
    SHARED service IdVocab of the task columns — `service_idx` values in
    the task table are directly usable as row indices here. Row 0 (the
    reserved empty id) is never valid. Like the task columns these are
    DERIVED TRUTH kept in lockstep by `MemoryStore._commit`; the batched
    orchestrator reads them so a steady reconcile pass over 100k
    services touches zero service objects."""

    def __init__(self, vocab: IdVocab, cap: int = _GROW):
        self.vocab = vocab
        cap = max(cap, len(vocab), 1)
        self.replicas = np.zeros(cap, np.int64)
        self.spec_version = np.zeros(cap, np.int64)
        self.replicated = np.zeros(cap, bool)
        self.pending_delete = np.zeros(cap, bool)
        # non-terminal update status (updating / rollback_started): the
        # reconciler must keep kicking the update pass until it writes
        # a terminal status, even when no slot is dirty any more (the
        # restart supervisor may converge the slots on its own)
        self.in_update = np.zeros(cap, bool)
        self.valid = np.zeros(cap, bool)

    _COLS = ("replicas", "spec_version", "replicated", "pending_delete",
             "in_update", "valid")

    def upsert(self, service) -> int:
        from ..api.types import ServiceMode

        row = self.vocab.intern(service.id)
        _grow_columns(self, self._COLS, row + 1)
        self.replicas[row] = int(service.spec.replicas)
        self.spec_version[row] = (service.spec_version.index
                                  if service.spec_version is not None else -1)
        self.replicated[row] = service.spec.mode == ServiceMode.REPLICATED
        self.pending_delete[row] = bool(service.pending_delete)
        self.in_update[row] = (service.update_status or {}).get(
            "state") in ("updating", "rollback_started")
        self.valid[row] = True
        return row

    def delete(self, service_id: str) -> None:
        row = self.vocab.lookup(service_id)
        if row > 0 and row < self.valid.shape[0]:
            self.valid[row] = False

    def row_of(self, service_id: str) -> int:
        row = self.vocab.lookup(service_id)
        if row <= 0 or row >= self.valid.shape[0] or not self.valid[row]:
            return -1
        return row


class ColumnarNodes:
    """Hot-column mirror of the NODE table: status state / availability,
    indexed by the shared node IdVocab (task `node_idx` values are row
    indices). The batched orchestrator's node-down victim scan reads
    these instead of walking node objects."""

    def __init__(self, vocab: IdVocab, cap: int = _GROW):
        self.vocab = vocab
        cap = max(cap, len(vocab), 1)
        self.state = np.zeros(cap, np.int8)
        self.availability = np.zeros(cap, np.int8)
        self.valid = np.zeros(cap, bool)

    _COLS = ("state", "availability", "valid")

    def upsert(self, node) -> int:
        row = self.vocab.intern(node.id)
        _grow_columns(self, self._COLS, row + 1)
        self.state[row] = int(node.status.state)
        self.availability[row] = int(node.spec.availability)
        self.valid[row] = True
        return row

    def delete(self, node_id: str) -> None:
        row = self.vocab.lookup(node_id)
        if row > 0 and row < self.valid.shape[0]:
            self.valid[row] = False


class ColumnarDeps:
    """Hot-column mirror of a dependency table (secrets or configs,
    ISSUE 16): version / valid over the table's own IdVocab. Unlike task
    rows, dep rows are NEVER recycled — the vocab only grows — so a row
    index captured by a consumer (the dispatcher's per-session known
    columns) stays bound to the same object id forever, and a deleted-
    then-recreated dep re-lands on its old row with a strictly newer
    version. Same derived-truth rules as the task columns: the commit
    path (`MemoryStore._commit`) is the only steady writer."""

    def __init__(self, cap: int = _GROW):
        self.vocab = IdVocab()
        cap = max(cap, 1)
        self.version = np.zeros(cap, np.int64)
        self.valid = np.zeros(cap, bool)

    _COLS = ("version", "valid")

    def upsert(self, obj) -> int:
        row = self.vocab.intern(obj.id)
        _grow_columns(self, self._COLS, row + 1)
        self.version[row] = obj.meta.version.index
        self.valid[row] = True
        return row

    def delete(self, obj_id: str) -> None:
        row = self.vocab.lookup(obj_id)
        if row > 0 and row < self.valid.shape[0]:
            self.valid[row] = False

    def row_of(self, obj_id: str) -> int:
        """Live row index, -1 when unseen or deleted."""
        row = self.vocab.lookup(obj_id)
        if row <= 0 or row >= self.valid.shape[0] or not self.valid[row]:
            return -1
        return row

    def apply_actions(self, actions: list) -> None:
        for action in actions:
            if action.kind == "delete":
                self.delete(action.obj.id)
            else:
                self.upsert(action.obj)


class ColumnarTasks:
    """Dense column mirror of the task table.

    Row lifetime: a task id interns into `_row` on first create; its row
    index is stable for the task's lifetime and recycled (free list) on
    delete. `valid[row]` is False only for never-used / freed rows.
    """

    def __init__(self, cap: int = _GROW):
        cap = max(cap, 1)
        self._row: dict[str, int] = {}
        self.ids: list[str | None] = []        # row -> task id (None = freed)
        self._free: list[int] = []
        self.nodes = IdVocab()
        self.services = IdVocab()
        self.state = np.zeros(cap, np.int32)
        self.desired = np.zeros(cap, np.int32)
        self.version = np.zeros(cap, np.int64)
        self.node_idx = np.zeros(cap, np.int32)
        self.service_idx = np.zeros(cap, np.int32)
        self.slot = np.zeros(cap, np.int64)
        # task spec-version index (-1 = None): the batched orchestrator's
        # dirty-candidate filter (ISSUE 14) — version-mismatch rows are
        # EXACTLY the set the scalar is_task_dirty would spec-compare
        self.spec_version = np.zeros(cap, np.int64)
        self.valid = np.zeros(cap, bool)
        # service / node hot columns over the SHARED vocabs (ISSUE 14)
        self.service_cols = ColumnarServices(self.services, cap)
        self.node_cols = ColumnarNodes(self.nodes, cap)
        # secret / config version mirrors (ISSUE 16): own vocabs, rows
        # never recycled — the dispatcher's columnar assignment diff
        # binds per-session known versions to these rows
        self.secret_cols = ColumnarDeps(cap)
        self.config_cols = ColumnarDeps(cap)
        # op counters (merged into store.op_counts views / debug/vars)
        self.stats: Counter = Counter()

    # ------------------------------------------------------------ capacity
    def _cap(self) -> int:
        return self.state.shape[0]

    _COLS = COLUMNS + ("valid",)

    def _ensure(self, rows_needed: int) -> None:
        _grow_columns(self, self._COLS, len(self.ids) + rows_needed)

    def _alloc_row(self, task_id: str) -> int:
        if self._free:
            row = self._free.pop()
            self.ids[row] = task_id
        else:
            self._ensure(1)
            row = len(self.ids)
            self.ids.append(task_id)
        self._row[task_id] = row
        return row

    # ----------------------------------------------------- lockstep writes
    def upsert_many(self, tasks: list) -> None:
        """Mirror a batch of created/updated task objects. One pass
        builds the row/value staging lists, then each column takes ONE
        flat fancy-index scatter — the bulk path the wave write-back
        rides (one commit = one scatter set, not one write per task)."""
        n = len(tasks)
        if not n:
            return
        rows = np.empty(n, np.int64)
        state = np.empty(n, np.int32)
        desired = np.empty(n, np.int32)
        version = np.empty(n, np.int64)
        node_idx = np.empty(n, np.int32)
        service_idx = np.empty(n, np.int32)
        slot = np.empty(n, np.int64)
        spec_version = np.empty(n, np.int64)
        row_of = self._row
        for j, t in enumerate(tasks):
            row = row_of.get(t.id)
            if row is None:
                row = self._alloc_row(t.id)
            rows[j] = row
            state[j] = int(t.status.state)
            desired[j] = int(t.desired_state)
            version[j] = t.meta.version.index
            node_idx[j] = self.nodes.intern(t.node_id)
            service_idx[j] = self.services.intern(t.service_id)
            slot[j] = t.slot
            spec_version[j] = (t.spec_version.index
                               if t.spec_version is not None else -1)
        self.state[rows] = state
        self.desired[rows] = desired
        self.version[rows] = version
        self.node_idx[rows] = node_idx
        self.service_idx[rows] = service_idx
        self.slot[rows] = slot
        self.spec_version[rows] = spec_version
        self.valid[rows] = True
        self.stats["rows_upserted"] += n
        self.stats["scatters"] += 1

    def delete(self, task_id: str) -> None:
        row = self._row.pop(task_id, None)
        if row is None:
            return
        self.ids[row] = None
        self.valid[row] = False
        self.node_idx[row] = 0
        self.service_idx[row] = 0
        self._free.append(row)
        self.stats["rows_deleted"] += 1

    def apply_actions(self, actions: list) -> None:
        """Commit-path lockstep hook: apply one committed changelist's
        task actions in order, coalescing consecutive creates/updates
        into one scatter batch."""
        pending: list = []
        for action in actions:
            if action.kind == "delete":
                if pending:
                    self.upsert_many(pending)
                    pending = []
                self.delete(action.obj.id)
            else:
                pending.append(action.obj)
        if pending:
            self.upsert_many(pending)

    def apply_service_actions(self, actions: list) -> None:
        """Commit-path lockstep hook for the service hot columns."""
        for action in actions:
            if action.kind == "delete":
                self.service_cols.delete(action.obj.id)
            else:
                self.service_cols.upsert(action.obj)
        self.stats["service_upserts"] += len(actions)

    def apply_node_actions(self, actions: list) -> None:
        """Commit-path lockstep hook for the node hot columns."""
        for action in actions:
            if action.kind == "delete":
                self.node_cols.delete(action.obj.id)
            else:
                self.node_cols.upsert(action.obj)
        self.stats["node_upserts"] += len(actions)

    def apply_secret_actions(self, actions: list) -> None:
        """Commit-path lockstep hook for the secret version mirror."""
        self.secret_cols.apply_actions(actions)
        self.stats["secret_upserts"] += len(actions)

    def apply_config_actions(self, actions: list) -> None:
        """Commit-path lockstep hook for the config version mirror."""
        self.config_cols.apply_actions(actions)
        self.stats["config_upserts"] += len(actions)

    def task_row(self, task_id: str) -> int:
        """Live row index for a task id, -1 when absent (rows recycle
        through the free list, so consumers holding a row must also hold
        the version they saw — see dispatcher/columnar_diff.py)."""
        return self._row.get(task_id, -1)

    # --------------------------------------------------- wave fast path
    def wave_codes(self, task_ids: list) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized wave-commit validation (the in-tx re-validation the
        object path did per task): returns (rows, codes) aligned with
        `task_ids`, codes in the ASSIGN_* space of store.memory — 0 ok,
        1 missing, 2 not assignable (dead / not PENDING / already has a
        node). Node readiness is the caller's overlay (it needs the node
        table)."""
        n = len(task_ids)
        rows = np.fromiter((self._row.get(t, -1) for t in task_ids),
                           np.int64, n)
        codes = np.zeros(n, np.int8)
        missing = rows < 0
        r = np.where(missing, 0, rows)
        bad = ((self.state[r] != int(TaskState.PENDING))
               | (self.node_idx[r] != 0)
               | (self.desired[r] > int(TaskState.COMPLETE))
               | ~self.valid[r])
        codes[bad] = 2
        codes[missing] = 1
        self.stats["wave_validations"] += 1
        return rows, codes

    def assign_rows(self, rows: np.ndarray, node_idx_vals: np.ndarray,
                    state: int, version: int) -> None:
        """The lazy wave's array write: whole-wave scatter into the hot
        columns. Object views for these rows are OWED — the caller must
        track them stale and materialize on first object read."""
        self.state[rows] = state
        self.node_idx[rows] = node_idx_vals
        self.version[rows] = version
        self.stats["assign_rows"] += int(rows.size)
        self.stats["assign_waves"] += 1

    # ------------------------------------------------------------ queries
    def __len__(self):
        return len(self._row)

    def row_of(self, task_id: str) -> int:
        return self._row.get(task_id, -1)

    def get(self, task_id: str):
        """(state, desired, version, node_id, service_id, slot) or None
        — the objectless hot read."""
        row = self._row.get(task_id)
        if row is None:
            return None
        self.stats["point_reads"] += 1
        return (int(self.state[row]), int(self.desired[row]),
                int(self.version[row]), self.nodes.name(self.node_idx[row]),
                self.services.name(self.service_idx[row]),
                int(self.slot[row]))

    def _rows_where(self, mask: np.ndarray) -> list[str]:
        self.stats["array_queries"] += 1
        ids = self.ids
        return [ids[r] for r in np.flatnonzero(mask & self.valid).tolist()]

    def ids_by_state(self, state: int) -> list[str]:
        return self._rows_where(self.state == int(state))

    def ids_by_node(self, node_id: str) -> list[str]:
        i = self.nodes.lookup(node_id)
        if i <= 0:
            return []
        return self._rows_where(self.node_idx == i)

    def ids_by_service(self, service_id: str) -> list[str]:
        i = self.services.lookup(service_id)
        if i < 0:
            return []
        return self._rows_where(self.service_idx == i)

    def count_by_state(self) -> dict[int, int]:
        self.stats["array_queries"] += 1
        states = self.state[self.valid]
        uniq, counts = np.unique(states, return_counts=True)
        return {int(s): int(c) for s, c in zip(uniq, counts)}

    # ------------------------------------------------- rebuild / parity
    def snapshot(self) -> dict:
        """Canonical (row-order-independent) image of the columns: every
        live task in sorted-id order, node/service indices resolved back
        to strings — bit-comparable against a from-scratch rebuild no
        matter how rows and vocab ids were historically assigned."""
        order = sorted(self._row)
        rows = np.fromiter((self._row[t] for t in order), np.int64,
                           len(order))
        return {
            "ids": order,
            "state": self.state[rows].copy(),
            "desired": self.desired[rows].copy(),
            "version": self.version[rows].copy(),
            "slot": self.slot[rows].copy(),
            "spec_version": self.spec_version[rows].copy(),
            "node_ids": [self.nodes.name(i) for i in self.node_idx[rows]],
            "service_ids": [self.services.name(i)
                            for i in self.service_idx[rows]],
        }

    @classmethod
    def rebuild(cls, tasks: list, services: list = (),
                nodes: list = (), secrets: list = (),
                configs: list = ()) -> "ColumnarTasks":
        """From-scratch mirror of a task list (the bit-equality oracle in
        tests, and the restore path). `services`/`nodes`/`secrets`/
        `configs` feed the hot sub-mirrors (the restore path passes
        them; parity tests that only compare task columns may omit
        them)."""
        col = cls(cap=max(len(tasks), 1))
        col.upsert_many(sorted(tasks, key=lambda t: t.id))
        for s in sorted(services, key=lambda s: s.id):
            col.service_cols.upsert(s)
        for n in sorted(nodes, key=lambda n: n.id):
            col.node_cols.upsert(n)
        for s in sorted(secrets, key=lambda s: s.id):
            col.secret_cols.upsert(s)
        for c in sorted(configs, key=lambda c: c.id):
            col.config_cols.upsert(c)
        return col

    # ------------------------------------------- snapshot section (ISSUE 18)
    def to_snapshot_section(self) -> dict:
        """Serialize the LIVE column layout (row order, free rows, vocab
        ids intact) as a versioned, codec-safe dict — the optional
        `__columnar__` section MemoryStore.save() embeds so restore()
        can rebuild the hot mirrors by array ADOPTION instead of
        rebuild()'s O(objects) upsert walk. Must be called under the
        store lock (the commit path is the only other column writer);
        tobytes() copies, so the section is immune to later commits."""
        n = len(self.ids)
        sc, nc = self.service_cols, self.node_cols
        n_s, n_n = len(self.services), len(self.nodes)
        sec = {
            "v": COLUMNAR_SECTION_VERSION,
            "ids": list(self.ids),                 # None = freed row
            "nodes_vocab": list(self.nodes.names),
            "services_vocab": list(self.services.names),
            "tasks": {name: _enc(getattr(self, name)[:n])
                      for name in self._COLS},
            "service_cols": {name: _enc(getattr(sc, name)[:n_s])
                             for name in ColumnarServices._COLS},
            "node_cols": {name: _enc(getattr(nc, name)[:n_n])
                          for name in ColumnarNodes._COLS},
        }
        for key, dep in (("secret_cols", self.secret_cols),
                         ("config_cols", self.config_cols)):
            n_d = len(dep.vocab)
            sec[key] = {
                "vocab": list(dep.vocab.names),
                "cols": {name: _enc(getattr(dep, name)[:n_d])
                         for name in ColumnarDeps._COLS},
            }
        return sec

    @classmethod
    def adopt(cls, section, tasks: list, services: list = (),
              nodes: list = (), secrets: list = (),
              configs: list = ()) -> "ColumnarTasks | None":
        """Reconstruct a mirror from a to_snapshot_section() payload by
        array adoption, validated against the freshly restored object
        tables. Returns None on ANY inconsistency — unknown version,
        dtype/length drift, id-set mismatch vs the task table, version
        column disagreeing with the objects, vocab not covering an index
        — and the caller falls back to rebuild(). The parity bar: an
        adopted mirror's snapshot() is bit-equal to rebuild()'s."""
        if not isinstance(section, dict) \
                or section.get("v") != COLUMNAR_SECTION_VERSION:
            return None
        ids = section.get("ids")
        if not isinstance(ids, list) or not all(
                tid is None or isinstance(tid, str) for tid in ids):
            return None
        live = [tid for tid in ids if tid is not None]
        by_id = {t.id: t for t in tasks}
        if len(live) != len(set(live)) or set(live) != set(by_id):
            return None
        nv = _revocab(section.get("nodes_vocab"))
        sv = _revocab(section.get("services_vocab"))
        if nv is None or sv is None:
            return None
        n = len(ids)
        col = cls(cap=max(n, 1))
        tcols = section.get("tasks")
        if not isinstance(tcols, dict):
            return None
        for name in cls._COLS:
            arr = _dec(tcols.get(name), getattr(col, name).dtype, n)
            if arr is None:
                return None
            if n == 0:
                continue  # keep the constructor's 1-row zero capacity
            setattr(col, name, arr)
        col.ids = list(ids)
        col._row = {tid: r for r, tid in enumerate(ids) if tid is not None}
        col._free = [r for r, tid in enumerate(ids) if tid is None]
        col.nodes, col.services = nv, sv
        # cross-checks against the restored object tables: the live rows
        # must be valid, reference in-vocab ids, and carry each object's
        # exact version — a stale or torn section must never adopt
        rows = np.fromiter(col._row.values(), np.int64, len(col._row))
        if rows.size:
            if not col.valid[rows].all():
                return None
            if int(col.node_idx[rows].max(initial=0)) >= len(nv) \
                    or int(col.service_idx[rows].max(initial=0)) >= len(sv):
                return None
            versions = np.fromiter(
                (by_id[tid].meta.version.index for tid in col._row),
                np.int64, len(col._row))
            if not np.array_equal(col.version[rows], versions):
                return None
        freed = np.fromiter(col._free, np.int64, len(col._free))
        if freed.size and col.valid[freed].any():
            return None
        # sub-mirrors: columns sized exactly to their vocab
        col.service_cols = ColumnarServices(sv, cap=len(sv))
        col.node_cols = ColumnarNodes(nv, cap=len(nv))
        for owner, key, n_rows in (
                (col.service_cols, "service_cols", len(sv)),
                (col.node_cols, "node_cols", len(nv))):
            cols = section.get(key)
            if not isinstance(cols, dict):
                return None
            for name in owner._COLS:
                arr = _dec(cols.get(name), getattr(owner, name).dtype,
                           n_rows)
                if arr is None:
                    return None
                setattr(owner, name, arr)
        for key, attr in (("secret_cols", "secret_cols"),
                          ("config_cols", "config_cols")):
            payload = section.get(key)
            if not isinstance(payload, dict):
                return None
            dv = _revocab(payload.get("vocab"))
            cols = payload.get("cols")
            if dv is None or not isinstance(cols, dict):
                return None
            dep = ColumnarDeps(cap=len(dv))
            dep.vocab = dv
            for name in ColumnarDeps._COLS:
                arr = _dec(cols.get(name), getattr(dep, name).dtype,
                           len(dv))
                if arr is None:
                    return None
                setattr(dep, name, arr)
            setattr(col, attr, dep)
        return col

    @staticmethod
    def snapshots_equal(a: dict, b: dict) -> bool:
        if a["ids"] != b["ids"] or a["node_ids"] != b["node_ids"] \
                or a["service_ids"] != b["service_ids"]:
            return False
        return all(np.array_equal(a[k], b[k])
                   for k in ("state", "desired", "version", "slot",
                             "spec_version"))
