"""Subprocess executor: the real-workload executor for swarmd.

The reference's production executor adapts tasks onto the Docker Engine API
(swarmd/dockerexec/controller.go:95-256 — Prepare creates the container,
Start runs it, Wait blocks on exit, Shutdown stops with a grace period,
Terminate kills). Our runtime substrate is the host itself: a task's
ContainerSpec.command/args/env run as a child process, which makes swarmd a
real process orchestrator without a container engine dependency.

FSM mapping (agent/exec.do drives this through the task states):
    prepare   → validate the spec, resolve the command
    start     → spawn the child (its own process group)
    wait      → wait for exit; nonzero exit → task FAILED with the code
    shutdown  → SIGTERM, then SIGKILL after stop_grace_period
    terminate → SIGKILL
Logs: stdout/stderr are captured to per-task files under the state dir and
served to the LogBroker via `logs()`.
"""
from __future__ import annotations

import os
import shlex
import signal
import subprocess
import threading

from ..analysis.lockgraph import make_lock
from ..api.objects import Task
from ..api.specs import NodeDescription, Platform, Resources
from .exec import ExitStatus, FatalError


def _platform() -> Platform:
    u = os.uname()
    arch = {"x86_64": "amd64", "aarch64": "arm64"}.get(u.machine, u.machine)
    return Platform(os=u.sysname.lower(), architecture=arch)


def _total_memory() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 8 * 2**30


class SubprocessController:
    def __init__(self, task: Task, log_dir: str | None,
                 secrets_dir: str | None = None,
                 dependencies=None):
        self.task = task
        self.log_dir = log_dir
        # per-task sandbox root for materialized secret/config files (the
        # reference mounts them at /run/secrets|/run/configs inside the
        # container, dockerexec/container.go; a process executor exposes
        # them as files + SWARMKIT_SECRETS_DIR/SWARMKIT_CONFIGS_DIR)
        self.secrets_root = (os.path.join(secrets_dir, task.id)
                             if secrets_dir else None)
        self.dependencies = dependencies  # (secrets_by_id, configs_by_id)
        self._proc: subprocess.Popen | None = None
        self._cmd: list[str] | None = None
        self._env: dict[str, str] | None = None
        self._lock = make_lock('agent.subprocexec.lock')
        self._exited = threading.Event()
        self._exit_code: int | None = None
        self._log_path: str | None = None

    # ------------------------------------------------------------------ FSM
    def update(self, task: Task):
        self.task = task

    def prepare(self):
        spec = self.task.spec.runtime
        if spec is None:
            raise FatalError("task has no container runtime spec")
        cmd = list(spec.command) + list(spec.args)
        if not cmd:
            # the "image" is the program for a process executor; support
            # `image: "sh -c '...'"` style one-liners
            if spec.image:
                cmd = shlex.split(spec.image)
        if not cmd:
            raise FatalError("no command to run")
        self._cmd = cmd
        env = dict(os.environ)
        for kv in spec.env:
            key, _, value = kv.partition("=")
            env[key] = value
        env["SWARMKIT_TASK_ID"] = self.task.id
        env["SWARMKIT_SERVICE_ID"] = self.task.service_id
        env["SWARMKIT_NODE_ID"] = self.task.node_id
        env["SWARMKIT_SLOT"] = str(self.task.slot)
        self._materialize_deps(spec, env)
        self._env = env
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            self._log_path = os.path.join(self.log_dir,
                                          f"{self.task.id}.log")

    def _materialize_deps(self, spec, env: dict[str, str]):
        """Write the task's secret/config payloads (already templated-
        expanded by the worker's restricted getter) under the per-task
        sandbox dir at each reference's target filename — the process-
        executor analogue of the reference's tmpfs secret mounts
        (dockerexec/container.go secret/config mount wiring)."""
        if self.secrets_root is None or self.dependencies is None:
            return
        secrets_by_id, configs_by_id = self.dependencies
        wrote_secret = wrote_config = False
        for kind, refs, objs, id_attr in (
                ("secrets", spec.secrets, secrets_by_id, "secret_id"),
                ("configs", spec.configs, configs_by_id, "config_id")):
            for ref in refs:
                obj = objs.get(getattr(ref, id_attr))
                if obj is None:
                    raise FatalError(
                        f"{kind[:-1]} {getattr(ref, id_attr)} not assigned "
                        "to this node")
                # the FULL target path relative to the sandbox dir (the
                # reference mounts each at its target inside the container:
                # 'db/password' and 'cache/password' are distinct files) —
                # but never outside it
                target = (ref.target or obj.spec.annotations.name).lstrip("/")
                target = os.path.normpath(target)
                if target.startswith("..") or os.path.isabs(target) \
                        or not target or target == ".":
                    raise FatalError(
                        f"invalid {kind[:-1]} target {ref.target!r}")
                d = os.path.join(self.secrets_root, kind)
                path = os.path.join(d, target)
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "wb") as f:
                    f.write(obj.spec.data)
                os.chmod(path, 0o600)
                if kind == "secrets":
                    wrote_secret = True
                else:
                    wrote_config = True
        if wrote_secret:
            env["SWARMKIT_SECRETS_DIR"] = os.path.join(self.secrets_root,
                                                       "secrets")
        if wrote_config:
            env["SWARMKIT_CONFIGS_DIR"] = os.path.join(self.secrets_root,
                                                       "configs")

    def start(self):
        if self._cmd is None:
            raise FatalError("start before prepare")
        out = (open(self._log_path, "ab")
               if self._log_path else subprocess.DEVNULL)
        try:
            proc = subprocess.Popen(
                self._cmd,
                stdout=out,
                stderr=subprocess.STDOUT,
                stdin=subprocess.DEVNULL,
                env=self._env,
                cwd=self.task.spec.runtime.dir or None,
                start_new_session=True,  # own process group: kill the tree
            )
        except (OSError, ValueError) as exc:
            raise FatalError(f"spawn failed: {exc}") from exc
        finally:
            if out is not subprocess.DEVNULL:
                out.close()
        with self._lock:
            self._proc = proc

    def wait(self) -> ExitStatus:
        with self._lock:
            proc = self._proc
        if proc is None:
            raise FatalError("wait before start")
        code = proc.wait()
        self._exit_code = code
        self._exited.set()
        return ExitStatus(code, f"exit {code}" if code else "")

    def _signal_group(self, sig: int):
        with self._lock:
            proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError):
            try:
                proc.send_signal(sig)
            except ProcessLookupError:
                pass

    def shutdown(self):
        """Graceful stop: SIGTERM, escalate to SIGKILL after the spec's
        grace period (dockerexec Shutdown → engine stop semantics)."""
        spec = self.task.spec.runtime
        grace = spec.stop_grace_period if spec is not None else 10.0
        self._signal_group(signal.SIGTERM)
        with self._lock:
            proc = self._proc
        if proc is None:
            return
        try:
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            self._signal_group(signal.SIGKILL)

    def terminate(self):
        self._signal_group(signal.SIGKILL)

    def remove(self):
        if self._log_path and os.path.exists(self._log_path):
            try:
                os.unlink(self._log_path)
            except OSError:
                pass
        if self.secrets_root and os.path.isdir(self.secrets_root):
            import shutil

            shutil.rmtree(self.secrets_root, ignore_errors=True)

    def logs(self):
        """Captured output for the LogBroker (stream, bytes) tuples."""
        if not self._log_path or not os.path.exists(self._log_path):
            return
        with open(self._log_path, "rb") as f:
            for line in f:
                yield "stdout", line.rstrip(b"\n")

    def close(self):
        self.terminate()


class SubprocessExecutor:
    """exec.Executor running tasks as host child processes."""

    def __init__(self, state_dir: str | None = None, hostname: str | None = None):
        self.log_dir = (os.path.join(state_dir, "task-logs")
                        if state_dir else None)
        self.secrets_dir = (os.path.join(state_dir, "task-deps")
                            if state_dir else None)
        self.hostname = hostname or os.uname().nodename

    def describe(self) -> NodeDescription:
        return NodeDescription(
            hostname=self.hostname,
            platform=_platform(),
            resources=Resources(
                nano_cpus=(os.cpu_count() or 1) * 10**9,
                memory_bytes=_total_memory(),
            ),
        )

    def configure(self, node):
        pass

    def controller(self, task: Task, dependencies=None) -> SubprocessController:
        return SubprocessController(task, self.log_dir,
                                    secrets_dir=self.secrets_dir,
                                    dependencies=dependencies)

    def set_network_bootstrap_keys(self, keys):
        pass
