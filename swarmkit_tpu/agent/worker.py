"""Worker: applies assignment sets and runs task managers.

Behavioral re-derivation of agent/worker.go + agent/task.go: full `assign`
replaces the task set, `update` applies incremental diffs; each task gets a
manager thread driving its controller through the FSM via exec.do, reporting
every observed transition to the reporter; secrets/configs land in restricted
in-memory stores; task state persists to a local JSON file (the reference's
BoltDB, agent/storage.go) so an agent restart resumes where it left off.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable

from ..analysis.lockgraph import make_lock
from ..api.objects import Task, TaskStatus
from ..api.specs import deepcopy_spec
from ..api.types import TaskState
from ..template.context import TemplateError
from . import exec as exec_mod

RUN_PROBE_INTERVAL = 0.05  # task manager poll; reference uses 10s run probe


def _has_template_markers(runtime) -> bool:
    """Cheap pre-scan so template-free tasks (the overwhelming majority)
    skip the per-start deepcopy + full expansion pass."""
    return (any("{{" in e for e in runtime.env)
            or "{{" in runtime.dir or "{{" in runtime.user
            or any("{{" in (getattr(m, "source", "") or "")
                   for m in runtime.mounts))


class DependencyStore:
    """Task-restricted secret/config access (agent/secrets, agent/configs)."""

    def __init__(self):
        self._secrets: dict[str, object] = {}
        self._configs: dict[str, object] = {}
        self._lock = make_lock('agent.worker.dependency_store')

    def update_secret(self, secret):
        with self._lock:
            self._secrets[secret.id] = secret

    def remove_secret(self, secret_id: str):
        with self._lock:
            self._secrets.pop(secret_id, None)

    def update_config(self, config):
        with self._lock:
            self._configs[config.id] = config

    def remove_config(self, config_id: str):
        with self._lock:
            self._configs.pop(config_id, None)

    def restricted(self, task: Task, node=None):
        """Only the task's own references are readable (agent/dependency.go),
        and templated payloads come back EXPANDED — the templated dependency
        getter (reference template/getter.go:16-121): a secret/config whose
        spec sets `templating` is returned as a copy with its data expanded
        against the (node, task) context; the context's secret/config maps
        are the task's raw sibling dependencies, so a templated secret can
        splice in another secret. Raises TemplateError on a bad template
        (the caller maps it to task rejection)."""
        runtime = task.spec.runtime
        allowed_secrets = {r.secret_id for r in runtime.secrets} if runtime else set()
        allowed_configs = {r.config_id for r in runtime.configs} if runtime else set()
        with self._lock:
            secrets = {k: v for k, v in self._secrets.items()
                       if k in allowed_secrets}
            configs = {k: v for k, v in self._configs.items()
                       if k in allowed_configs}
        if any(s.spec.templating for s in secrets.values()) or \
                any(c.spec.templating for c in configs.values()):
            from ..template.context import Context, expand_payload

            raw_s = {s.spec.annotations.name: s.spec.data
                     for s in secrets.values()}
            raw_c = {c.spec.annotations.name: c.spec.data
                     for c in configs.values()}
            ctx = Context.from_task(node, None, task,
                                    secrets=raw_s, configs=raw_c)
            for sid, s in list(secrets.items()):
                if s.spec.templating:
                    s = s.copy()
                    s.spec.data = expand_payload(ctx, s.spec.data)
                    secrets[sid] = s
            for cid, c in list(configs.items()):
                if c.spec.templating:
                    c = c.copy()
                    c.spec.data = expand_payload(ctx, c.spec.data)
                    configs[cid] = c
        return secrets, configs


class TaskManager(threading.Thread):
    """Per-task FSM driver (agent/task.go:16-140)."""

    def __init__(self, task: Task, controller, report: Callable[[str, TaskStatus], None]):
        super().__init__(daemon=True, name=f"taskmgr-{task.id[:8]}")
        self.task = task
        self.controller = controller
        self.report = report
        self._lock = make_lock('agent.worker.taskmanager')
        self._halt = threading.Event()
        self._poke = threading.Event()
        self._shutdown_requested = False

    def update(self, task: Task):
        with self._lock:
            prev_desired = self.task.desired_state
            # desired state changes flow in; observed state stays ours.
            # The spec is NOT replaced: a task's spec is immutable once
            # created (service updates make NEW tasks), and our copy is
            # the template-EXPANDED one — the wire version would regress it
            self.task.desired_state = task.desired_state
            want_shutdown = (task.desired_state >= TaskState.SHUTDOWN
                             and prev_desired < TaskState.SHUTDOWN)
        if want_shutdown:
            # the run loop may be blocked inside controller.wait(); signal
            # the runtime directly so wait() returns (the reference runs
            # Wait concurrently with desired-state handling, agent/task.go)
            self._shutdown_requested = True
            try:
                self.controller.shutdown()
            except Exception:
                pass
        self._poke.set()

    def stop(self):
        self._halt.set()
        try:
            self.controller.terminate()
        except Exception:
            pass
        self._poke.set()

    def run(self):
        while not self._halt.is_set():
            with self._lock:
                task = self.task
                before = task.status.state
            status = exec_mod.do(task, self.controller)
            if self._shutdown_requested and status.state == TaskState.COMPLETE:
                # wait() returned because shutdown was requested, not because
                # the workload finished: the observed terminal state is
                # SHUTDOWN (reference exec.Do desired-state gating)
                status = exec_mod._status(task, TaskState.SHUTDOWN, "shutdown")
            with self._lock:
                changed = status.state != before or status.err != task.status.err
                task.status = status
            if changed:
                self.report(task.id, status)
            if status.state >= TaskState.COMPLETE:
                break
            if status.state == before:
                # blocked (e.g. READY awaiting desired RUNNING); wait for poke
                self._poke.wait(RUN_PROBE_INTERVAL)
                self._poke.clear()
        try:
            self.controller.close()
        except Exception:
            pass


class Worker:
    """reference: agent/worker.go."""

    def __init__(self, executor, report: Callable[[str, TaskStatus], None],
                 state_path: str | None = None, volume_manager=None,
                 node_id: str | None = None):
        self.executor = executor
        self.report = report
        self.state_path = state_path
        self.node_id = node_id
        self.deps = DependencyStore()
        self.volumes = volume_manager  # NodeVolumeManager (agent/csi.py)
        self._managers: dict[str, TaskManager] = {}
        self._tasks: dict[str, Task] = {}
        # tasks parked until their CSI volumes are staged (worker waitReady)
        self._awaiting_volumes: dict[str, Task] = {}
        self._node_view = None
        import inspect
        try:
            self._controller_takes_deps = "dependencies" in \
                inspect.signature(executor.controller).parameters
        except (TypeError, ValueError):
            self._controller_takes_deps = False
        self._lock = make_lock('agent.worker.worker')
        self._load_state()

    # ------------------------------------------------------------ assignment
    def assign(self, changes):
        """Full set (reference worker.go:129-166)."""
        with self._lock:
            wanted_tasks: dict[str, Task] = {}
            wanted_volumes: set[str] = set()
            for ch in changes:
                if ch.kind == "task" and ch.action == "update":
                    wanted_tasks[ch.item.id] = ch.item
                elif ch.kind == "volume" and ch.action == "update":
                    wanted_volumes.add(ch.item.id)
            self._apply_deps(changes, full=True)
            if self.volumes is not None:
                self.volumes.reconcile(wanted_volumes)
            # drop unknown tasks
            for tid in list(self._managers):
                if tid not in wanted_tasks:
                    self._shutdown_manager(tid)
            for tid in list(self._awaiting_volumes):
                if tid not in wanted_tasks:
                    del self._awaiting_volumes[tid]
            for task in wanted_tasks.values():
                self._start_or_update(task)
        self._persist()

    def subscribe_logs(self, selector, publish, skip_task_ids=()) -> set[str]:
        """Pump logs for this worker's tasks matching `selector` through
        `publish(task, stream, data)` (reference worker.go Subscribe:596 →
        taskManager log attachment). `skip_task_ids` are tasks already
        pumped for this subscription (the caller's dedupe, so follow-mode
        re-offers only emit new tasks). Returns the task ids pumped.
        Controllers opt in by exposing `logs() -> iterable[(stream, bytes)]`."""
        with self._lock:
            managers = list(self._managers.values())
        pumped: set[str] = set()
        for mgr in managers:
            t = mgr.task
            if t.id in skip_task_ids:
                continue
            if (
                t.id in selector.task_ids
                or t.service_id in selector.service_ids
                or t.node_id in selector.node_ids
            ):
                logs_fn = getattr(mgr.controller, "logs", None)
                if logs_fn is None:
                    continue
                pumped.add(t.id)
                for stream, data in logs_fn():
                    publish(t, stream, data)
        return pumped

    def update(self, changes):
        """Incremental diff (reference worker.go:168-196)."""
        with self._lock:
            self._apply_deps(changes, full=False)
            for ch in changes:
                if ch.kind != "task":
                    continue
                if ch.action == "update":
                    self._start_or_update(ch.item)
                else:
                    self._shutdown_manager(ch.item)
        self._persist()

    def _apply_deps(self, changes, full: bool):
        if full:
            self.deps = DependencyStore()
        for ch in changes:
            if ch.kind == "secret":
                if ch.action == "update":
                    self.deps.update_secret(ch.item)
                else:
                    self.deps.remove_secret(ch.item)
            elif ch.kind == "config":
                if ch.action == "update":
                    self.deps.update_config(ch.item)
                else:
                    self.deps.remove_config(ch.item)
            elif ch.kind == "volume" and self.volumes is not None:
                if ch.action == "update":
                    self.volumes.add(ch.item)
                else:
                    self.volumes.remove(ch.item)

    def volume_ready(self, volume_obj_id: str):
        """A CSI volume finished staging: start any parked tasks whose
        volume set is now fully ready (worker waitReady unblocking)."""
        with self._lock:
            ready = [
                t
                for t in self._awaiting_volumes.values()
                if all(self.volumes.is_ready(v) for v in t.volumes)
            ]
            for t in ready:
                del self._awaiting_volumes[t.id]
                self._start_or_update(t)

    def _start_or_update(self, task: Task):
        mgr = self._managers.get(task.id)
        if mgr is not None and mgr.is_alive():
            mgr.update(task)
            return
        if (
            self.volumes is not None
            and task.volumes
            and not all(self.volumes.is_ready(v) for v in task.volumes)
        ):
            # park until node staging completes; resumed by volume_ready
            self._awaiting_volumes[task.id] = task
            return
        self._awaiting_volumes.pop(task.id, None)
        known = self._tasks.get(task.id)
        if known is not None and known.status.state > task.status.state:
            # we know more than the manager does (restart case)
            task = task.copy()
            task.status = known.status
        if task.status.state >= TaskState.COMPLETE:
            self._tasks[task.id] = task
            return
        task = task.copy()
        try:
            task, secrets, configs = self._expand_task(task)
        except TemplateError as exc:
            # pre-start fatal: the reference's exec.Do maps failures before
            # start to REJECTED (agent/exec/controller.go fatal handling)
            status = exec_mod._status(task, TaskState.REJECTED, "rejected",
                                      err=f"template expansion failed: {exc}")
            task.status = status
            self._tasks[task.id] = task
            self.report(task.id, status)
            return
        if self._controller_takes_deps:
            controller = self.executor.controller(
                task, dependencies=(secrets, configs))
        else:
            controller = self.executor.controller(task)
        mgr = TaskManager(task, controller, self._report_and_track)
        self._managers[task.id] = mgr
        self._tasks[task.id] = task
        mgr.start()

    def _node_view_obj(self):
        """Node identity + description for the template context, built from
        the executor's own Describe (the same source the dispatcher
        registration advertises). A failed describe is NOT cached — the
        next task start retries it rather than pinning every later
        {{.Node.*}} expansion to empty strings."""
        if self._node_view is None:
            from types import SimpleNamespace

            try:
                desc = self.executor.describe()
            except Exception:
                return SimpleNamespace(id=self.node_id or "",
                                       description=None)
            self._node_view = SimpleNamespace(id=self.node_id or "",
                                              description=desc)
        return self._node_view

    def _expand_task(self, task: Task):
        """Executor-boundary template expansion (reference dockerexec/
        container.go:68 ExpandContainerSpec + template/getter.go getters):
        the container spec's env/dir/user/mount-sources are expanded
        against the (node, service, task) context — with the task's own
        restricted secret/config payloads available to `{{secret ...}}` —
        and templated dependency payloads come back expanded. Raises
        TemplateError; the caller rejects the task."""
        node = self._node_view_obj()
        secrets, configs = self.deps.restricted(task, node=node)
        runtime = task.spec.runtime
        if runtime is not None and hasattr(runtime, "env") \
                and _has_template_markers(runtime):
            from ..template.context import Context, expand_container_spec

            raw_s = {s.spec.annotations.name: s.spec.data
                     for s in secrets.values()}
            raw_c = {c.spec.annotations.name: c.spec.data
                     for c in configs.values()}
            ctx = Context.from_task(node, None, task,
                                    secrets=raw_s, configs=raw_c)
            task.spec = deepcopy_spec(task.spec)
            task.spec.runtime = expand_container_spec(ctx, runtime)
        return task, secrets, configs

    def _shutdown_manager(self, task_id: str):
        mgr = self._managers.pop(task_id, None)
        if mgr is not None:
            mgr.stop()
        self._tasks.pop(task_id, None)

    def _report_and_track(self, task_id: str, status: TaskStatus):
        with self._lock:
            t = self._tasks.get(task_id)
            if t is not None:
                t.status = status
        self._persist()
        self.report(task_id, status)

    # ----------------------------------------------------------- persistence
    def _persist(self):
        if not self.state_path:
            return
        with self._lock:
            data = {
                tid: {"state": int(t.status.state), "message": t.status.message,
                      "err": t.status.err}
                for tid, t in self._tasks.items()
            }
        tmp = self.state_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, self.state_path)
        except OSError:
            pass

    def _load_state(self):
        if not self.state_path or not os.path.exists(self.state_path):
            return
        try:
            with open(self.state_path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        for tid, st in data.items():
            t = Task(id=tid)
            t.status = TaskStatus(state=TaskState(st["state"]),
                                  message=st.get("message", ""),
                                  err=st.get("err", ""))
            self._tasks[tid] = t

    def stop(self):
        with self._lock:
            managers = list(self._managers.values())
            self._managers.clear()
        for m in managers:
            m.stop()
        for m in managers:
            m.join(timeout=1)
