"""Agent: the worker-side session lifecycle.

Behavioral re-derivation of agent/{agent.go, session.go, reporter.go}:
register with a dispatcher, heartbeat on the returned period, consume the
assignment stream (COMPLETE → worker.assign, INCREMENTAL → worker.update),
and batch observed-status updates back upstream with retry. Reconnects with
exponential backoff when the session dies (session.go:90-118).
"""
from __future__ import annotations

import logging
import threading
import time

from ..analysis.lockgraph import make_lock
from ..api.objects import TaskStatus
from ..store.watch import ChannelClosed
from .worker import Worker

log = logging.getLogger("swarmkit_tpu.agent")

REPORT_INTERVAL = 0.05
BACKOFF_BASE = 0.1
BACKOFF_MAX = 8.0


class Agent:
    # log-pump batching: messages buffered per subscription event and
    # shipped in chunks of this many via ONE publish_logs each (ISSUE 20)
    LOG_PUBLISH_CHUNK = 256

    def __init__(self, node_id: str, dispatcher, executor,
                 state_path: str | None = None, log_broker=None,
                 csi_plugins=None, generic_resources=None,
                 fips: bool = False):
        self.node_id = node_id
        self.dispatcher = dispatcher
        self.executor = executor
        # operator-declared generic resources (swarmd
        # --generic-node-resources, e.g. gpu=4 or gpu=id1;id2) merged into
        # the advertised NodeDescription (reference swarmd main.go:38-266);
        # either a {kind: count} dict or an api Resources (parse_cmd output)
        self.generic_resources = generic_resources
        # advertised in the NodeDescription: a mandatory-FIPS cluster's
        # dispatcher refuses registrations that don't carry it
        self.fips = fips
        self.log_broker = log_broker
        self.volume_manager = None
        if csi_plugins is not None:
            from .csi import NodeVolumeManager

            self.volume_manager = NodeVolumeManager(
                csi_plugins, on_unpublished=self._report_unpublished
            )
        self.worker = Worker(executor, self._enqueue_status, state_path,
                             volume_manager=self.volume_manager,
                             node_id=node_id)
        if self.volume_manager is not None:
            self.volume_manager.on_ready = self.worker.volume_ready
        self.session_id: str | None = None
        # Session-message consumer (manager list, root CA, network keys,
        # role changes — agent/agent.go handleSessionMessage:416-477). The
        # daemon sets this to drive seed updates and role flips.
        self.on_session_message = None
        self._pending: dict[str, TaskStatus] = {}
        self._unpublished_pending: set[str] = set()
        self._pending_lock = make_lock('agent.agent.pending_lock')
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------- lifecycle
    def start(self):
        if self.volume_manager is not None:
            self.volume_manager.start()
        t = threading.Thread(target=self._run, daemon=True,
                             name=f"agent-{self.node_id[:8]}")
        t.start()
        self._threads.append(t)
        if self.log_broker is not None:
            lt = threading.Thread(target=self._listen_subscriptions, daemon=True,
                                  name=f"agent-logs-{self.node_id[:8]}")
            lt.start()
            self._threads.append(lt)

    def _listen_subscriptions(self):
        """Consume log-subscription messages from the broker and pump
        matching task logs back (reference agent/agent.go subscriptions +
        worker.Subscribe). The reference streams continuously; controllers
        here surface their buffered logs per subscription event."""
        from ..logbroker.broker import make_log_message
        from ..store.watch import ChannelClosed

        ch = self.log_broker.listen_subscriptions(self.node_id)
        # sub id -> task ids already pumped (follow-mode re-offers only
        # emit tasks that appeared since)
        pumped: dict[str, set[str]] = {}
        while not self._stop.is_set():
            try:
                msg = ch.get(timeout=0.2)
            except TimeoutError:
                continue
            except ChannelClosed:
                # broker restarted (leadership flap) or channel overflow:
                # re-listen, like the session reconnect loop does
                if self._stop.wait(timeout=0.2):
                    return
                try:
                    ch = self.log_broker.listen_subscriptions(self.node_id)
                except Exception:
                    continue  # broker unreachable; retry after the wait
                pumped.clear()
                continue
            if msg.close:
                pumped.pop(msg.id, None)
                continue
            sub_id = msg.id

            # batched pump (ISSUE 20): the broker's publish path is one
            # offer burst per call, so the agent buffers and ships chunks
            # instead of one RPC + one channel offer per log line
            buf: list = []

            def publish(task, stream, data, sub_id=sub_id, buf=buf):
                buf.append(make_log_message(task, stream, data))
                if len(buf) >= self.LOG_PUBLISH_CHUNK:
                    chunk = buf[:]
                    buf.clear()
                    self.log_broker.publish_logs(sub_id, chunk)

            err = ""
            try:
                done = pumped.setdefault(sub_id, set())
                done |= self.worker.subscribe_logs(
                    msg.selector, publish, skip_task_ids=done
                )
            except Exception as exc:
                err = f"log pump failed on {self.node_id}: {exc}"
            if buf:
                # tail flush — also after a pump failure: these messages
                # were produced before the fault
                try:
                    self.log_broker.publish_logs(sub_id, buf)
                except Exception as exc:
                    if not err:
                        err = f"log pump failed on {self.node_id}: {exc}"
            if not msg.follow:
                # publisher EOF: this node pumped everything it has — the
                # broker's completion accounting ends the client stream
                # once every publisher closed (broker.go PublishLogs EOF).
                # The dedupe entry goes with it: the broker never re-offers
                # a completed non-follow subscription.
                try:
                    self.log_broker.publish_logs(
                        sub_id, [], node_id=self.node_id, close=True,
                        error=err)
                except Exception:
                    pass
                pumped.pop(sub_id, None)

    def stop(self):
        self._stop.set()
        if self.volume_manager is not None:
            self.volume_manager.stop()
        self.worker.stop()
        for t in self._threads:
            t.join(timeout=2)

    def _report_unpublished(self, volume_obj_id: str):
        """NodeVolumeManager finished node-unpublish → confirm upstream
        (agent/csi/volumes.go → Dispatcher.UpdateVolumeStatus)."""
        with self._pending_lock:
            self._unpublished_pending.add(volume_obj_id)
        self._flush_unpublished()

    def _flush_unpublished(self):
        sid = self.session_id
        if sid is None:
            return  # flushed again once a session is established
        with self._pending_lock:
            pending = list(self._unpublished_pending)
        if not pending:
            return
        try:
            self.dispatcher.update_volume_status(self.node_id, sid, pending)
        except Exception:
            return  # kept pending; next session flush retries
        with self._pending_lock:
            self._unpublished_pending.difference_update(pending)

    def leave(self):
        if self.session_id is not None:
            try:
                self.dispatcher.leave(self.node_id, self.session_id)
            except Exception:
                pass
        self.stop()

    # ---------------------------------------------------------------- session
    def _run(self):
        backoff = BACKOFF_BASE
        while not self._stop.is_set():
            try:
                self._session()
                backoff = BACKOFF_BASE
            except Exception as e:
                if self._stop.is_set():
                    return
                log.debug("agent %s session error: %r; reconnecting in %.2fs",
                          self.node_id, e, backoff)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, BACKOFF_MAX)

    def _session(self):
        description = self.executor.describe()
        if description is not None and self.fips:
            description.fips = True
        gr = self.generic_resources
        if gr and description is not None \
                and description.resources is not None:
            if isinstance(gr, dict):
                for kind, qty in gr.items():
                    description.resources.generic[kind] = qty
            else:  # api Resources from genericresource.parse_cmd
                for kind, qty in gr.generic.items():
                    description.resources.generic[kind] = qty
                for kind, ids in gr.named_generic.items():
                    description.resources.named_generic.setdefault(
                        kind, set()).update(ids)
        if self.volume_manager is not None:
            # advertise CSI driver support so the scheduler places cluster
            # volumes here (reference: agent fills NodeDescription.CSIInfo
            # from its node plugins)
            from ..api.specs import NodeCSIInfo

            for name in self.volume_manager.plugins.names():
                description.csi_info.setdefault(
                    name,
                    NodeCSIInfo(plugin_name=name, node_id=f"{name}-{self.node_id}"),
                )
                if name not in description.csi_plugins:
                    description.csi_plugins.append(name)
        session_id = self.dispatcher.register(self.node_id, description)
        self.session_id = session_id
        period = self.dispatcher.heartbeat(self.node_id, session_id)
        self._flush_unpublished()  # confirms lost across reconnects

        hb_stop = threading.Event()

        def heartbeat_loop():
            # each response carries the CURRENT period so live cluster
            # reconfig (dispatcher.go:1072-1077) re-paces the beats; a beat
            # slower than the server's grace window would flap the node DOWN
            from ..utils import telemetry

            p = period
            beats = 0
            while not (self._stop.is_set() or hb_stop.is_set()):
                if self._stop.wait(p / 2) or hb_stop.is_set():
                    return
                try:
                    # telemetry piggyback (ISSUE 15): every Kth beat
                    # carries this node's metric snapshot. Disarmed, the
                    # beat path is ONE truthiness test — no snapshot is
                    # ever built (the span-in-loop lint audits this
                    # guard), and the 2-arg call keeps driven-test
                    # dispatcher stubs working unchanged.
                    snap = None
                    if telemetry.enabled():
                        beats += 1
                        if beats % telemetry.report_every() == 0:
                            snap = telemetry.node_snapshot(agent=self)
                    if snap is not None:
                        p = self.dispatcher.heartbeat(
                            self.node_id, session_id, metrics=snap) or p
                    else:
                        p = self.dispatcher.heartbeat(
                            self.node_id, session_id) or p
                except Exception:
                    return

        def report_loop():
            while not (self._stop.is_set() or hb_stop.is_set()):
                self._flush_statuses(session_id)
                if self._stop.wait(REPORT_INTERVAL):
                    return

        def session_message_loop():
            """Consume the Session stream when both sides support it; its
            loss is non-fatal (the main session carries the workload)."""
            if self.on_session_message is None \
                    or not hasattr(self.dispatcher, "session"):
                return
            try:
                sch = self.dispatcher.session(self.node_id, session_id)
            except Exception:
                return
            while not (self._stop.is_set() or hb_stop.is_set()):
                try:
                    msg = sch.get(timeout=0.2)
                except TimeoutError:
                    continue
                except ChannelClosed:
                    return
                try:
                    self.on_session_message(msg)
                except Exception:
                    log.exception("agent %s: session message handler failed",
                                  self.node_id)

        hb = threading.Thread(target=heartbeat_loop, daemon=True)
        rp = threading.Thread(target=report_loop, daemon=True)
        sm = threading.Thread(target=session_message_loop, daemon=True)
        hb.start()
        rp.start()
        sm.start()

        try:
            ch = self.dispatcher.assignments(self.node_id, session_id)
            while not self._stop.is_set():
                try:
                    msg = ch.get(timeout=0.2)
                except TimeoutError:
                    continue
                if msg.type == "complete":
                    self.worker.assign(msg.changes)
                else:
                    self.worker.update(msg.changes)
        except ChannelClosed:
            raise ConnectionError("assignment stream closed")
        finally:
            hb_stop.set()
            self._flush_statuses(session_id)

    # ------------------------------------------------------------- reporting
    def _enqueue_status(self, task_id: str, status: TaskStatus):
        with self._pending_lock:
            self._pending[task_id] = status

    def _flush_statuses(self, session_id: str):
        with self._pending_lock:
            if not self._pending:
                return
            updates = list(self._pending.items())
            self._pending.clear()
        try:
            self.dispatcher.update_task_status(self.node_id, session_id, updates)
        except Exception:
            # retry later (reference agent/reporter.go retry queue)
            with self._pending_lock:
                for tid, st in updates:
                    self._pending.setdefault(tid, st)
