"""Agent: the worker-side session lifecycle.

Behavioral re-derivation of agent/{agent.go, session.go, reporter.go}:
register with a dispatcher, heartbeat on the returned period, consume the
assignment stream (COMPLETE → worker.assign, INCREMENTAL → worker.update),
and batch observed-status updates back upstream with retry. Reconnects with
exponential backoff when the session dies (session.go:90-118).
"""
from __future__ import annotations

import logging
import threading
import time

from ..api.objects import TaskStatus
from ..store.watch import ChannelClosed
from .worker import Worker

log = logging.getLogger("swarmkit_tpu.agent")

REPORT_INTERVAL = 0.05
BACKOFF_BASE = 0.1
BACKOFF_MAX = 8.0


class Agent:
    def __init__(self, node_id: str, dispatcher, executor,
                 state_path: str | None = None, log_broker=None):
        self.node_id = node_id
        self.dispatcher = dispatcher
        self.executor = executor
        self.log_broker = log_broker
        self.worker = Worker(executor, self._enqueue_status, state_path)
        self.session_id: str | None = None
        self._pending: dict[str, TaskStatus] = {}
        self._pending_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------- lifecycle
    def start(self):
        t = threading.Thread(target=self._run, daemon=True,
                             name=f"agent-{self.node_id[:8]}")
        t.start()
        self._threads.append(t)
        if self.log_broker is not None:
            lt = threading.Thread(target=self._listen_subscriptions, daemon=True,
                                  name=f"agent-logs-{self.node_id[:8]}")
            lt.start()
            self._threads.append(lt)

    def _listen_subscriptions(self):
        """Consume log-subscription messages from the broker and pump
        matching task logs back (reference agent/agent.go subscriptions +
        worker.Subscribe). The reference streams continuously; controllers
        here surface their buffered logs per subscription event."""
        from ..logbroker.broker import make_log_message
        from ..store.watch import ChannelClosed

        ch = self.log_broker.listen_subscriptions(self.node_id)
        active: set[str] = set()
        while not self._stop.is_set():
            try:
                msg = ch.get(timeout=0.2)
            except TimeoutError:
                continue
            except ChannelClosed:
                # broker restarted (leadership flap) or channel overflow:
                # re-listen, like the session reconnect loop does
                if self._stop.wait(timeout=0.2):
                    return
                ch = self.log_broker.listen_subscriptions(self.node_id)
                active.clear()
                continue
            if msg.close:
                active.discard(msg.id)
                continue
            if msg.id in active:
                continue
            active.add(msg.id)
            sub_id = msg.id

            def publish(task, stream, data, sub_id=sub_id):
                self.log_broker.publish_logs(
                    sub_id, [make_log_message(task, stream, data)]
                )

            try:
                self.worker.subscribe_logs(msg.selector, publish)
            except Exception:
                pass

    def stop(self):
        self._stop.set()
        self.worker.stop()
        for t in self._threads:
            t.join(timeout=2)

    def leave(self):
        if self.session_id is not None:
            try:
                self.dispatcher.leave(self.node_id, self.session_id)
            except Exception:
                pass
        self.stop()

    # ---------------------------------------------------------------- session
    def _run(self):
        backoff = BACKOFF_BASE
        while not self._stop.is_set():
            try:
                self._session()
                backoff = BACKOFF_BASE
            except Exception as e:
                if self._stop.is_set():
                    return
                log.debug("agent %s session error: %r; reconnecting in %.2fs",
                          self.node_id, e, backoff)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, BACKOFF_MAX)

    def _session(self):
        description = self.executor.describe()
        session_id = self.dispatcher.register(self.node_id, description)
        self.session_id = session_id
        period = self.dispatcher.heartbeat(self.node_id, session_id)

        hb_stop = threading.Event()

        def heartbeat_loop():
            while not (self._stop.is_set() or hb_stop.is_set()):
                if self._stop.wait(period / 2) or hb_stop.is_set():
                    return
                try:
                    self.dispatcher.heartbeat(self.node_id, session_id)
                except Exception:
                    return

        def report_loop():
            while not (self._stop.is_set() or hb_stop.is_set()):
                self._flush_statuses(session_id)
                if self._stop.wait(REPORT_INTERVAL):
                    return

        hb = threading.Thread(target=heartbeat_loop, daemon=True)
        rp = threading.Thread(target=report_loop, daemon=True)
        hb.start()
        rp.start()

        try:
            ch = self.dispatcher.assignments(self.node_id, session_id)
            while not self._stop.is_set():
                try:
                    msg = ch.get(timeout=0.2)
                except TimeoutError:
                    continue
                if msg.type == "complete":
                    self.worker.assign(msg.changes)
                else:
                    self.worker.update(msg.changes)
        except ChannelClosed:
            raise ConnectionError("assignment stream closed")
        finally:
            hb_stop.set()
            self._flush_statuses(session_id)

    # ------------------------------------------------------------- reporting
    def _enqueue_status(self, task_id: str, status: TaskStatus):
        with self._pending_lock:
            self._pending[task_id] = status

    def _flush_statuses(self, session_id: str):
        with self._pending_lock:
            if not self._pending:
                return
            updates = list(self._pending.items())
            self._pending.clear()
        try:
            self.dispatcher.update_task_status(self.node_id, session_id, updates)
        except Exception:
            # retry later (reference agent/reporter.go retry queue)
            with self._pending_lock:
                for tid, st in updates:
                    self._pending.setdefault(tid, st)
