"""Agent-side CSI volume staging/publishing.

Re-derivation of agent/csi/volumes.go:20-240: the worker receives volume
assignments (volumes published to this node); for each, the node plugin
stages then publishes the volume, with exponential-backoff retries; when an
assignment is removed, the volume is node-unpublished/unstaged and the
manager is told so the controller can detach (UpdateVolumeStatus →
confirm_node_unpublish).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..analysis.lockgraph import make_lock
from ..csi.plugin import PluginGetter
from ..utils.volumequeue import VolumeQueue


@dataclass
class VolumeAssignment:
    """api/objects.proto VolumeAssignment: what the dispatcher ships."""

    id: str  # volume object id
    volume_id: str  # plugin-scoped id from VolumeInfo
    driver: str
    volume_context: dict[str, str] = field(default_factory=dict)
    publish_context: dict[str, str] = field(default_factory=dict)
    availability: str = "active"


class NodeVolumeManager:
    """agent/csi/volumes.go volumes: staging state machine + retry queue."""

    def __init__(self, plugins: PluginGetter, on_unpublished=None, on_ready=None):
        self.plugins = plugins
        self.on_unpublished = on_unpublished  # callable(volume_obj_id)
        self.on_ready = on_ready  # callable(volume_obj_id): staged+published
        self._lock = make_lock('agent.csi.lock')
        self._assignments: dict[str, VolumeAssignment] = {}
        self._ready: set[str] = set()
        self._removing: dict[str, VolumeAssignment] = {}
        self.queue = VolumeQueue()
        self._attempts: dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self):
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="agent-csi", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        self.queue.stop()
        if self._thread:
            self._thread.join(timeout=5)

    # -- assignment intake (worker.reconcileVolumes) -----------------------

    def add(self, assignment: VolumeAssignment):
        with self._lock:
            self._assignments[assignment.id] = assignment
            self._removing.pop(assignment.id, None)
        self.queue.enqueue(assignment.id)

    def remove(self, item: "VolumeAssignment | str"):
        """Withdraw a volume. `item` may be the bare object id or a full
        VolumeAssignment (the dispatcher ships the latter for volumes
        pending node-unpublish, so a restarted agent with no local state
        can still run the idempotent unpublish and confirm upstream)."""
        vid = item if isinstance(item, str) else item.id
        with self._lock:
            a = self._assignments.pop(vid, None)
            if a is None and not isinstance(item, str):
                a = item  # no local state: use the shipped assignment
            if a is None:
                already_confirming = vid in self._removing
            else:
                self._removing[vid] = a
                already_confirming = False
        if a is None:
            # bare id and no state at all: nothing is mounted here (fresh
            # process, never staged) — confirm so the manager can advance
            # PENDING_NODE_UNPUBLISH → controller unpublish
            if not already_confirming and self.on_unpublished is not None:
                self.on_unpublished(vid)
            return
        self.queue.enqueue(vid)

    def reconcile(self, wanted_ids: set[str]):
        """Full-assignment reconcile (worker.go reconcileVolumes): anything
        held but absent from the complete set was withdrawn while we were
        disconnected and must be node-unpublished."""
        with self._lock:
            stale = [vid for vid in self._assignments if vid not in wanted_ids]
        for vid in stale:
            self.remove(vid)

    def is_ready(self, volume_obj_id: str) -> bool:
        """tasks gate on their volumes being staged (worker waitReady)."""
        with self._lock:
            return volume_obj_id in self._ready

    # -- worker loop -------------------------------------------------------

    def _run(self):
        while not self._stop.is_set():
            item = self.queue.wait(timeout=0.5)
            if item is None:
                continue
            vid, _ = item
            with self._lock:
                adding = self._assignments.get(vid)
                removing = self._removing.get(vid)
            try:
                if adding is not None:
                    plugin = self.plugins.get(adding.driver)
                    plugin.node_stage(adding)
                    plugin.node_publish(adding)
                    with self._lock:
                        self._ready.add(vid)
                    if self.on_ready is not None:
                        self.on_ready(vid)
                elif removing is not None:
                    plugin = self.plugins.get(removing.driver)
                    plugin.node_unpublish(removing)
                    plugin.node_unstage(removing)
                    with self._lock:
                        self._removing.pop(vid, None)
                        self._ready.discard(vid)
                    if self.on_unpublished is not None:
                        self.on_unpublished(vid)
                self._attempts.pop(vid, None)
            except Exception:
                attempt = self._attempts.get(vid, 0) + 1
                self._attempts[vid] = attempt
                self.queue.enqueue(vid, attempt=attempt)
