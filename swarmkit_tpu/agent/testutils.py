"""Fake executor/controller for tests (reference: agent/testutils/fakes.go
TestExecutor/TestController): tasks transition instantly; behavior knobs let
scenarios inject failures, slow starts, and long-running tasks."""
from __future__ import annotations

import threading
import time

from ..analysis.lockgraph import make_lock
from ..api.objects import Task
from ..api.specs import NodeDescription, Platform, Resources
from .exec import ExitStatus, FatalError


class FakeController:
    def __init__(self, task: Task, behavior: dict):
        self.task = task
        self.behavior = behavior
        self._exit = threading.Event()
        self._exit_status = ExitStatus(0, "")
        self.closed = False

    # behavior keys: fail_prepare, fail_start, run_forever, run_time, exit_code
    def update(self, task):
        self.task = task

    def prepare(self):
        if self.behavior.get("fail_prepare"):
            raise FatalError("prepare failed (injected)")
        # simulated executor work duration (test harness behavior knob,
        # not a retry loop)  # lint: allow(ad-hoc-sleep)
        time.sleep(self.behavior.get("prepare_time", 0))

    def start(self):
        if self.behavior.get("fail_start"):
            raise FatalError("start failed (injected)")

    def wait(self) -> ExitStatus:
        if self.behavior.get("run_forever"):
            # block until shutdown/terminate
            self._exit.wait()
            return self._exit_status
        run_time = self.behavior.get("run_time", 0)
        if run_time:
            if self._exit.wait(run_time):
                return self._exit_status
        code = self.behavior.get("exit_code", 0)
        return ExitStatus(code, f"exit {code}")

    def shutdown(self):
        self._exit_status = ExitStatus(0, "shutdown")
        self._exit.set()

    def terminate(self):
        self._exit_status = ExitStatus(137, "terminated")
        self._exit.set()

    def remove(self):
        pass

    def logs(self):
        """Buffered log lines for LogBroker tests; behavior key `logs` is a
        list of str/bytes (stdout) or (stream, bytes) tuples."""
        for entry in self.behavior.get("logs", []):
            if isinstance(entry, tuple):
                stream, data = entry
            else:
                stream, data = "stdout", entry
            if isinstance(data, str):
                data = data.encode()
            yield stream, data

    def close(self):
        self.closed = True
        self._exit.set()


class FakeExecutor:
    """Configurable fake. `behavior_for` maps service_id -> behavior dict."""

    def __init__(self, behavior_for: dict | None = None, hostname="fake-host"):
        # keep the caller's dict identity: tests mutate a shared (possibly
        # still empty) behaviors dict after construction
        self.behavior_for = behavior_for if behavior_for is not None else {}
        self.hostname = hostname
        self.controllers: list[FakeController] = []
        self._lock = make_lock('agent.testutils.lock')

    def describe(self) -> NodeDescription:
        return NodeDescription(
            hostname=self.hostname,
            platform=Platform(os="linux", architecture="amd64"),
            resources=Resources(nano_cpus=8 * 10**9, memory_bytes=16 * 2**30),
        )

    def configure(self, node):
        pass

    def controller(self, task: Task, dependencies=None) -> FakeController:
        behavior = self.behavior_for.get(
            task.service_id, self.behavior_for.get("*", {})
        )
        c = FakeController(task, dict(behavior))
        # the worker hands the task's restricted (and template-expanded)
        # secret/config maps here; tests observe delivered payloads
        c.dependencies = dependencies
        with self._lock:
            self.controllers.append(c)
        return c

    def set_network_bootstrap_keys(self, keys):
        pass
