"""Executor framework: the pluggable runtime boundary.

Behavioral re-derivation of agent/exec/{executor.go, controller.go}:
`Executor` describes the node and makes per-task `Controller`s; `do` maps one
controller step onto the task FSM — desired-state gating, fatal errors before
start → REJECTED, after start → FAILED, temporary errors retried, exit codes
captured (controller.go:142-345). Observed state is monotonic: `do` never
returns a lower state than the task already has (controller.go:163-166).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Protocol

from ..api.objects import Task, TaskStatus
from ..api.types import TaskState


class TemporaryError(Exception):
    """Transient failure: retry the same step."""


class FatalError(Exception):
    """Permanent failure: REJECTED before start, FAILED after."""


@dataclass
class ExitStatus:
    code: int = 0
    message: str = ""


class Controller(Protocol):
    """Per-task runtime driver (reference agent/exec/controller.go:16-47)."""

    def update(self, task: Task) -> None: ...
    def prepare(self) -> None: ...
    def start(self) -> None: ...
    def wait(self) -> ExitStatus: ...
    def shutdown(self) -> None: ...
    def terminate(self) -> None: ...
    def remove(self) -> None: ...
    def close(self) -> None: ...


class Executor(Protocol):
    """reference agent/exec/executor.go:10-121."""

    def describe(self): ...
    def configure(self, node) -> None: ...
    def controller(self, task: Task) -> Controller: ...
    def set_network_bootstrap_keys(self, keys) -> None: ...


def _status(task: Task, state: TaskState, message: str,
            err: str = "", exit_code: int | None = None) -> TaskStatus:
    s = TaskStatus(
        timestamp=time.time(),
        state=state,
        message=message,
        err=err,
        exit_code=exit_code,
    )
    # monotonic observed state (controller.go:163-166)
    if state < task.status.state:
        s.state = task.status.state
    return s


def do(task: Task, controller: Controller) -> TaskStatus:
    """Advance the task one FSM step. Returns the new status (which may equal
    the current one when the task is blocked on desired state)."""
    state = task.status.state
    desired = task.desired_state

    try:
        # teardown path wins over progress
        if desired >= TaskState.SHUTDOWN and state < TaskState.COMPLETE:
            if state >= TaskState.STARTING:
                controller.shutdown()
            return _status(task, TaskState.SHUTDOWN, "shutdown")

        if state == TaskState.ASSIGNED:
            controller.update(task)
            return _status(task, TaskState.ACCEPTED, "accepted")
        if state == TaskState.ACCEPTED:
            return _status(task, TaskState.PREPARING, "preparing")
        if state == TaskState.PREPARING:
            controller.prepare()
            return _status(task, TaskState.READY, "prepared")
        if state == TaskState.READY:
            # gate on desired: restart-delay holds tasks at READY
            if desired >= TaskState.RUNNING:
                return _status(task, TaskState.STARTING, "starting")
            return task.status
        if state == TaskState.STARTING:
            controller.start()
            return _status(task, TaskState.RUNNING, "started")
        if state == TaskState.RUNNING:
            exit_status = controller.wait()
            if exit_status.code == 0:
                return _status(task, TaskState.COMPLETE, "finished",
                               exit_code=0)
            return _status(task, TaskState.FAILED,
                           exit_status.message or "task failed",
                           err=f"exit code {exit_status.code}",
                           exit_code=exit_status.code)
        return task.status
    except TemporaryError as e:
        return _status(task, state, f"retrying: {e}", err=str(e))
    except FatalError as e:
        if state < TaskState.STARTING:
            return _status(task, TaskState.REJECTED, "rejected", err=str(e))
        return _status(task, TaskState.FAILED, "failed", err=str(e))
    except Exception as e:  # unexpected errors behave like fatal
        if state < TaskState.STARTING:
            return _status(task, TaskState.REJECTED, "rejected", err=repr(e))
        return _status(task, TaskState.FAILED, "failed", err=repr(e))
