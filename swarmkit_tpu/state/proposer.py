"""Proposer seam between the store and consensus
(reference: manager/state/proposer.go:17-31).

The store never talks to raft directly; it hands a changelist to a Proposer
and commits locally only when the proposer confirms. `LocalProposer` is the
no-consensus stand-in used by single-manager tests (the analogue of
manager/state/testutils/mock_proposer.go MockProposer).
"""
from __future__ import annotations

from typing import Callable, Protocol

from ..api.objects import Version


class Proposer(Protocol):
    def propose_value(self, actions,
                      commit_cb: Callable[..., None]) -> None:
        """Replicate `actions`; once committed, invoke
        commit_cb(version_index=<replicated index>) — the store stamps object
        versions from it so replicas agree. Must not return before commit_cb
        has run (raft.ProposeValue blocks on quorum)."""
        ...

    def get_version(self) -> Version:
        ...

    def changes_between(self, from_v: Version, to_v: Version) -> list:
        ...

    # Proposers may additionally offer `propose_async(actions, commit_cb)
    # -> handle` (handle.wait/result/done) — the non-blocking path the
    # store's pipelined Batch rides so depth-K transactions share one raft
    # group-commit flush. Callers feature-test with hasattr; the blocking
    # propose_value semantics above stay the contract.


class _CompletedProposal:
    """LocalProposer's propose_async handle: commit already happened."""

    done = True

    def wait(self, timeout=None) -> bool:
        return True

    def result(self, timeout=None) -> None:
        return None


class LocalProposer:
    """Versioning without consensus (MockProposer in the reference tests)."""

    def __init__(self):
        self._index = 0
        self._log: list[tuple[int, list]] = []

    def propose_value(self, actions, commit_cb: Callable[..., None]) -> None:
        self._index += 1
        self._log.append((self._index, list(actions)))
        commit_cb(version_index=self._index)

    def propose_async(self, actions, commit_cb: Callable[..., None]):
        self.propose_value(actions, commit_cb)
        return _CompletedProposal()

    def get_version(self) -> Version:
        return Version(self._index)

    def changes_between(self, from_v: Version, to_v: Version) -> list:
        return [
            actions for idx, actions in self._log
            if from_v.index < idx <= to_v.index
        ]
