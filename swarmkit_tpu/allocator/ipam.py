"""IPAM: subnet pools and address allocation for cluster networks.

Behavioral re-derivation of the reference's IPAM usage inside
manager/allocator/network.go:448-1132 (via libnetwork's default address
pools): every network gets a subnet (from its spec, or auto-assigned from
the default 10.0.0.0/8 space carved into /24s), a gateway (first host
address), and sequential host addresses for service VIPs, task attachment
addresses, and node attachments. State is rebuilt idempotently from the
replicated store on leadership change (`reserve` — the restore path of
doNetworkInit), so the allocator never double-assigns across failovers.
"""
from __future__ import annotations

import ipaddress
import threading
from ..analysis.lockgraph import make_lock


class IPAMError(Exception):
    pass


def validate_subnet(subnet: str) -> ipaddress.IPv4Network:
    """Parse and validate an operator-specified subnet. The single source
    of truth for the minimum size — the control API calls this at network
    create time so allocation can't later fail on a subnet the API
    accepted."""
    try:
        net = ipaddress.ip_network(subnet, strict=False)
    except ValueError as exc:
        raise IPAMError(f"invalid subnet {subnet!r}: {exc}")
    # gateway is network+1 and hosts start at network+2, so anything
    # smaller than /30 has no allocatable host address
    if net.num_addresses < 4:
        raise IPAMError(
            f"subnet {net} too small: need at least a /30 "
            "(gateway + one host address)")
    return net


class _Pool:
    # mirror-registry pair "ipam-pool" (analysis/mirror.py): allocate/
    # reserve/release shapes are pinned against _ArrayPool — a one-sided
    # edit fails tier-1 until both twins move (and the table re-records)
    def __init__(self, subnet: ipaddress.IPv4Network):
        self.subnet = subnet
        self.gateway = str(subnet.network_address + 1)
        self.allocated: set[str] = {self.gateway}
        self._cursor = 2  # host addresses start past the gateway

    def allocate(self) -> str:
        size = self.subnet.num_addresses
        offset = self._cursor
        # bounded probe: at most one full sweep of the host range — a
        # wrap-relative termination check can spin forever when the cursor
        # sits at the wrap target on an exhausted pool
        for _ in range(size):
            if offset >= size - 1:      # skip broadcast
                offset = 2
            addr = str(self.subnet.network_address + offset)
            if addr not in self.allocated:
                self.allocated.add(addr)
                self._cursor = offset + 1
                return addr
            offset += 1
        raise IPAMError(f"subnet {self.subnet} exhausted")

    def reserve(self, addr: str) -> None:
        if ipaddress.ip_address(addr) not in self.subnet:
            raise IPAMError(f"{addr} outside {self.subnet}")
        self.allocated.add(addr)

    def release(self, addr: str) -> None:
        if addr != self.gateway:
            self.allocated.discard(addr)


class IPAM:
    """Per-network address pools with auto subnet assignment."""

    DEFAULT_SPACE = ipaddress.ip_network("10.0.0.0/8")
    DEFAULT_PREFIX = 24
    # pool implementation seam: allocator/batched.py BatchedIPAM swaps
    # in the array-native pool (bit-identical semantics, fuzz-pinned)
    _POOL_CLS = _Pool

    def __init__(self):
        self._pools: dict[str, _Pool] = {}
        self._lock = make_lock('allocator.ipam.lock')

    # ------------------------------------------------------------ networks
    def add_network(self, net_id: str,
                    subnet: str | None = None) -> tuple[str, str]:
        """Create (or re-create, on restore) a network's pool. Returns
        (subnet_cidr, gateway)."""
        with self._lock:
            pool = self._pools.get(net_id)
            if pool is not None:
                return str(pool.subnet), pool.gateway
            if subnet:
                net = validate_subnet(subnet)
            else:
                net = self._next_free_subnet()
            pool = self._POOL_CLS(net)
            self._pools[net_id] = pool
            return str(net), pool.gateway

    def _next_free_subnet(self) -> ipaddress.IPv4Network:
        used = {p.subnet for p in self._pools.values()}
        for candidate in self.DEFAULT_SPACE.subnets(
                new_prefix=self.DEFAULT_PREFIX):
            if not any(candidate.overlaps(u) for u in used):
                return candidate
        raise IPAMError("default address space exhausted")

    def remove_network(self, net_id: str) -> None:
        with self._lock:
            self._pools.pop(net_id, None)

    def has_network(self, net_id: str) -> bool:
        with self._lock:
            return net_id in self._pools

    # ----------------------------------------------------------- addresses
    def allocate(self, net_id: str) -> str:
        with self._lock:
            pool = self._pools.get(net_id)
            if pool is None:
                raise IPAMError(f"unknown network {net_id}")
            return pool.allocate()

    def reserve(self, net_id: str, addr: str) -> None:
        """Restore path: mark an address from replicated state as taken."""
        with self._lock:
            pool = self._pools.get(net_id)
            if pool is not None:
                pool.reserve(addr)

    def release(self, net_id: str, addr: str) -> None:
        with self._lock:
            pool = self._pools.get(net_id)
            if pool is not None:
                pool.release(addr)
