"""Allocator: advances tasks NEW → PENDING once resources are allocated.

Behavioral re-derivation of manager/allocator/: the in-tree reference ships
an *inert* network provider (networkallocator/inert.go — the real CNM
allocator lives in moby) plus a real port allocator; likewise here the
network backend is a pluggable seam defaulting to an inert provider, while
service endpoints get published ports resolved (dynamic range 30000-32767,
reference portallocator.go) and every NEW task is moved to PENDING once its
service's networks/ports exist (doTaskAlloc, network.go:870).
"""
from __future__ import annotations

import threading

from ..api.objects import (
    EventCreate,
    EventDelete,
    EventUpdate,
    Network,
    Service,
    Task,
)
from ..api.types import TaskState
from ..store import by
from ..orchestrator.base import EventLoopComponent

DYNAMIC_PORT_START = 30000  # reference portallocator.go dynamic range
DYNAMIC_PORT_END = 32767


class InertNetworkProvider:
    """No-op network backend (reference networkallocator/inert.go:12-40)."""

    def allocate_network(self, network) -> dict:
        return {}

    def allocate_service(self, service) -> dict:
        return {}

    def allocate_task(self, task) -> list:
        return []

    def deallocate(self, obj) -> None:
        pass


class PortAllocator:
    """Published-port bookkeeping (reference manager/allocator/portallocator.go)."""

    def __init__(self):
        self._allocated: dict[tuple[str, int], str] = {}  # (proto, port) -> service
        self._next_dynamic = DYNAMIC_PORT_START
        self._lock = threading.Lock()

    def allocate(self, service_id: str, ports) -> bool:
        """Resolve published_port==0 to a dynamic port; refuse conflicts."""
        with self._lock:
            for p in ports:
                if p.published_port:
                    owner = self._allocated.get((p.protocol, p.published_port))
                    if owner is not None and owner != service_id:
                        return False
            for p in ports:
                if p.published_port:
                    self._allocated[(p.protocol, p.published_port)] = service_id
                elif p.publish_mode == "ingress":
                    port = self._find_dynamic(p.protocol)
                    if port is None:
                        return False
                    p.published_port = port
                    self._allocated[(p.protocol, port)] = service_id
            return True

    def _find_dynamic(self, protocol: str):
        start = self._next_dynamic
        port = start
        while True:
            if (protocol, port) not in self._allocated:
                self._next_dynamic = port + 1
                if self._next_dynamic > DYNAMIC_PORT_END:
                    self._next_dynamic = DYNAMIC_PORT_START
                return port
            port += 1
            if port > DYNAMIC_PORT_END:
                port = DYNAMIC_PORT_START
            if port == start:
                return None

    def release(self, service_id: str):
        with self._lock:
            for key in [k for k, v in self._allocated.items() if v == service_id]:
                del self._allocated[key]

    def release_except(self, service_id: str, keep: set[tuple[str, int]]) -> bool:
        """Release the service's ports not in `keep` (spec changed its port
        set). Returns True when anything was freed."""
        with self._lock:
            stale = [k for k, v in self._allocated.items()
                     if v == service_id and k not in keep]
            for k in stale:
                del self._allocated[k]
            return bool(stale)


class Allocator(EventLoopComponent):
    name = "allocator"

    def __init__(self, store, network_provider=None):
        super().__init__(store)
        self.network = network_provider or InertNetworkProvider()
        self.ports = PortAllocator()
        # services whose port allocation failed, retried when ports free up
        self._starved: set[str] = set()

    def setup(self, tx):
        return tx.find_tasks(by.ByTaskState(TaskState.NEW)), tx.find_services()

    def on_start(self, snapshot):
        tasks, services = snapshot
        for s in services:
            self._allocate_service(s.id)
        self._allocate_tasks([t.id for t in tasks])

    def handle(self, event):
        obj = getattr(event, "obj", None)
        if isinstance(event, (EventCreate, EventUpdate)):
            if isinstance(obj, Task) and obj.status.state == TaskState.NEW:
                self._allocate_tasks([obj.id])
            elif isinstance(obj, Service):
                self._allocate_service(obj.id)
            elif isinstance(obj, Network):
                self._allocate_network(obj.id)
        elif isinstance(event, EventDelete):
            if isinstance(obj, Service):
                self.ports.release(obj.id)
                self._retry_starved()
            elif isinstance(obj, Network):
                self.network.deallocate(obj)

    def _retry_starved(self):
        """A freed port may unblock a service whose allocation failed; its
        NEW tasks were waiting on the service endpoint."""
        starved, self._starved = self._starved, set()
        for service_id in starved:
            self._allocate_service(service_id)
        if starved:
            view = self.store.view()
            pending = [t.id for t in view.find_tasks(by.ByTaskState(TaskState.NEW))]
            if pending:
                self._allocate_tasks(pending)

    # ------------------------------------------------------------- allocation
    def _allocate_network(self, network_id: str):
        def cb(tx):
            n = tx.get_network(network_id)
            if n is None or n.driver_state is not None:
                return
            n = n.copy()
            n.driver_state = self.network.allocate_network(n) or {"inert": True}
            tx.update(n)

        self.store.update(cb)

    def _allocate_service(self, service_id: str):
        freed = False

        def cb(tx):
            nonlocal freed
            s = tx.get_service(service_id)
            if s is None:
                return
            ports = s.spec.endpoint.ports
            if not ports:
                # spec dropped all ports: free whatever was held and clear
                # the endpoint so a later re-add re-claims from scratch
                freed = self.ports.release_except(service_id, set())
                if s.endpoint is not None and s.endpoint.get("ports_allocated"):
                    s = s.copy()
                    s.endpoint = None
                    tx.update(s)
                return
            if s.endpoint is not None and s.endpoint.get("ports_allocated"):
                # re-allocate only when the spec's port set changed
                current = {(p.protocol, p.target_port, p.published_port,
                            p.publish_mode) for p in ports}
                if s.endpoint.get("port_set") == sorted(current):
                    return
            s = s.copy()
            # free ports the new spec no longer publishes before claiming
            wanted = {(p.protocol, p.published_port)
                      for p in ports if p.published_port}
            freed = self.ports.release_except(s.id, wanted)
            ok = self.ports.allocate(s.id, s.spec.endpoint.ports)
            if not ok:
                self._starved.add(s.id)
                return  # retried when a conflicting service releases ports
            s.endpoint = {
                "ports_allocated": True,
                "port_set": sorted({(p.protocol, p.target_port,
                                     p.published_port, p.publish_mode)
                                    for p in s.spec.endpoint.ports}),
                "ports": [
                    (p.protocol, p.target_port, p.published_port, p.publish_mode)
                    for p in s.spec.endpoint.ports
                ],
            }
            tx.update(s)

        self.store.update(cb)
        if freed:
            self._retry_starved()

    def _allocate_tasks(self, task_ids: list[str]):
        def cb(batch):
            for tid in task_ids:
                def move_one(tx, tid=tid):
                    t = tx.get_task(tid)
                    if t is None or t.status.state != TaskState.NEW:
                        return
                    service = tx.get_service(t.service_id) if t.service_id else None
                    if service is not None and service.spec.endpoint.ports and (
                            service.endpoint is None
                            or not service.endpoint.get("ports_allocated")):
                        return  # wait for service allocation first
                    t = t.copy()
                    t.networks = self.network.allocate_task(t)
                    if service is not None and service.endpoint:
                        from ..api.specs import EndpointSpec, PortConfig
                        t.endpoint = EndpointSpec(ports=[
                            PortConfig(protocol=proto, target_port=tp,
                                       published_port=pub, publish_mode=mode)
                            for proto, tp, pub, mode in service.endpoint["ports"]
                        ])
                    t.status.state = TaskState.PENDING
                    t.status.message = "pending task scheduling"
                    tx.update(t)

                batch.update(move_one)

        self.store.batch(cb)
