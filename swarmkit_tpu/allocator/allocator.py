"""Allocator: advances tasks NEW → PENDING once resources are allocated.

Behavioral re-derivation of manager/allocator/: the in-tree reference ships
an *inert* network provider (networkallocator/inert.go — the real CNM
allocator lives in moby) plus a real port allocator; likewise here the
network backend is a pluggable seam defaulting to an inert provider, while
the address plane is real (allocator/ipam.py): networks get subnets and
gateways (doNetworkInit), services get per-network virtual IPs
(network.go allocateVIP), tasks get attachment addresses (doTaskAlloc,
network.go:870), nodes get ingress attachments (allocateNodes,
network.go:448), and published ports resolve through the dynamic range
30000-32767 (portallocator.go). All allocation state is rebuilt
idempotently from the replicated store on leadership change.
"""
from __future__ import annotations

import logging
import os
import threading

from ..analysis.lockgraph import make_lock
from ..api.objects import (
    EventCreate,
    EventDelete,
    EventUpdate,
    Network,
    Node,
    Service,
    Task,
)
from ..api.types import NodeStatusState, TaskState
from ..store import by
from ..store.memory import MAX_CHANGES_PER_TRANSACTION
from ..orchestrator.base import EventLoopComponent
from ..utils import failpoints, lifecycle
from .ipam import IPAM, IPAMError

log = logging.getLogger("swarmkit_tpu.allocator")

DYNAMIC_PORT_START = 30000  # reference portallocator.go dynamic range
DYNAMIC_PORT_END = 32767


class InertNetworkProvider:
    """No-op network backend (reference networkallocator/inert.go:12-40)."""

    def allocate_network(self, network) -> dict:
        return {}

    def allocate_service(self, service) -> dict:
        return {}

    def allocate_task(self, task) -> list:
        return []

    def deallocate(self, obj) -> None:
        pass


class PortAllocator:
    """Published-port bookkeeping (reference manager/allocator/portallocator.go).

    Mirror-registry pair "port-alloc" (analysis/mirror.py): the
    owner-precheck / dynamic-run / partial-failure shapes are pinned
    against BatchedPorts — land edits in both twins."""

    def __init__(self):
        self._allocated: dict[tuple[str, int], str] = {}  # (proto, port) -> service
        self._next_dynamic = DYNAMIC_PORT_START
        self._lock = make_lock('allocator.allocator.lock')

    def allocate(self, service_id: str, ports) -> bool:
        """Resolve published_port==0 to a dynamic port; refuse conflicts."""
        with self._lock:
            for p in ports:
                if p.published_port:
                    owner = self._allocated.get((p.protocol, p.published_port))
                    if owner is not None and owner != service_id:
                        return False
            for p in ports:
                if p.published_port:
                    self._allocated[(p.protocol, p.published_port)] = service_id
                elif p.publish_mode == "ingress":
                    port = self._find_dynamic(p.protocol)
                    if port is None:
                        return False
                    p.published_port = port
                    self._allocated[(p.protocol, port)] = service_id
            return True

    def _find_dynamic(self, protocol: str):
        start = self._next_dynamic
        port = start
        while True:
            if (protocol, port) not in self._allocated:
                self._next_dynamic = port + 1
                if self._next_dynamic > DYNAMIC_PORT_END:
                    self._next_dynamic = DYNAMIC_PORT_START
                return port
            port += 1
            if port > DYNAMIC_PORT_END:
                port = DYNAMIC_PORT_START
            if port == start:
                return None

    def release(self, service_id: str):
        with self._lock:
            for key in [k for k, v in self._allocated.items() if v == service_id]:
                del self._allocated[key]

    def release_except(self, service_id: str, keep: set[tuple[str, int]]) -> bool:
        """Release the service's ports not in `keep` (spec changed its port
        set). Returns True when anything was freed."""
        with self._lock:
            stale = [k for k, v in self._allocated.items()
                     if v == service_id and k not in keep]
            for k in stale:
                del self._allocated[k]
            return bool(stale)


class Allocator(EventLoopComponent):
    name = "allocator"

    def __init__(self, store, network_provider=None, batched=None):
        """batched=True (the default; SWARMKIT_TPU_NO_BATCHED_ALLOC=1 or
        batched=False reverts) swaps the scalar IPAM/PortAllocator for
        the array-native twins (allocator/batched.py) and moves whole
        PENDING batches through per-network bulk grants — bit-identical
        to the scalar oracle (tests/test_batched_alloc.py fuzz)."""
        super().__init__(store)
        self.network = network_provider or InertNetworkProvider()
        if batched is None:
            batched = not os.environ.get("SWARMKIT_TPU_NO_BATCHED_ALLOC")
        self.batched = bool(batched)
        if self.batched:
            from .batched import BatchedIPAM, BatchedPorts

            self.ports = BatchedPorts()
            self.ipam = BatchedIPAM()
        else:
            self.ports = PortAllocator()
            self.ipam = IPAM()
        # services whose port allocation failed, retried when ports free up
        self._starved: set[str] = set()
        # tasks whose attachment addresses were already returned — terminal
        # tasks keep getting status updates, and a double release could free
        # an address the pool re-assigned in the meantime
        self._released_tasks: set[str] = set()
        # services whose VIP allocation hit an exhausted pool; retried when
        # any address is released (ports have the same mechanism above)
        self._vip_starved: set[str] = set()
        # services whose VIP/attachment wants were DEFERRED because a
        # referenced network isn't allocated yet (ISSUE 11 satellite):
        # an explicit marker set in the dispatcher reverse-index-as-hint
        # style — every hit is re-checked in-tx by _allocate_service, a
        # stale id heals lazily, and the find_services sweep remains the
        # un-primed fallback (primed by on_start's full pass)
        self._deferred_services: set[str] = set()
        self._deferred_primed = False

    def setup(self, tx):
        # ONE consistent snapshot: the NEW subset derives from the full task
        # list instead of a second, later view racing the first
        return (tx.find_tasks(), tx.find_services(), tx.find_networks(),
                tx.find_nodes())

    def on_start(self, snapshot):
        all_tasks, services, networks, nodes = snapshot
        tasks = [t for t in all_tasks if t.status.state == TaskState.NEW]
        # ---- idempotent state rebuild (doNetworkInit restore path) -------
        for n in networks:
            state = n.driver_state or {}
            if isinstance(state, dict) and state.get("subnet"):
                try:
                    self.ipam.add_network(n.id, state["subnet"])
                except (IPAMError, ValueError):
                    # a bad persisted subnet (a /32 accepted before the size
                    # check existed, or corrupted state) must not abort the
                    # whole rebuild — every later pool/VIP/attachment
                    # reservation would be skipped and a fresh leader would
                    # double-assign
                    log.warning("skipping unusable persisted subnet %s for "
                                "network %s", state["subnet"], n.id)
        def reserve(net_id, addr):
            # same tolerance as the pool loop above: one corrupted persisted
            # address (outside its subnet, or garbage) must not abort the
            # remaining reservations
            try:
                self.ipam.reserve(net_id, addr)
            except (IPAMError, ValueError):
                log.warning("skipping unusable persisted address %s on "
                            "network %s", addr, net_id)

        for s in services:
            if s.endpoint:
                for net_id, addr in s.endpoint.get("virtual_ips", []):
                    reserve(net_id, addr)
        for t in all_tasks:
            for att in t.networks or []:
                if isinstance(att, dict) and att.get("network_id"):
                    for addr in att.get("addresses", []):
                        reserve(att["network_id"], addr)
        for node in nodes:
            for att in node.attachments or []:
                if isinstance(att, dict) and att.get("network_id"):
                    for addr in att.get("addresses", []):
                        reserve(att["network_id"], addr)

        for n in networks:
            self._allocate_network(n.id)
        for s in services:
            self._allocate_service(s.id)
        # the full sweep above marked every service with unresolved
        # network refs: the deferred set is primed from here on
        self._deferred_primed = True
        for node in nodes:
            self._allocate_node(node.id)
        self._allocate_tasks([t.id for t in tasks])

    def handle(self, event):
        obj = getattr(event, "obj", None)
        if isinstance(event, (EventCreate, EventUpdate)):
            if isinstance(obj, Task):
                if obj.status.state == TaskState.NEW:
                    self._allocate_tasks([obj.id])
                elif obj.status.state >= TaskState.COMPLETE:
                    # dead task: its attachment addresses return to the pool
                    # (network.go doTaskAlloc handles task death the same way)
                    self._release_task_attachments(obj)
            elif isinstance(obj, Service):
                self._allocate_service(obj.id)
            elif isinstance(obj, Network):
                self._allocate_network(obj.id)
                # services created BEFORE their referenced network deferred
                # their VIPs; a fresh network unblocks them (and their tasks)
                self._retry_all_services()
                self._retry_waiting_tasks()
            elif isinstance(obj, Node):
                self._allocate_node(obj.id)
        elif isinstance(event, EventDelete):
            if isinstance(obj, Service):
                self.ports.release(obj.id)
                if obj.endpoint:
                    for net_id, addr in obj.endpoint.get("virtual_ips", []):
                        self.ipam.release(net_id, addr)
                self._retry_after_free()
            elif isinstance(obj, Network):
                self.network.deallocate(obj)
                self.ipam.remove_network(obj.id)
            elif isinstance(obj, Task):
                self._release_task_attachments(obj, deleted=True)
                self._released_tasks.discard(obj.id)
            elif isinstance(obj, Node):
                freed = False
                for att in obj.attachments or []:
                    if isinstance(att, dict):
                        for addr in att.get("addresses", []):
                            self.ipam.release(att["network_id"], addr)
                            freed = True
                if freed:
                    self._retry_after_free()

    def _release_task_attachments(self, task: Task, deleted: bool = False):
        """Return a dead task's addresses AND persist the release by
        clearing task.networks in the store — otherwise a later leader would
        rebuild its pools with (or re-release) addresses long since
        recycled. The in-memory guard only dedups same-leader event bursts.
        """
        if task.id in self._released_tasks:
            return
        self._released_tasks.add(task.id)
        released = False
        if not deleted:
            def clear(tx):
                nonlocal released
                cur = tx.get_task(task.id)
                if cur is None or not cur.networks:
                    return
                for att in cur.networks:
                    if isinstance(att, dict):
                        for addr in att.get("addresses", []):
                            self.ipam.release(att["network_id"], addr)
                released = True
                cur = cur.copy()
                cur.networks = []
                tx.update(cur)

            try:
                self.store.update(clear)
            except Exception:
                self._released_tasks.discard(task.id)  # retried next event
                return
        else:
            for att in task.networks or []:
                if isinstance(att, dict):
                    for addr in att.get("addresses", []):
                        self.ipam.release(att["network_id"], addr)
                        released = True
        if released:
            self._retry_after_free()

    def _retry_starved(self):
        """A freed port may unblock a service whose allocation failed; its
        NEW tasks were waiting on the service endpoint."""
        starved, self._starved = self._starved, set()
        for service_id in starved:
            self._allocate_service(service_id)
        if starved:
            self._retry_waiting_tasks()

    def _retry_waiting_tasks(self):
        view = self.store.view()
        pending = [t.id for t in view.find_tasks(by.ByTaskState(TaskState.NEW))]
        if pending:
            self._allocate_tasks(pending)

    def _retry_vip_starved(self):
        starved, self._vip_starved = self._vip_starved, set()
        for service_id in starved:
            self._allocate_service(service_id)

    def _retry_after_free(self):
        """Any released address/port may unblock anything that failed to
        allocate: port-starved services, VIP-starved services, and NEW
        tasks stuck on an exhausted pool."""
        self._retry_starved()
        self._retry_vip_starved()
        self._retry_waiting_tasks()

    def _retry_all_services(self):
        """A new network may complete services whose VIP allocation was
        DEFERRED (created before the network). Deferred services carry
        an explicit marker (`_deferred_services`, written wherever
        `_service_networks` returns None), so a network commit retries
        O(deferred), not O(services) — each hit re-checked in-tx by the
        idempotent _allocate_service (a still-unresolved service
        re-marks itself; a deleted one heals out of the set). Before
        on_start's full sweep primes the set, fall back to the
        find_services scan."""
        if not self._deferred_primed:
            view = self.store.view()
            for s in view.find_services():
                self._allocate_service(s.id)
            return
        deferred, self._deferred_services = self._deferred_services, set()
        pending = list(deferred)
        try:
            while pending:
                self._allocate_service(pending[-1])
                pending.pop()          # only a completed retry leaves
        except BaseException:
            # a transient failure (store churn) must not lose the
            # un-retried remainder — the old full sweep self-healed on
            # the next network event, so must the marker set
            self._deferred_services.update(pending)
            raise

    # -------------------------------------------------------- net resolution
    def _resolve_network(self, tx, target: str):
        """A NetworkAttachmentConfig.target is an id or a name."""
        n = tx.get_network(target)
        if n is not None:
            return n
        for n in tx.find_networks():
            if n.spec.annotations.name == target:
                return n
        return None

    def _service_networks(self, tx, service) -> list | None:
        """The networks a service's tasks attach to: explicit refs plus the
        ingress network when it publishes ingress-mode ports
        (network.go:448-1132). None == a referenced network is missing or
        not yet allocated (callers defer)."""
        nets = []
        for ref in service.spec.task.networks:
            n = self._resolve_network(tx, ref.target)
            if n is None or not self.ipam.has_network(n.id):
                return None
            nets.append(n)
        ports = service.spec.endpoint.ports
        if any(p.publish_mode == "ingress" for p in ports):
            for n in tx.find_networks():
                if n.spec.ingress:
                    if not self.ipam.has_network(n.id):
                        return None
                    if n.id not in [x.id for x in nets]:
                        nets.append(n)
                    break
        return nets

    # ------------------------------------------------------------- allocation
    def _allocate_network(self, network_id: str):
        def cb(tx):
            n = tx.get_network(network_id)
            if n is None:
                return
            state = n.driver_state if isinstance(n.driver_state, dict) else None
            if state is not None and state.get("subnet"):
                try:
                    self.ipam.add_network(n.id, state["subnet"])  # idempotent
                except (IPAMError, ValueError) as exc:
                    log.warning("network %s: unusable persisted subnet %s: "
                                "%s", network_id, state["subnet"], exc)
                return
            n = n.copy()
            wanted = (n.spec.ipam or {}).get("subnet") if n.spec.ipam else None
            try:
                subnet, gateway = self.ipam.add_network(n.id, wanted)
            except (IPAMError, ValueError) as exc:
                log.warning("network %s: subnet allocation failed: %s",
                            network_id, exc)
                return
            state = self.network.allocate_network(n) or {}
            state.update({"subnet": subnet, "gateway": gateway})
            n.driver_state = state
            tx.update(n)

        self.store.update(cb)

    def _allocate_node(self, node_id: str):
        """Ingress attachment for READY nodes (network.go allocateNodes —
        every node carrying ingress-published tasks needs an address on the
        ingress network)."""
        def cb(tx):
            node = tx.get_node(node_id)
            if node is None or node.status.state != NodeStatusState.READY:
                return
            ingress = next(
                (n for n in tx.find_networks() if n.spec.ingress), None)
            if ingress is None or not self.ipam.has_network(ingress.id):
                return
            existing = [a for a in (node.attachments or [])
                        if isinstance(a, dict)
                        and a.get("network_id") == ingress.id]
            if existing:
                return
            try:
                addr = self.ipam.allocate(ingress.id)
            except IPAMError:
                return
            node = node.copy()
            node.attachments = list(node.attachments or []) + [
                {"network_id": ingress.id, "addresses": [addr]}]
            tx.update(node)

        self.store.update(cb)

    def _allocate_service(self, service_id: str):
        freed = False

        def cb(tx):
            nonlocal freed
            s = tx.get_service(service_id)
            if s is None:
                return
            ports = s.spec.endpoint.ports
            nets = self._service_networks(tx, s)
            if nets is None:
                # referenced network not allocated yet: mark so the
                # network-commit retry is O(deferred) (_retry_all_services)
                self._deferred_services.add(s.id)
            endpoint = dict(s.endpoint or {})
            have_vips = {net_id: addr
                         for net_id, addr in endpoint.get("virtual_ips", [])}
            dirty = False

            # ---- virtual IPs: one per attached network (allocateVIP) -----
            # nets is None == a referenced network isn't allocated yet:
            # DEFER — releasing existing VIPs on that sentinel would hand
            # live addresses back to the pool mid-flight
            if nets is not None:
                if s.spec.endpoint.mode == "vip" and not s.pending_delete:
                    want_vips = [n.id for n in nets]
                    for net_id in want_vips:
                        if net_id not in have_vips:
                            try:
                                have_vips[net_id] = self.ipam.allocate(net_id)
                                dirty = True
                            except IPAMError:
                                self._vip_starved.add(s.id)
                else:
                    # dnsrr (or teardown): no VIPs are wanted — release any
                    # held ones, the reference deallocates on mode flips
                    want_vips = []
                for net_id in [k for k in have_vips if k not in want_vips]:
                    self.ipam.release(net_id, have_vips.pop(net_id))
                    dirty = True

            if not ports:
                # spec dropped all ports: free whatever was held and drop
                # the port fields so a later re-add re-claims from scratch
                freed = self.ports.release_except(service_id, set())
                if endpoint.get("ports_allocated") or dirty:
                    s = s.copy()
                    endpoint.pop("ports_allocated", None)
                    endpoint.pop("port_set", None)
                    endpoint.pop("ports", None)
                    endpoint["virtual_ips"] = sorted(have_vips.items())
                    s.endpoint = endpoint or None
                    tx.update(s)
                return
            if endpoint.get("ports_allocated"):
                # re-allocate only when the spec's port set changed
                current = {(p.protocol, p.target_port, p.published_port,
                            p.publish_mode) for p in ports}
                if endpoint.get("port_set") == sorted(current):
                    if dirty:
                        s = s.copy()
                        endpoint["virtual_ips"] = sorted(have_vips.items())
                        s.endpoint = endpoint
                        tx.update(s)
                    return
            s = s.copy()
            # free ports the new spec no longer publishes before claiming
            wanted = {(p.protocol, p.published_port)
                      for p in ports if p.published_port}
            freed = self.ports.release_except(s.id, wanted)
            ok = self.ports.allocate(s.id, s.spec.endpoint.ports)
            if not ok:
                self._starved.add(s.id)
                if dirty:
                    # VIP pool state already changed above — persist it even
                    # though ports are starved, or the endpoint would go on
                    # listing addresses the pool has re-handed out
                    endpoint["virtual_ips"] = sorted(have_vips.items())
                    s.endpoint = endpoint
                    tx.update(s)
                return  # retried when a conflicting service releases ports
            endpoint.update({
                "ports_allocated": True,
                "port_set": sorted({(p.protocol, p.target_port,
                                     p.published_port, p.publish_mode)
                                    for p in s.spec.endpoint.ports}),
                "ports": [
                    (p.protocol, p.target_port, p.published_port, p.publish_mode)
                    for p in s.spec.endpoint.ports
                ],
                "virtual_ips": sorted(have_vips.items()),
            })
            s.endpoint = endpoint
            tx.update(s)

        self.store.update(cb)
        if freed:
            self._retry_starved()

    def _allocate_tasks(self, task_ids: list[str]):
        if self.batched and len(task_ids) > 1 \
                and hasattr(self.ipam, "allocate_many"):
            return self._allocate_tasks_batched(task_ids)
        # lifecycle plane: collect the ids actually moved NEW->PENDING
        # and file them as ONE batched record after the store batch (the
        # decision boundary); disarmed, no list is ever built
        moved: list[str] | None = [] if lifecycle.enabled() else None

        def cb(batch):
            for tid in task_ids:
                def move_one(tx, tid=tid):
                    t = tx.get_task(tid)
                    if t is None or t.status.state != TaskState.NEW:
                        return
                    service = tx.get_service(t.service_id) if t.service_id else None
                    if service is not None and service.spec.endpoint.ports and (
                            service.endpoint is None
                            or not service.endpoint.get("ports_allocated")):
                        return  # wait for service allocation first
                    # attachment addresses: explicit refs + ingress
                    attachments = []
                    if service is not None:
                        nets = self._service_networks(tx, service)
                        if nets is None:
                            self._deferred_services.add(service.id)
                            return  # a referenced network isn't ready yet
                        for n in nets:
                            try:
                                attachments.append({
                                    "network_id": n.id,
                                    "addresses": [self.ipam.allocate(n.id)],
                                })
                            except IPAMError:
                                for a in attachments:
                                    self.ipam.release(a["network_id"],
                                                      a["addresses"][0])
                                return  # pool exhausted: stays NEW
                    t = t.copy()
                    t.networks = (self.network.allocate_task(t) or []) \
                        + attachments
                    if service is not None and service.endpoint \
                            and service.endpoint.get("ports"):
                        from ..api.specs import EndpointSpec, PortConfig
                        t.endpoint = EndpointSpec(ports=[
                            PortConfig(protocol=proto, target_port=tp,
                                       published_port=pub, publish_mode=mode)
                            for proto, tp, pub, mode in service.endpoint["ports"]
                        ])
                    t.status.state = TaskState.PENDING
                    t.status.message = "pending task scheduling"
                    tx.update(t)
                    if moved is not None:
                        moved.append(tid)

                batch.update(move_one)

        self.store.batch(cb)
        if moved:
            lifecycle.record_batch(TaskState.PENDING, moved)

    # ------------------------------------------------ batched PENDING path
    def _allocate_tasks_batched(self, task_ids: list[str]):
        """The allocator's hot half over whole batches (ISSUE 11): per
        chunk, ONE in-tx validation pass plans the batch, per-network
        demand grants ride one `allocate_many` mask/scan kernel call
        each, and the tasks commit in one update transaction. When a
        pool can't cover its chunk demand the chunk falls back to the
        per-task probe loop — bit-identical to the scalar path,
        including its cursor churn on failed tasks. A chunk that crashes
        mid-flight (failpoint `alloc.batch.commit`, store errors)
        releases every uncommitted grant before re-raising, so a retry
        can't leak addresses."""
        moved: list[str] | None = [] if lifecycle.enabled() else None
        for off in range(0, len(task_ids), MAX_CHANGES_PER_TRANSACTION):
            chunk = task_ids[off:off + MAX_CHANGES_PER_TRANSACTION]
            granted: list[tuple[str, str]] = []
            try:
                self.store.update(
                    lambda tx, chunk=chunk: self._alloc_chunk_in_tx(
                        tx, chunk, granted, moved))
            except BaseException:
                # the transaction never committed: hand every grant of
                # this chunk back (release is an idempotent discard, so
                # per-task rollbacks already performed are harmless)
                for net_id, addr in granted:
                    self.ipam.release(net_id, addr)
                raise
        if moved:
            lifecycle.record_batch(TaskState.PENDING, moved)

    def _alloc_chunk_in_tx(self, tx, chunk, granted, moved):
        # pass 1: in-tx validation (same gates as the scalar move_one)
        # and per-network demand aggregation
        plans = []
        demand: dict[str, int] = {}
        for tid in chunk:
            t = tx.get_task(tid)
            if t is None or t.status.state != TaskState.NEW:
                continue
            service = tx.get_service(t.service_id) if t.service_id else None
            if service is not None and service.spec.endpoint.ports and (
                    service.endpoint is None
                    or not service.endpoint.get("ports_allocated")):
                continue  # wait for service allocation first
            nets = []
            if service is not None:
                nets = self._service_networks(tx, service)
                if nets is None:
                    self._deferred_services.add(service.id)
                    continue  # a referenced network isn't ready yet
                for n in nets:
                    demand[n.id] = demand.get(n.id, 0) + 1
            plans.append((t, service, nets))
        # pass 2: bulk grants when every pool covers its chunk demand —
        # K grants with no interleaved release == K sequential scalar
        # grants (ops/alloc.py), so the fallback below is the ONLY other
        # shape and both are oracle-identical
        bulk: dict[str, list[str]] | None = None
        if demand and all(self.ipam.free_count(nid) >= k
                          for nid, k in demand.items()):
            bulk = {}
            for nid, k in demand.items():
                addrs = self.ipam.allocate_many(nid, k)
                granted.extend((nid, a) for a in addrs)
                bulk[nid] = addrs[::-1]  # pop() consumes in grant order
        failpoints.fp("alloc.batch.commit")
        # pass 3: distribute in task order and stage the store writes
        for t, service, nets in plans:
            attachments = []
            if bulk is not None:
                for n in nets:
                    attachments.append({"network_id": n.id,
                                        "addresses": [bulk[n.id].pop()]})
            else:
                exhausted = False
                for n in nets:
                    try:
                        addr = self.ipam.allocate(n.id)
                    except IPAMError:
                        # pool exhausted: this task's partial grants go
                        # back, the task stays NEW (scalar semantics —
                        # the failed probes' cursor churn included)
                        for a in attachments:
                            self.ipam.release(a["network_id"],
                                              a["addresses"][0])
                        exhausted = True
                        break
                    granted.append((n.id, addr))
                    attachments.append({"network_id": n.id,
                                        "addresses": [addr]})
                if exhausted:
                    continue
            t = t.copy()
            t.networks = (self.network.allocate_task(t) or []) + attachments
            if service is not None and service.endpoint \
                    and service.endpoint.get("ports"):
                from ..api.specs import EndpointSpec, PortConfig
                t.endpoint = EndpointSpec(ports=[
                    PortConfig(protocol=proto, target_port=tp,
                               published_port=pub, publish_mode=mode)
                    for proto, tp, pub, mode in service.endpoint["ports"]
                ])
            t.status.state = TaskState.PENDING
            t.status.message = "pending task scheduling"
            tx.update(t)
            if moved is not None:
                moved.append(t.id)
