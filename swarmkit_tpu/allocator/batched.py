"""Batched allocator subsystem (ISSUE 11): array-native IPAM pools and
port bookkeeping, bit-identical to the scalar oracles.

`BatchedIPAM` / `BatchedPorts` are drop-in replacements for `IPAM` /
`PortAllocator` (the scalar classes stay as the CPU oracles — the
seeded fuzz in tests/test_batched_alloc.py pins grants, release order,
cursor state and exhaustion behavior across every public op). The
allocator's hot half — moving whole PENDING batches — grants addresses
through `allocate_many`: one `ops/alloc.py` mask/scan kernel call per
(network, chunk) instead of one probe loop per task, legal because a
batch of K grants with no interleaved release IS K sequential scalar
grants (ops/alloc.py module docs).

Parity rules this module must preserve:
- grant order: the circular probe order starting at the pool cursor,
  cursor left just past the last grant;
- partial failure: a dynamic-port run that exhausts mid-way applies
  exactly the grants the scalar loop would have applied before failing;
- release never moves the cursor, the gateway is never released.
"""
from __future__ import annotations

import ipaddress

import numpy as np

from ..ops import alloc as _alloc
from .allocator import (
    DYNAMIC_PORT_END,
    DYNAMIC_PORT_START,
    PortAllocator,
)
from .ipam import IPAM, IPAMError

_PORT_SPAN = DYNAMIC_PORT_END - DYNAMIC_PORT_START + 1


class _ArrayPool:
    """Array twin of ipam._Pool: occupancy as a flat bool mask, grants
    via the shared circular-order kernel. Mirror-registry pair
    "ipam-pool" (analysis/mirror.py) pins the method shapes against the
    scalar oracle; the fuzz pins the values."""

    def __init__(self, subnet: ipaddress.IPv4Network):
        self.subnet = subnet
        self.gateway = str(subnet.network_address + 1)
        size = subnet.num_addresses
        self.taken = np.zeros(size, bool)
        self.taken[1] = True            # the gateway
        self._cursor = 2

    # -- oracle-parity surface (ipam._Pool) ------------------------------
    def allocate(self) -> str:
        """Single grant: the scalar pool's incremental probe, verbatim,
        over the mask — O(probe distance), not a whole-pool order
        computation (single grants are the service-VIP / node-ingress /
        fallback shape; the kernel earns its keep on k > 1)."""
        size = self.taken.shape[0]
        taken = self.taken
        offset = self._cursor
        for _ in range(size):
            if offset >= size - 1:      # skip broadcast (scalar wrap)
                offset = 2
            if not taken[offset]:
                taken[offset] = True
                self._cursor = offset + 1
                return str(self.subnet.network_address + offset)
            offset += 1
        raise IPAMError(f"subnet {self.subnet} exhausted")

    def allocate_many(self, k: int) -> list[str]:
        """K grants in probe order — all-or-nothing (callers that need
        the scalar loop's grant-then-raise shape fall back to k
        `allocate()` calls, which are bit-identical per grant)."""
        if k <= 0:
            return []
        size = self.taken.shape[0]
        order = _alloc.grant_order(self.taken, self._cursor, 2, size - 2)
        if k > order.shape[0]:
            raise IPAMError(f"subnet {self.subnet} exhausted")
        offs = order[:k]
        self.taken[offs] = True
        self._cursor = int(offs[-1]) + 1
        base = self.subnet.network_address
        return [str(base + int(o)) for o in offs]

    def free_count(self) -> int:
        size = self.taken.shape[0]
        return int((~self.taken[2:size - 1]).sum())

    def reserve(self, addr: str) -> None:
        ip = ipaddress.ip_address(addr)
        if ip not in self.subnet:
            raise IPAMError(f"{addr} outside {self.subnet}")
        self.taken[int(ip) - int(self.subnet.network_address)] = True

    def release(self, addr: str) -> None:
        if addr == self.gateway:
            return
        try:
            off = int(ipaddress.ip_address(addr)) \
                - int(self.subnet.network_address)
        except ValueError:
            return                      # scalar discard() tolerance
        if 0 <= off < self.taken.shape[0] and off != 1:
            self.taken[off] = False

    @property
    def allocated(self) -> set[str]:
        """Parity view of the scalar pool's `allocated` set (consumers
        and the fuzz read it; the mask is the storage)."""
        base = self.subnet.network_address
        return {str(base + int(o)) for o in np.flatnonzero(self.taken)}


class BatchedIPAM(IPAM):
    """IPAM over array pools, plus the whole-batch grant surface."""

    _POOL_CLS = _ArrayPool

    def allocate_many(self, net_id: str, k: int) -> list[str]:
        with self._lock:
            pool = self._pools.get(net_id)
            if pool is None:
                raise IPAMError(f"unknown network {net_id}")
            return pool.allocate_many(k)

    def free_count(self, net_id: str) -> int:
        with self._lock:
            pool = self._pools.get(net_id)
            return 0 if pool is None else pool.free_count()


class BatchedPorts(PortAllocator):
    """PortAllocator with the dynamic range mirrored as per-protocol
    masks: consecutive same-protocol dynamic picks inside one service's
    allocation run as ONE kernel grant, explicit claims scatter into
    the mask between runs — the run segmentation is what keeps a batch
    bit-identical to the scalar loop (including its partial-grant
    failure shape)."""

    def __init__(self):
        super().__init__()
        self._masks: dict[str, np.ndarray] = {}

    def _mask(self, protocol: str) -> np.ndarray:
        m = self._masks.get(protocol)
        if m is None:
            m = self._masks[protocol] = np.zeros(_PORT_SPAN, bool)
        return m

    def _claim(self, protocol: str, port: int, service_id: str) -> None:
        self._allocated[(protocol, port)] = service_id
        if DYNAMIC_PORT_START <= port <= DYNAMIC_PORT_END:
            self._mask(protocol)[port - DYNAMIC_PORT_START] = True

    def _unclaim(self, key: tuple[str, int]) -> None:
        protocol, port = key
        if DYNAMIC_PORT_START <= port <= DYNAMIC_PORT_END:
            self._mask(protocol)[port - DYNAMIC_PORT_START] = False

    def _grant_dynamic_run(self, protocol: str, k: int) -> list[int]:
        """Up to k dynamic ports in probe order (may return fewer when
        the range exhausts — the caller applies the partial exactly as
        the scalar loop would before failing). Cursor lands just past
        the last grant."""
        order = _alloc.grant_order(
            self._mask(protocol),
            self._next_dynamic - DYNAMIC_PORT_START, 0, _PORT_SPAN - 1)
        grants = [DYNAMIC_PORT_START + int(o) for o in order[:k]]
        if grants:
            self._next_dynamic = grants[-1] + 1
            if self._next_dynamic > DYNAMIC_PORT_END:
                self._next_dynamic = DYNAMIC_PORT_START
        return grants

    def _find_dynamic(self, protocol: str):
        grants = self._grant_dynamic_run(protocol, 1)
        return grants[0] if grants else None

    def allocate(self, service_id: str, ports) -> bool:
        with self._lock:
            for p in ports:
                if p.published_port:
                    owner = self._allocated.get(
                        (p.protocol, p.published_port))
                    if owner is not None and owner != service_id:
                        return False
            i, n = 0, len(ports)
            while i < n:
                p = ports[i]
                if p.published_port:
                    self._claim(p.protocol, p.published_port, service_id)
                    i += 1
                    continue
                if p.publish_mode != "ingress":
                    i += 1
                    continue
                # maximal run of consecutive same-protocol dynamic picks
                j = i
                while (j < n and not ports[j].published_port
                       and ports[j].publish_mode == "ingress"
                       and ports[j].protocol == p.protocol):
                    j += 1
                grants = self._grant_dynamic_run(p.protocol, j - i)
                for q, port in zip(ports[i:i + len(grants)], grants):
                    q.published_port = port
                    self._claim(q.protocol, port, service_id)
                if len(grants) < j - i:
                    return False        # scalar shape: partial applied
                i = j
            return True

    def release(self, service_id: str):
        with self._lock:
            for key in [k for k, v in self._allocated.items()
                        if v == service_id]:
                del self._allocated[key]
                self._unclaim(key)

    def release_except(self, service_id: str,
                       keep: set[tuple[str, int]]) -> bool:
        with self._lock:
            stale = [k for k, v in self._allocated.items()
                     if v == service_id and k not in keep]
            for k in stale:
                del self._allocated[k]
                self._unclaim(k)
            return bool(stale)
