"""Deallocator: completes deferred deletion of user-facing resources.

Re-derivation of manager/deallocator/deallocator.go: services marked
`pending_delete` wait until their last task is gone, then the service
record is deleted and any of its service-level networks that are
themselves pending deletion (and now unused) are freed; networks marked
`pending_delete` independently are deleted once nothing references them.
The deallocator is the only place a pending-delete object is finally
removed — tasks are the task reaper's job, this handles what the USER
owns.
"""
from __future__ import annotations

from ..api.objects import (
    EventCreate,
    EventDelete,
    EventUpdate,
    Network,
    Service,
    Task,
)
from ..orchestrator.base import EventLoopComponent
from ..store import by


class Deallocator(EventLoopComponent):
    name = "deallocator"

    def setup(self, tx):
        return (tx.find_services(), tx.find_networks())

    def on_start(self, snapshot):
        services, networks = snapshot
        for s in services:
            if s.pending_delete:
                self._process_service(s.id)
        for n in networks:
            if n.pending_delete:
                self._process_network(n.id)

    def handle(self, event):
        obj = getattr(event, "obj", None)
        if isinstance(event, EventDelete) and isinstance(obj, Task):
            if obj.service_id:
                self._process_service(obj.service_id)
        elif isinstance(event, (EventCreate, EventUpdate)) \
                and isinstance(obj, Service):
            if obj.pending_delete:
                self._process_service(obj.id)
        elif isinstance(event, (EventCreate, EventUpdate)) \
                and isinstance(obj, Network):
            if obj.pending_delete:
                self._process_network(obj.id)
        elif isinstance(event, EventDelete) and isinstance(obj, Service):
            # a freed service may unblock pending-delete networks
            for na in list(obj.spec.task.networks) + list(obj.spec.networks):
                if na.target:
                    self._process_network(na.target)

    # ------------------------------------------------------------- services
    def _process_service(self, service_id: str):
        nets: list[str] = []

        def cb(tx):
            s = tx.get_service(service_id)
            if s is None or not s.pending_delete:
                return
            if tx.find_tasks(by.ByServiceID(service_id)):
                return  # tasks still winding down
            for na in list(s.spec.task.networks) + list(s.spec.networks):
                if na.target:
                    nets.append(na.target)
            tx.delete(Service, service_id)

        self.store.update(cb)
        for nid in nets:
            self._process_network(nid)

    # ------------------------------------------------------------- networks
    def _process_network(self, network_id: str):
        def cb(tx):
            n = tx.get_network(network_id)
            if n is None or not n.pending_delete:
                return
            for s in tx.find_services():
                targets = {na.target for na in s.spec.task.networks}
                targets |= {na.target for na in s.spec.networks}
                if network_id in targets:
                    return  # still referenced
            for t in tx.find_tasks():
                if network_id in (t.networks or []):
                    return
            tx.delete(Network, network_id)

        self.store.update(cb)
