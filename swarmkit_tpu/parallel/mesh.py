"""Device-mesh sharding of the scheduling and raft kernels.

SURVEY.md §5 long-context note: this framework's scale axes are nodes, tasks,
services and raft-log length, so the mesh maps those — per-node arrays shard
over the `nodes` axis (the 100k×10k case from BASELINE.md exceeds one core's
appetite), per-manager ack bitmaps over the `managers` axis. Shardings are
declared with NamedSharding/PartitionSpec and the kernels run under jit so
XLA inserts the collectives (psum for quorum tallies and water-level sums,
gathers for the tiny boundary sort) over ICI — the design recipe of the
public scaling-book: pick a mesh, annotate, let XLA place collectives.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import placement as placement_ops
from ..scheduler.encode import KERNEL_ARG_FIELDS

NODE_AXIS = "nodes"

# Per-field sharding: (node-axis position or None, pad fill value). Order is
# NOT duplicated here — it comes from KERNEL_ARG_FIELDS.
_FIELD_SHARDING: dict[str, tuple[int | None, object]] = {
    "ready": (0, False),
    "node_val": (0, -1),
    "node_plat": (0, 0),
    "node_plugins": (0, False),
    "extra_mask": (1, False),
    "constraints": (None, 0),
    "plat_req": (None, 0),
    "req_plugins": (None, 0),
    "avail_res": (0, 0),
    "total0": (0, 0),
    "svc_count0": (1, 0),
    "n_tasks": (None, 0),
    "svc_idx": (None, 0),
    "need_res": (None, 0),
    "max_replicas": (None, 0),
    "penalty": (1, False),
    "has_ports": (None, 0),
    "group_ports": (None, 0),
    "port_used0": (0, False),
    # phantom pad nodes fall into segment 0 with zero capacity and zero
    # service counts — invisible to every pour
    "spread_rank": (2, 0),
}


def make_mesh(n_devices: int | None = None, axis: str = NODE_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


# ---------------------------------------------------------------------------
# Production resident-state shardings (ops/resident.py mesh mode).
#
# The device-resident scheduler carries its node tables across ticks; in mesh
# mode every per-node axis is sharded over `nodes` and the per-tick group
# tables replicate (they are small) except the [*, N]-shaped ones, which
# shard their node axis so the fill kernel reads co-resident data. XLA
# inserts the cross-shard collectives (segment-sum psums, the boundary
# lexsort gather) exactly as in the one-shot `sharded_schedule` proof path —
# this dict is what makes that layout the PRODUCTION layout.

RESIDENT_STATE_SPECS = {
    "ready": P(NODE_AXIS),
    "node_val": P(NODE_AXIS, None),
    "node_plat": P(NODE_AXIS, None),
    "node_plugins": P(NODE_AXIS, None),
    "port_used": P(NODE_AXIS, None),
    "avail_res": P(NODE_AXIS, None),
    "total0": P(NODE_AXIS),
    "svc_mat": P(None, NODE_AXIS),
}


def resident_shardings(mesh: Mesh) -> dict:
    """NamedShardings for ResidentPlacement's device state, plus the
    replicated default under `None`."""
    out = {k: NamedSharding(mesh, spec)
           for k, spec in RESIDENT_STATE_SPECS.items()}
    out[None] = NamedSharding(mesh, P())
    return out


def node_axis_sharding(mesh: Mesh, ndim: int, axis: int) -> NamedSharding:
    """A NamedSharding placing `axis` of an ndim-array on the node axis."""
    parts = [None] * ndim
    parts[axis] = NODE_AXIS
    return NamedSharding(mesh, P(*parts))


def _pad_nodes(arr: np.ndarray, n_pad: int, axis: int, fill):
    if n_pad == 0:
        return arr
    pad_width = [(0, 0)] * arr.ndim
    pad_width[axis] = (0, n_pad)
    return np.pad(arr, pad_width, constant_values=fill)


def shard_problem(p, mesh: Mesh):
    """Place an EncodedProblem's arrays onto the mesh: every per-node axis is
    sharded, group-side tables are replicated. Node count is padded to a
    multiple of the mesh size with ineligible phantom nodes (ready=False),
    which the mask kernel excludes, so results are unchanged."""
    n_dev = mesh.devices.size
    N = len(p.node_ids)
    n_pad = (-N) % n_dev

    args = []
    for field in KERNEL_ARG_FIELDS:
        node_axis, fill = _FIELD_SHARDING[field]
        arr = np.asarray(getattr(p, field))
        if node_axis is None:
            spec = P()
        else:
            arr = _pad_nodes(arr, n_pad, node_axis, fill)
            parts = [None] * arr.ndim
            parts[node_axis] = NODE_AXIS
            spec = P(*parts)
        args.append(jax.device_put(arr, NamedSharding(mesh, spec)))
    return tuple(args), N


def sharded_schedule(p, mesh: Mesh):
    """Run the placement kernel with per-node arrays sharded over the mesh.
    Returns counts[G, N] (numpy, truncated back to the real node count)."""
    args, N = shard_problem(p, mesh)
    with jax.sharding.set_mesh(mesh):
        counts, totals, svc_counts = placement_ops.schedule_groups(*args)
    return np.asarray(counts)[:, :N]


def sharded_cluster_step(p, acks, quorum, mesh: Mesh):
    """The FUSED flagship step (models.cluster_step) on the mesh: per-node
    placement arrays shard over the node axis, the raft ack matrix shards
    its log axis over the same devices (the tally is elementwise along the
    log; the commit prefix-scan crosses shards, XLA inserting the
    collectives). Returns (counts[G, N] numpy, commit_index int)."""
    args, N = shard_problem(p, mesh)
    n_dev = mesh.devices.size
    E = acks.shape[1]
    e_pad = (-E) % n_dev
    if e_pad:
        # padding with un-acked entries can only sit past the commit
        # frontier (the prefix cumprod stops at the first hole)
        acks = np.pad(np.asarray(acks), ((0, 0), (0, e_pad)),
                      constant_values=False)
    acks_dev = jax.device_put(
        np.asarray(acks), NamedSharding(mesh, P(None, NODE_AXIS)))
    with jax.sharding.set_mesh(mesh):
        counts, totals, commit = _fused_step()(acks_dev, quorum, *args)
    return np.asarray(counts)[:, :N], int(commit)


_FUSED_JIT = None


def _fused_step():
    """Module-cached jit of the fused flagship step: rebuilding the jit
    wrapper per call would recompile the whole fused program every time
    (10-20 s on the real chip)."""
    global _FUSED_JIT
    if _FUSED_JIT is None:
        from ..models.cluster_step import cluster_step

        _FUSED_JIT = jax.jit(cluster_step)
    return _FUSED_JIT
