"""Device-mesh sharding of the scheduling and raft kernels.

SURVEY.md §5 long-context note: this framework's scale axes are nodes, tasks,
services and raft-log length, so the mesh maps those — per-node arrays shard
over the `nodes` axis (the 100k×10k case from BASELINE.md exceeds one core's
appetite), per-manager ack bitmaps over the `managers` axis. Shardings are
declared with NamedSharding/PartitionSpec and the kernels run under jit so
XLA inserts the collectives (psum for quorum tallies and water-level sums,
gathers for the tiny boundary sort) over ICI — the design recipe of the
public scaling-book: pick a mesh, annotate, let XLA place collectives.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import placement as placement_ops

NODE_AXIS = "nodes"


def make_mesh(n_devices: int | None = None, axis: str = NODE_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def _pad_nodes(arr: np.ndarray, n_pad: int, axis: int, fill):
    if n_pad == 0:
        return arr
    pad_width = [(0, 0)] * arr.ndim
    pad_width[axis] = (0, n_pad)
    return np.pad(arr, pad_width, constant_values=fill)


def shard_problem(p, mesh: Mesh):
    """Place an EncodedProblem's arrays onto the mesh: every per-node axis is
    sharded, group-side tables are replicated. Node count is padded to a
    multiple of the mesh size with ineligible phantom nodes (ready=False),
    which the mask kernel excludes, so results are unchanged."""
    n_dev = mesh.devices.size
    N = len(p.node_ids)
    n_pad = (-N) % n_dev

    def put(arr, spec, pad_axis=None, fill=0):
        arr = np.asarray(arr)
        if pad_axis is not None:
            arr = _pad_nodes(arr, n_pad, pad_axis, fill)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    args = (
        put(p.ready, P(NODE_AXIS), 0, False),
        put(p.node_val, P(NODE_AXIS, None), 0, -1),
        put(p.node_plat, P(NODE_AXIS, None), 0, 0),
        put(p.node_plugins, P(NODE_AXIS, None), 0, False),
        put(p.extra_mask, P(None, NODE_AXIS), 1, False),
        put(p.constraints, P()),
        put(p.plat_req, P()),
        put(p.req_plugins, P()),
        put(p.avail_res, P(NODE_AXIS, None), 0, 0),
        put(p.total0, P(NODE_AXIS), 0, 0),
        put(p.svc_count0, P(None, NODE_AXIS), 1, 0),
        put(p.n_tasks, P()),
        put(p.svc_idx, P()),
        put(p.need_res, P()),
        put(p.max_replicas, P()),
        put(p.penalty, P(None, NODE_AXIS), 1, False),
        put(p.has_ports, P()),
        put(p.group_ports, P()),
        put(p.port_used0, P(NODE_AXIS, None), 0, False),
    )
    return args, N


def sharded_schedule(p, mesh: Mesh):
    """Run the placement kernel with per-node arrays sharded over the mesh.
    Returns counts[G, N] (numpy, truncated back to the real node count)."""
    args, N = shard_problem(p, mesh)
    with jax.sharding.set_mesh(mesh):
        counts, totals, svc_counts = placement_ops.schedule_groups(*args)
    return np.asarray(counts)[:, :N]


def sharded_cluster_step(mesh: Mesh):
    """One jittable 'cluster step' over the mesh: batched placement for the
    scheduler plus a raft quorum tally — the two manager-side hot loops of
    SURVEY.md §2.4/§2.3 fused into a single compiled program.

    Returns a function suitable for jit-compiling under the mesh; per-node
    arrays arrive sharded over the node axis, raft acks replicated (the
    dedicated manager-axis variant lives in ops.raft_replay)."""

    def step(placement_args, acks, quorum):
        counts, totals, svc_counts = placement_ops.schedule_groups(*placement_args)
        tally = jnp.sum(acks.astype(jnp.int32), axis=0)
        committed = tally >= quorum
        prefix = jnp.cumprod(committed.astype(jnp.int32))
        commit_index = jnp.sum(prefix).astype(jnp.int32)
        return counts, totals, commit_index

    return step
