"""Device-mesh sharding of the scheduling and raft kernels.

SURVEY.md §5 long-context note: this framework's scale axes are nodes, tasks,
services and raft-log length, so the mesh maps those — per-node arrays shard
over the `nodes` axis (the 100k×10k case from BASELINE.md exceeds one core's
appetite), per-manager ack bitmaps over the `managers` axis. Shardings are
declared with NamedSharding/PartitionSpec and the kernels run under jit so
XLA inserts the collectives (psum for quorum tallies and water-level sums,
gathers for the tiny boundary sort) over ICI — the design recipe of the
public scaling-book: pick a mesh, annotate, let XLA place collectives.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import placement as placement_ops
from ..scheduler.encode import KERNEL_ARG_FIELDS

NODE_AXIS = "nodes"


def mesh_context(mesh: "Mesh"):
    """Context manager making `mesh` ambient for jitted collectives, across
    jax versions: `jax.sharding.use_mesh` (always a scoped context manager
    where present), `jax.sharding.set_mesh` only when it returns one, and
    the Mesh's own context manager as the 0.4.x fallback (there,
    NamedSharding-carrying jits need no ambient mesh at all, so entering
    the Mesh is sufficient). use_mesh is probed FIRST: a set_mesh variant
    that is a bare global setter would leak the mesh past the with-block.
    Every `with set_mesh(...)` call site in this repo goes through here;
    this container's jax has neither helper, which made test_parallel and
    dryrun_multichip fail at seed."""
    fn = getattr(jax.sharding, "use_mesh", None)
    if fn is not None:
        return fn(mesh)
    fn = getattr(jax.sharding, "set_mesh", None)
    if fn is not None:
        cm = fn(mesh)
        if hasattr(cm, "__enter__"):
            return cm
    return mesh

# Per-field sharding: (node-axis position or None, pad fill value). Order is
# NOT duplicated here — it comes from KERNEL_ARG_FIELDS.
_FIELD_SHARDING: dict[str, tuple[int | None, object]] = {
    "ready": (0, False),
    "node_val": (0, -1),
    "node_plat": (0, 0),
    "node_plugins": (0, False),
    "extra_mask": (1, False),
    "constraints": (None, 0),
    "plat_req": (None, 0),
    "req_plugins": (None, 0),
    "avail_res": (0, 0),
    "total0": (0, 0),
    "svc_count0": (1, 0),
    "n_tasks": (None, 0),
    "svc_idx": (None, 0),
    "need_res": (None, 0),
    "max_replicas": (None, 0),
    "penalty": (1, False),
    "has_ports": (None, 0),
    "group_ports": (None, 0),
    "port_used0": (0, False),
    # phantom pad nodes fall into segment 0 with zero capacity and zero
    # service counts — invisible to every pour
    "spread_rank": (2, 0),
    # per-group CSI rows — group-side, replicated (the kernel gathers
    # node_val columns by row key, so the node axis never appears here)
    "vol_topo": (None, -1),
}


def make_mesh(n_devices: int | None = None, axis: str = NODE_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


# ---------------------------------------------------------------------------
# Production resident-state shardings (ops/resident.py mesh mode).
#
# The device-resident scheduler carries its node tables across ticks; in mesh
# mode every per-node axis is sharded over `nodes` and the per-tick group
# tables replicate (they are small) except the [*, N]-shaped ones, which
# shard their node axis so the fill kernel reads co-resident data. XLA
# inserts the cross-shard collectives (segment-sum psums, the boundary
# lexsort gather) exactly as in the one-shot `sharded_schedule` proof path —
# this dict is what makes that layout the PRODUCTION layout.

RESIDENT_STATE_SPECS = {
    "ready": P(NODE_AXIS),
    "node_val": P(NODE_AXIS, None),
    "node_plat": P(NODE_AXIS, None),
    "node_plugins": P(NODE_AXIS, None),
    "port_used": P(NODE_AXIS, None),
    "avail_res": P(NODE_AXIS, None),
    "total0": P(NODE_AXIS),
    "svc_mat": P(None, NODE_AXIS),
}


def resident_shardings(mesh: Mesh) -> dict:
    """NamedShardings for ResidentPlacement's device state, plus the
    replicated default under `None`."""
    out = {k: NamedSharding(mesh, spec)
           for k, spec in RESIDENT_STATE_SPECS.items()}
    out[None] = NamedSharding(mesh, P())
    return out


def node_axis_sharding(mesh: Mesh, ndim: int, axis: int) -> NamedSharding:
    """A NamedSharding placing `axis` of an ndim-array on the node axis."""
    parts = [None] * ndim
    parts[axis] = NODE_AXIS
    return NamedSharding(mesh, P(*parts))


def _pad_nodes(arr: np.ndarray, n_pad: int, axis: int, fill):
    if n_pad == 0:
        return arr
    pad_width = [(0, 0)] * arr.ndim
    pad_width[axis] = (0, n_pad)
    return np.pad(arr, pad_width, constant_values=fill)


# node-axis arrays at/above this many bytes upload shard-by-shard via
# jax.make_array_from_callback instead of one padded whole-array
# device_put: at the 1M-node grid the [G, LMAX, N] spread table alone is
# hundreds of MB, and the padded host copy would double peak memory
CHUNKED_UPLOAD_BYTES = 64 << 20


def _put_node_sharded(arr: np.ndarray, mesh: Mesh, node_axis: int,
                      fill, n_padded: int, stats: dict | None = None):
    """Ship one node-axis array to the mesh WITHOUT materializing a padded
    whole-array host copy: each device shard is sliced (and tail-padded)
    on demand, so peak host staging is one shard. `arr` may be a
    broadcast view — only shard-sized chunks are ever made contiguous."""
    shape = (arr.shape[:node_axis] + (n_padded,)
             + arr.shape[node_axis + 1:])
    sharding = node_axis_sharding(mesh, len(shape), node_axis)
    n_real = arr.shape[node_axis]

    def cb(index):
        sl = index[node_axis]
        start = sl.start or 0
        stop = sl.stop if sl.stop is not None else shape[node_axis]
        idx = list(index)
        if stop <= n_real:
            out = np.ascontiguousarray(arr[tuple(idx)])
        else:
            out_shape = tuple(
                (stop - start) if d == node_axis
                else ((s.stop if s.stop is not None else shape[d])
                      - (s.start or 0))
                for d, s in enumerate(idx))
            out = np.full(out_shape, fill, arr.dtype)
            take = n_real - start
            if take > 0:
                idx[node_axis] = slice(start, n_real)
                dst = [slice(None)] * len(out_shape)
                dst[node_axis] = slice(0, take)
                out[tuple(dst)] = arr[tuple(idx)]
        if stats is not None:
            stats["h2d_bytes"] = stats.get("h2d_bytes", 0) + out.nbytes
        return out

    return jax.make_array_from_callback(shape, sharding, cb)


def shard_problem(p, mesh: Mesh, stats: dict | None = None,
                  chunked: int | None = None):
    """Place an EncodedProblem's arrays onto the mesh: every per-node axis is
    sharded, group-side tables are replicated. Node count is padded to a
    multiple of the mesh size with ineligible phantom nodes (ready=False),
    which the mask kernel excludes, so results are unchanged.

    stats (optional dict) accumulates `h2d_bytes` — the wire bytes this
    upload cost, the bench's H2D column. Node-axis arrays at/above
    `chunked` bytes (default CHUNKED_UPLOAD_BYTES) upload shard-by-shard
    so the padded host copy is never materialized whole."""
    if chunked is None:
        chunked = CHUNKED_UPLOAD_BYTES
    n_dev = mesh.devices.size
    N = len(p.node_ids)
    n_pad = (-N) % n_dev

    args = []
    for field in KERNEL_ARG_FIELDS:
        node_axis, fill = _FIELD_SHARDING[field]
        arr = np.asarray(getattr(p, field))
        if node_axis is None:
            dev = jax.device_put(arr, NamedSharding(mesh, P()))
            if stats is not None:
                stats["h2d_bytes"] = stats.get("h2d_bytes", 0) + arr.nbytes
        elif arr.nbytes >= chunked:
            dev = _put_node_sharded(arr, mesh, node_axis, fill,
                                    arr.shape[node_axis] + n_pad, stats)
        else:
            arr = _pad_nodes(arr, n_pad, node_axis, fill)
            dev = jax.device_put(
                arr, node_axis_sharding(mesh, arr.ndim, node_axis))
            if stats is not None:
                stats["h2d_bytes"] = stats.get("h2d_bytes", 0) + arr.nbytes
        args.append(dev)
    return tuple(args), N


def sharded_schedule(p, mesh: Mesh):
    """Run the placement kernel with per-node arrays sharded over the mesh.
    Returns counts[G, N] (numpy, truncated back to the real node count)."""
    args, N = shard_problem(p, mesh)
    strategy = 1 if getattr(p, "strategy", "spread") == "binpack" else 0
    with mesh_context(mesh):
        counts, totals, svc_counts = placement_ops.schedule_groups(
            *args, strategy=strategy)
    return np.asarray(counts)[:, :N]


def sharded_cluster_step(p, acks, quorum, mesh: Mesh,
                         stats: dict | None = None):
    """The FUSED flagship step (models.cluster_step) on the mesh: per-node
    placement arrays shard over the node axis, the raft ack matrix shards
    its log axis over the same devices (the tally is elementwise along the
    log; the commit prefix-scan crosses shards, XLA inserting the
    collectives). Returns (counts[G, N] numpy, commit_index int).

    stats (optional dict) records the bench's split: h2d_bytes,
    upload_s, fill_s (dispatch + device compute) and pull_s (the one real
    value pull — through a tunnel this is the true sync; see CLAUDE.md)."""
    import time as _time

    t0 = _time.perf_counter()
    args, N = shard_problem(p, mesh, stats=stats)
    n_dev = mesh.devices.size
    E = acks.shape[1]
    e_pad = (-E) % n_dev
    if e_pad:
        # padding with un-acked entries can only sit past the commit
        # frontier (the prefix cumprod stops at the first hole)
        acks = np.pad(np.asarray(acks), ((0, 0), (0, e_pad)),
                      constant_values=False)
    acks_dev = jax.device_put(
        np.asarray(acks), NamedSharding(mesh, P(None, NODE_AXIS)))
    if stats is not None:
        stats["h2d_bytes"] = stats.get("h2d_bytes", 0) \
            + np.asarray(acks).nbytes
        stats["upload_s"] = _time.perf_counter() - t0
    t1 = _time.perf_counter()
    strategy = 1 if getattr(p, "strategy", "spread") == "binpack" else 0
    with mesh_context(mesh):
        counts, totals, commit = _fused_step()(acks_dev, quorum, *args,
                                               strategy=strategy)
    # the scalar commit pull is the TRUE device sync (CLAUDE.md tunnel
    # rule: block_until_ready lies through the tunnel; only a real value
    # pull syncs) — it delimits fill_s honestly on the platform the
    # bench targets, leaving pull_s as the counts D2H alone
    commit_i = int(commit)
    if stats is not None:
        stats["fill_s"] = _time.perf_counter() - t1
    t2 = _time.perf_counter()
    counts_np = np.asarray(counts)[:, :N]
    if stats is not None:
        stats["pull_s"] = _time.perf_counter() - t2
        stats["d2h_bytes"] = counts_np.nbytes
    return counts_np, commit_i


_FUSED_JIT = None


def _fused_step():
    """Module-cached jit of the fused flagship step: rebuilding the jit
    wrapper per call would recompile the whole fused program every time
    (10-20 s on the real chip)."""
    global _FUSED_JIT
    if _FUSED_JIT is None:
        from ..models.cluster_step import cluster_step

        _FUSED_JIT = jax.jit(cluster_step, static_argnames=("strategy",))
    return _FUSED_JIT
