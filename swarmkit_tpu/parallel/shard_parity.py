"""Parity at oracle-infeasible sizes: sampled-shard oracle + invariants.

The judged property of this framework is BIT-parity between the TPU
water-fill and the CPU greedy oracle (`tests/test_placement_parity.py`).
The oracle is Python-per-task, so at the mesh flagship's 100k–1M-node ×
1M-task grid a full-oracle check cannot run. This module implements the
scale-out verification ladder (ISSUE 7 / docs/mesh.md):

  1. full oracle at every feasible shape (unchanged — the dryrun and
     test_parallel keep doing it);
  2. SAMPLED-SHARD oracle above that: for problems built shard-
     partitioned (`models.cluster_step.synth_shard_cluster` — every
     group eligible on exactly one contiguous node slice, spread
     branches and warm service counts confined to their slice, port ids
     reused only within a slice), the global sequential-group fill
     RESTRICTED to a slice is bit-identical to the greedy oracle run on
     that slice alone: groups of other slices cannot place there (the
     eligibility mask), their service rows are distinct, and every fold
     they perform (totals, avail, ports, svc counts) touches only their
     own slice — so they are no-ops on this slice's state. Slicing
     preserves the relative node order (the canonical node_idx
     tie-break) and the relative branch-rank order (the pour's
     tie-break), so the restricted fill IS the slice's fill.
  3. invariant checks on the FULL output: non-negativity, per-group task
     conservation, static-mask eligibility (which since ISSUE 19 folds
     the CSI volume-topology leg — `cpu_static_mask` carries it, so a
     placement on a vol-topo-infeasible node fails here), resource
     capacity, max-replicas caps, ORDER-AWARE host-port claims (the
     oracle's incremental batch-internal conflict semantics), and the
     topology-balance water property of the outermost preference level
     — each a vectorized numpy pass, feasible at any size the arrays
     fit in memory.

The sampled-shard oracle is STRATEGY-AWARE: `slice_shard_problem`
carries `strategy` and the group-side `vol_topo` rows, and
`cpu_schedule_encoded` dispatches on them — so binpack and topology
fills at the scale-out grid are held to the same sliced bit-parity bar
as spread.

A violation raises AssertionError (bench rows translate that into
parity=False and join failed_rows).
"""
from __future__ import annotations

import numpy as np


def slice_shard_problem(p, group_idx: np.ndarray, node_lo: int,
                        node_hi: int):
    """Restrict an EncodedProblem to `group_idx` (ascending, original
    order) × the contiguous node slice [node_lo, node_hi). Service rows
    are kept whole (svc_idx values stay valid); only their node columns
    are sliced."""
    from ..scheduler.encode import EncodedProblem

    gsel = np.asarray(group_idx, np.int64)
    sl = slice(node_lo, node_hi)
    q = EncodedProblem(
        node_ids=p.node_ids[node_lo:node_hi],
        group_keys=[p.group_keys[int(g)] for g in gsel],
        service_ids=p.service_ids,
        groups=[],
    )
    q.ready = np.ascontiguousarray(p.ready[sl])
    q.node_val = np.ascontiguousarray(p.node_val[sl])
    q.node_plat = np.ascontiguousarray(p.node_plat[sl])
    q.node_plugins = np.ascontiguousarray(p.node_plugins[sl])
    q.port_used0 = np.ascontiguousarray(p.port_used0[sl])
    q.avail_res = np.ascontiguousarray(p.avail_res[sl])
    q.total0 = np.ascontiguousarray(p.total0[sl])
    q.svc_count0 = np.ascontiguousarray(p.svc_count0[:, sl])
    q.n_tasks = p.n_tasks[gsel]
    q.svc_idx = p.svc_idx[gsel]
    q.need_res = p.need_res[gsel]
    q.max_replicas = p.max_replicas[gsel]
    q.constraints = p.constraints[gsel]
    q.plat_req = p.plat_req[gsel]
    q.req_plugins = p.req_plugins[gsel]
    q.has_ports = p.has_ports[gsel]
    q.group_ports = p.group_ports[gsel]
    q.penalty = np.ascontiguousarray(p.penalty[gsel][:, sl])
    q.extra_mask = np.ascontiguousarray(p.extra_mask[gsel][:, sl])
    q.spread_rank = np.ascontiguousarray(
        np.asarray(p.spread_rank)[gsel][:, :, sl])
    vt = getattr(p, "vol_topo", None)
    if vt is not None:
        # group-side CSI topology rows: the group axis slices, the node
        # axis never appears (the mask leg gathers node_val columns by
        # row key, and node_val keeps its columns under node slicing)
        q.vol_topo = np.ascontiguousarray(np.asarray(vt)[gsel])
        q.vol_topo_any = bool(q.vol_topo.shape[1])
    # the slice oracle must score with the SAME strategy as the kernel
    q.strategy = getattr(p, "strategy", "spread")
    return q


def sampled_shard_parity(p, counts: np.ndarray, group_shard: np.ndarray,
                         n_shards: int, sample, log=None) -> list[int]:
    """Bit-parity of `counts` against the greedy oracle on sampled shards.

    `sample`: iterable of shard indices (or an int — that many shards
    picked deterministically, spread across the range). For each sampled
    shard s the oracle re-runs on s's node slice with s's groups, and
    counts[groups_of_s] must (a) equal the oracle inside the slice and
    (b) be identically zero outside it. Returns the shards checked."""
    from ..scheduler.batch import cpu_schedule_encoded

    N = len(p.node_ids)
    per = N // n_shards
    group_shard = np.asarray(group_shard)
    if isinstance(sample, int):
        k = max(1, min(sample, n_shards))
        sample = sorted({int(s) for s in
                         np.linspace(0, n_shards - 1, k).round()})
    checked = []
    for s in sample:
        s = int(s)
        gsel = np.flatnonzero(group_shard == s)
        a, b = s * per, (s + 1) * per
        sub = slice_shard_problem(p, gsel, a, b)
        expected = cpu_schedule_encoded(sub)
        got = counts[gsel]
        outside = got.copy()
        outside[:, a:b] = 0
        assert not outside.any(), \
            f"shard {s}: placements leaked outside the shard's node slice"
        np.testing.assert_array_equal(
            got[:, a:b], expected,
            err_msg=f"shard {s}: kernel fill != greedy oracle on the "
                    f"shard's node slice [{a}, {b})")
        checked.append(s)
        if log is not None:
            log(f"sampled-shard parity ok: shard {s} "
                f"({len(gsel)} groups, {per} nodes, "
                f"{int(expected.sum())} placed)")
    return checked


def check_fill_invariants(p, counts: np.ndarray) -> dict:
    """Vectorized invariant checks on a full fill output — the guardrail
    at sizes where even the sampled oracle covers only a fraction.
    Raises AssertionError on violation; returns summary stats."""
    from ..scheduler.batch import cpu_static_mask

    c = np.asarray(counts, np.int64)
    assert (c >= 0).all(), "negative placement count"
    placed_per_group = c.sum(axis=1)
    assert (placed_per_group <= p.n_tasks.astype(np.int64)).all(), \
        "a group placed more tasks than it has"

    mask = cpu_static_mask(p)
    assert not (c[~mask] > 0).any(), \
        "placement on a statically-ineligible node"

    used = c.T @ p.need_res.astype(np.int64)              # [N, R]
    assert (used <= p.avail_res.astype(np.int64)).all(), \
        "resource capacity overcommitted"

    # max-replicas: final per-service per-node count never exceeds the cap
    svc_final = p.svc_count0.astype(np.int64).copy()
    np.add.at(svc_final, p.svc_idx, c)
    for gi in np.flatnonzero(p.max_replicas > 0):
        assert (svc_final[p.svc_idx[gi]]
                <= int(p.max_replicas[gi])).all(), \
            f"group {gi}: max_replicas cap exceeded"

    # host ports, ORDER-AWARE: claims fold in canonical group order, so
    # group gi may never claim a port occupied by the initial state OR by
    # any earlier group's claim — the oracle's incremental-claim
    # semantics, which the kernel's in-scan port fold must mirror. (Also
    # subsumes the pairwise "no two groups share a port on one node".)
    port_occ = p.port_used0.copy()                        # [N, PV]
    for gi in np.flatnonzero(p.has_ports):
        assert (c[gi] <= 1).all(), \
            f"port group {gi}: >1 task on one node"
        pids = np.flatnonzero(p.group_ports[gi])
        conflict = port_occ[:, pids].any(axis=1)
        assert not (c[gi][conflict] > 0).any(), \
            f"port group {gi}: placed on a node whose port was already " \
            f"claimed (initial state or an earlier group in batch order)"
        port_occ[np.ix_(c[gi] > 0, pids)] = True

    # topology balance: the outermost preference level pours by the water
    # principle, so a branch with END-state slack (a fortiori slack at
    # fill time — capacity only depletes as groups fold) bounds every
    # poured branch's final service total to within one unit. Binpack
    # ignores preferences (flat consolidation fill) so the check applies
    # to the spread/topology strategies only; binpack's fill itself is
    # covered by the strategy-aware sampled oracle above.
    sr = np.asarray(p.spread_rank)
    if sr.shape[1] > 0 and getattr(p, "strategy", "spread") != "binpack":
        _check_topology_balance(p, c, mask, used, svc_final, port_occ)

    return {
        "placed": int(c.sum()),
        "tasks": int(p.n_tasks.sum()),
        "groups": int(len(p.n_tasks)),
        "nodes": len(p.node_ids),
    }


def _check_topology_balance(p, c, mask, used, svc_final, port_occ):
    """Water property of the outermost preference level (ISSUE 19).

    The level-0 pour gives each unit to the branch with the smallest
    (service total, rank), where a branch's total counts ALL its nodes
    (nodeset.go:88-104) and its cap sums eligible nodes' capacity. Hence
    at completion, for any poured branch a and any branch b that still
    had capacity: k_a + y_a <= k_b + y_b + 1 (b was in the pour heap the
    whole time, so a's last unit went to a total no higher than b's).
    Fill-time caps are unobservable post-hoc, but capacity is MONOTONE
    non-increasing across the batch fold — so end-state slack implies
    fill-time slack, and the end-state check is sound (conservative:
    branches saturated only late escape it). Fill-time service totals
    are exact: unique service rows (the synth builder's shape) read
    svc_count0 directly; shared rows replay the canonical fold order.
    """
    G, N = c.shape
    r0 = np.asarray(p.spread_rank)[:, 0, :]
    B = int(r0.max()) + 1
    avail_end = p.avail_res.astype(np.int64) - used            # [N, R]
    svc_idx = np.asarray(p.svc_idx)
    unique_rows = len(np.unique(svc_idx)) == len(svc_idx)
    if not unique_rows:
        run: dict[int, np.ndarray] = {}
        before = []
        for gi in range(G):
            s = int(svc_idx[gi])
            b = run.get(s)
            if b is None:
                b = p.svc_count0[s].astype(np.int64)
            before.append(b)
            run[s] = b + c[gi]

    # chunked over groups: each chunk is a handful of O(chunk·N) C-speed
    # passes (bincount on flattened (group, branch) keys), so the sweep
    # stays feasible at the scale-out grid without a [G, N] staging copy
    CH = 128
    big = np.int64(1) << 40
    for g0 in range(0, G, CH):
        gs = slice(g0, min(g0 + CH, G))
        ch = gs.stop - g0
        r = np.ascontiguousarray(r0[gs]).astype(np.int64)
        flat = (np.arange(ch, dtype=np.int64)[:, None] * B + r).ravel()
        y = np.bincount(flat, weights=c[gs].ravel(),
                        minlength=ch * B).reshape(ch, B).astype(np.int64)
        if unique_rows:
            sb = p.svc_count0[svc_idx[gs]]
        else:
            sb = np.stack(before[g0:gs.stop])
        k = np.bincount(flat, weights=np.asarray(sb, np.float64).ravel(),
                        minlength=ch * B).reshape(ch, B).astype(np.int64)
        slack = mask[gs] & (avail_end[None, :, :]
                            >= p.need_res[gs][:, None, :]).all(axis=2)
        for j in np.flatnonzero(p.max_replicas[gs] > 0):
            slack[j] &= (svc_final[svc_idx[g0 + j]]
                         < int(p.max_replicas[g0 + j]))
        for j in np.flatnonzero(p.has_ports[gs]):
            pids = np.flatnonzero(p.group_ports[g0 + j])
            slack[j] &= ~port_occ[:, pids].any(axis=1)
        b_slack = np.bincount(flat, weights=slack.ravel(),
                              minlength=ch * B).reshape(ch, B) > 0
        ky = k + y
        poured = y > 0
        hi = np.where(poured, ky, -big).max(axis=1)
        lo = np.where(b_slack, ky, big).min(axis=1)
        valid = poured.any(axis=1) & b_slack.any(axis=1)
        bad = valid & (hi > lo + 1)
        assert not bad.any(), (
            f"group {g0 + int(np.flatnonzero(bad)[0])}: topology "
            f"imbalance — a poured branch's service total exceeds a "
            f"slack branch's by more than one")
