"""Parity at oracle-infeasible sizes: sampled-shard oracle + invariants.

The judged property of this framework is BIT-parity between the TPU
water-fill and the CPU greedy oracle (`tests/test_placement_parity.py`).
The oracle is Python-per-task, so at the mesh flagship's 100k–1M-node ×
1M-task grid a full-oracle check cannot run. This module implements the
scale-out verification ladder (ISSUE 7 / docs/mesh.md):

  1. full oracle at every feasible shape (unchanged — the dryrun and
     test_parallel keep doing it);
  2. SAMPLED-SHARD oracle above that: for problems built shard-
     partitioned (`models.cluster_step.synth_shard_cluster` — every
     group eligible on exactly one contiguous node slice, spread
     branches and warm service counts confined to their slice, port ids
     reused only within a slice), the global sequential-group fill
     RESTRICTED to a slice is bit-identical to the greedy oracle run on
     that slice alone: groups of other slices cannot place there (the
     eligibility mask), their service rows are distinct, and every fold
     they perform (totals, avail, ports, svc counts) touches only their
     own slice — so they are no-ops on this slice's state. Slicing
     preserves the relative node order (the canonical node_idx
     tie-break) and the relative branch-rank order (the pour's
     tie-break), so the restricted fill IS the slice's fill.
  3. invariant checks on the FULL output: non-negativity, per-group task
     conservation, static-mask eligibility, resource capacity,
     max-replicas caps, host-port exclusivity — each a vectorized numpy
     pass, feasible at any size the arrays fit in memory.

A violation raises AssertionError (bench rows translate that into
parity=False and join failed_rows).
"""
from __future__ import annotations

import numpy as np


def slice_shard_problem(p, group_idx: np.ndarray, node_lo: int,
                        node_hi: int):
    """Restrict an EncodedProblem to `group_idx` (ascending, original
    order) × the contiguous node slice [node_lo, node_hi). Service rows
    are kept whole (svc_idx values stay valid); only their node columns
    are sliced."""
    from ..scheduler.encode import EncodedProblem

    gsel = np.asarray(group_idx, np.int64)
    sl = slice(node_lo, node_hi)
    q = EncodedProblem(
        node_ids=p.node_ids[node_lo:node_hi],
        group_keys=[p.group_keys[int(g)] for g in gsel],
        service_ids=p.service_ids,
        groups=[],
    )
    q.ready = np.ascontiguousarray(p.ready[sl])
    q.node_val = np.ascontiguousarray(p.node_val[sl])
    q.node_plat = np.ascontiguousarray(p.node_plat[sl])
    q.node_plugins = np.ascontiguousarray(p.node_plugins[sl])
    q.port_used0 = np.ascontiguousarray(p.port_used0[sl])
    q.avail_res = np.ascontiguousarray(p.avail_res[sl])
    q.total0 = np.ascontiguousarray(p.total0[sl])
    q.svc_count0 = np.ascontiguousarray(p.svc_count0[:, sl])
    q.n_tasks = p.n_tasks[gsel]
    q.svc_idx = p.svc_idx[gsel]
    q.need_res = p.need_res[gsel]
    q.max_replicas = p.max_replicas[gsel]
    q.constraints = p.constraints[gsel]
    q.plat_req = p.plat_req[gsel]
    q.req_plugins = p.req_plugins[gsel]
    q.has_ports = p.has_ports[gsel]
    q.group_ports = p.group_ports[gsel]
    q.penalty = np.ascontiguousarray(p.penalty[gsel][:, sl])
    q.extra_mask = np.ascontiguousarray(p.extra_mask[gsel][:, sl])
    q.spread_rank = np.ascontiguousarray(
        np.asarray(p.spread_rank)[gsel][:, :, sl])
    return q


def sampled_shard_parity(p, counts: np.ndarray, group_shard: np.ndarray,
                         n_shards: int, sample, log=None) -> list[int]:
    """Bit-parity of `counts` against the greedy oracle on sampled shards.

    `sample`: iterable of shard indices (or an int — that many shards
    picked deterministically, spread across the range). For each sampled
    shard s the oracle re-runs on s's node slice with s's groups, and
    counts[groups_of_s] must (a) equal the oracle inside the slice and
    (b) be identically zero outside it. Returns the shards checked."""
    from ..scheduler.batch import cpu_schedule_encoded

    N = len(p.node_ids)
    per = N // n_shards
    group_shard = np.asarray(group_shard)
    if isinstance(sample, int):
        k = max(1, min(sample, n_shards))
        sample = sorted({int(s) for s in
                         np.linspace(0, n_shards - 1, k).round()})
    checked = []
    for s in sample:
        s = int(s)
        gsel = np.flatnonzero(group_shard == s)
        a, b = s * per, (s + 1) * per
        sub = slice_shard_problem(p, gsel, a, b)
        expected = cpu_schedule_encoded(sub)
        got = counts[gsel]
        outside = got.copy()
        outside[:, a:b] = 0
        assert not outside.any(), \
            f"shard {s}: placements leaked outside the shard's node slice"
        np.testing.assert_array_equal(
            got[:, a:b], expected,
            err_msg=f"shard {s}: kernel fill != greedy oracle on the "
                    f"shard's node slice [{a}, {b})")
        checked.append(s)
        if log is not None:
            log(f"sampled-shard parity ok: shard {s} "
                f"({len(gsel)} groups, {per} nodes, "
                f"{int(expected.sum())} placed)")
    return checked


def check_fill_invariants(p, counts: np.ndarray) -> dict:
    """Vectorized invariant checks on a full fill output — the guardrail
    at sizes where even the sampled oracle covers only a fraction.
    Raises AssertionError on violation; returns summary stats."""
    from ..scheduler.batch import cpu_static_mask

    c = np.asarray(counts, np.int64)
    assert (c >= 0).all(), "negative placement count"
    placed_per_group = c.sum(axis=1)
    assert (placed_per_group <= p.n_tasks.astype(np.int64)).all(), \
        "a group placed more tasks than it has"

    mask = cpu_static_mask(p)
    assert not (c[~mask] > 0).any(), \
        "placement on a statically-ineligible node"

    used = c.T @ p.need_res.astype(np.int64)              # [N, R]
    assert (used <= p.avail_res.astype(np.int64)).all(), \
        "resource capacity overcommitted"

    # max-replicas: final per-service per-node count never exceeds the cap
    svc_final = p.svc_count0.astype(np.int64).copy()
    np.add.at(svc_final, p.svc_idx, c)
    for gi in np.flatnonzero(p.max_replicas > 0):
        assert (svc_final[p.svc_idx[gi]]
                <= int(p.max_replicas[gi])).all(), \
            f"group {gi}: max_replicas cap exceeded"

    # host ports: ≤1 task of a port group per node, never on a node whose
    # port was already in use, and no two groups sharing a port id on the
    # same node
    port_claims = np.zeros(p.port_used0.shape, np.int64)  # [N, PV]
    for gi in np.flatnonzero(p.has_ports):
        assert (c[gi] <= 1).all(), \
            f"port group {gi}: >1 task on one node"
        pids = np.flatnonzero(p.group_ports[gi])
        conflict = p.port_used0[:, pids].any(axis=1)
        assert not (c[gi][conflict] > 0).any(), \
            f"port group {gi}: placed on a node with the port in use"
        port_claims[np.ix_(c[gi] > 0, pids)] += 1
    assert (port_claims <= 1).all(), \
        "two groups claimed the same host port on one node"

    return {
        "placed": int(c.sum()),
        "tasks": int(p.n_tasks.sum()),
        "groups": int(len(p.n_tasks)),
        "nodes": len(p.node_ids),
    }
