"""Manager-peer tracking and selection (reference: remotes/, connectionbroker/)."""
from .remotes import DEFAULT_OBSERVATION_WEIGHT, Remotes
from .broker import ConnectionBroker

__all__ = ["Remotes", "ConnectionBroker", "DEFAULT_OBSERVATION_WEIGHT"]
