"""Connection broker: pick a manager connection, preferring the local one.

Re-derivation of connectionbroker/broker.go (123 ln): `select_conn` returns
the local manager when this process runs one (zero network hop), otherwise a
remote picked through the weighted `Remotes`; callers report the outcome so
weights track health.
"""
from __future__ import annotations

from .remotes import DEFAULT_OBSERVATION_WEIGHT, Remotes


class Conn:
    """A selected peer + the observation plumbing (broker.go Conn)."""

    def __init__(self, broker: "ConnectionBroker", peer, is_local: bool):
        self._broker = broker
        self.peer = peer
        self.is_local = is_local

    def close(self, success: bool = True):
        """broker.go Conn.Close: feed the health observation back."""
        if not self.is_local:
            self._broker.remotes.observe(
                self.peer,
                DEFAULT_OBSERVATION_WEIGHT if success else -DEFAULT_OBSERVATION_WEIGHT,
            )


class ConnectionBroker:
    def __init__(self, remotes: Remotes | None = None, local_peer=None):
        self.remotes = remotes or Remotes()
        self._local = local_peer

    def set_local_peer(self, peer):
        """The embedded manager came up (or went away: None)."""
        self._local = peer

    def select_conn(self, *excluding) -> Conn:
        if self._local is not None and self._local not in set(excluding):
            return Conn(self, self._local, is_local=True)
        return Conn(self, self.remotes.select(*excluding), is_local=False)
