"""Weighted manager-peer picker.

Re-derivation of remotes/remotes.go (589 ln): workers keep a weight per
known manager peer, raised/lowered by observations (successful RPC = +,
failure = −) with EWMA-style decay toward the observation, and `select`
samples proportionally to the positive part of the weights so traffic
spreads but prefers healthy managers.
"""
from __future__ import annotations

import random
import threading
from ..analysis.lockgraph import make_lock

# remotes.go: DefaultObservationWeight = 10; weights clamp to [-128, 128]
DEFAULT_OBSERVATION_WEIGHT = 10
_WEIGHT_MAX = 128.0
_WEIGHT_MIN = -128.0
_EWMA = 0.5  # remoteWeightSmoothingFactor


class NoPeersError(Exception):
    pass


class Remotes:
    """Peers are opaque hashable handles (addresses on the wire transport,
    Manager objects in-process)."""

    def __init__(self, *peers, rng: random.Random | None = None):
        self._lock = make_lock('remotes.remotes.lock')
        self._weights: dict = {}
        self._rng = rng or random.Random()
        for p in peers:
            self._weights[p] = 0.0

    def add(self, *peers):
        with self._lock:
            for p in peers:
                self._weights.setdefault(p, 0.0)

    def remove(self, *peers):
        with self._lock:
            for p in peers:
                self._weights.pop(p, None)

    def weights(self) -> dict:
        with self._lock:
            return dict(self._weights)

    def observe(self, peer, weight: int = DEFAULT_OBSERVATION_WEIGHT):
        """Blend an observation into the peer's weight
        (remotes.go Observe/ObserveIfExists EWMA)."""
        with self._lock:
            if peer not in self._weights:
                self._weights[peer] = 0.0
            cur = self._weights[peer]
            nxt = cur * _EWMA + float(weight) * (1 - _EWMA)
            self._weights[peer] = max(_WEIGHT_MIN, min(_WEIGHT_MAX, nxt))

    def select(self, *excluding):
        """Weighted-random pick (remotes.go Select): weights are shifted so
        the minimum is slightly positive — unhealthy peers stay selectable
        (they may have recovered) but rarely chosen."""
        with self._lock:
            candidates = {
                p: w for p, w in self._weights.items() if p not in set(excluding)
            }
            if not candidates:
                raise NoPeersError("no manager peers available")
            lo = min(candidates.values())
            # shift: minimum weight maps to 1 (remotes.go select index math)
            shifted = {p: (w - lo) + 1.0 for p, w in candidates.items()}
            total = sum(shifted.values())
            pick = self._rng.uniform(0, total)
            acc = 0.0
            for p, w in shifted.items():
                acc += w
                if pick <= acc:
                    return p
            return next(iter(shifted))
