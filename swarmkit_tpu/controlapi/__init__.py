from .control import ControlAPI, ListFilters  # noqa: F401
from .errors import (  # noqa: F401
    AlreadyExists,
    ControlError,
    FailedPrecondition,
    InvalidArgument,
    NotFound,
    PermissionDenied,
    Unimplemented,
)
