"""gRPC-style status errors for the control surface.

The reference returns grpc codes from every Control RPC
(manager/controlapi/*.go, e.g. service.go's
`status.Errorf(codes.InvalidArgument, ...)`); a transport layer maps these
1:1 onto wire status codes.
"""
from __future__ import annotations


class ControlError(Exception):
    code = "unknown"

    def __init__(self, message: str = ""):
        super().__init__(message)
        self.message = message


class InvalidArgument(ControlError):
    code = "invalid_argument"


class NotFound(ControlError):
    code = "not_found"


class AlreadyExists(ControlError):
    code = "already_exists"


class FailedPrecondition(ControlError):
    code = "failed_precondition"


class PermissionDenied(ControlError):
    code = "permission_denied"


class Unimplemented(ControlError):
    code = "unimplemented"
