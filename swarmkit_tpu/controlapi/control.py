"""Control API: the user-facing CRUD + validation surface.

Behavioral re-derivation of manager/controlapi/ (service.go, node.go,
cluster.go, secret.go, config.go, network.go, volume.go, extension.go,
resource.go, task.go): every mutation is validated, version-checked
(ErrSequenceConflict → FailedPrecondition), and written through the store so
it replicates via raft. List calls support the reference's filter set
(names, id prefixes, labels, plus per-type filters).
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

from ..api.objects import (
    Cluster,
    Config,
    Extension,
    Network,
    Node,
    Resource,
    Secret,
    Service,
    Task,
    Version,
    Volume,
)
from ..api.specs import ClusterSpec, ConfigSpec, NetworkSpec, SecretSpec, \
    UpdateConfig, \
    ServiceSpec, VolumeSpec, normalize_nones
from ..api.types import (NodeRole, RestartCondition, ServiceMode,
                         TaskState, UpdateFailureAction, UpdateOrder)
from ..scheduler import constraint as constraint_mod
from ..store import by
from ..store.memory import MemoryStore, SequenceConflict
from ..utils.identity import new_id
from .errors import (
    AlreadyExists,
    FailedPrecondition,
    InvalidArgument,
    NotFound,
    Unimplemented,
)

# Docker object-name grammar (reference: controlapi/service.go validateAnnotations
# via docker/docker restricted name rules).
_NAME_RE = re.compile(r"^[a-zA-Z0-9]+(?:[a-zA-Z0-9-_.]*[a-zA-Z0-9])?$")

# reference: controlapi/secret.go MaxSecretSize = 500KiB;
# config.go caps config data at 1000KiB (MaxConfigSize).
MAX_SECRET_SIZE = 500 * 1024
MAX_CONFIG_SIZE = 1000 * 1024

VALID_PORT_PROTOCOLS = {"tcp", "udp", "sctp"}

# jobs must not deviate from this (service.go validateJob rejects any
# update config; the field is non-optional here)
_DEFAULT_UPDATE_CONFIG = UpdateConfig()


@dataclass
class ListFilters:
    """reference: api/control.proto List*Request.Filters."""

    names: list[str] = field(default_factory=list)
    id_prefixes: list[str] = field(default_factory=list)
    name_prefixes: list[str] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    # per-type extras
    service_ids: list[str] = field(default_factory=list)
    node_ids: list[str] = field(default_factory=list)
    desired_states: list[TaskState] = field(default_factory=list)
    roles: list[NodeRole] = field(default_factory=list)
    memberships: list[int] = field(default_factory=list)
    modes: list[ServiceMode] = field(default_factory=list)
    up_to_date: bool = False


def _match_filters(obj, f: ListFilters | None,
                   annotations=None) -> bool:
    """Name/prefix matching delegates to the by.py selectors so the
    case-folding rules stay single-sourced with the store indexes."""
    if f is None:
        return True
    if f.names and not any(by.ByName(n).match(obj) for n in f.names):
        return False
    if f.name_prefixes and not any(
            by.ByNamePrefix(p).match(obj) for p in f.name_prefixes):
        return False
    if f.id_prefixes and not any(obj.id.startswith(p)
                                 for p in f.id_prefixes):
        return False
    if f.labels:
        ann = annotations if annotations is not None else getattr(
            obj, "spec", obj).annotations
        for k, v in f.labels.items():
            if k not in ann.labels:
                return False
            if v and ann.labels[k] != v:
                return False
    return True


class ControlAPI:
    """The Control service (reference: api/control.proto, ~40 RPCs)."""

    def __init__(self, store: MemoryStore):
        self.store = store


    def _committed(self, obj):
        """Re-read an object after commit: WriteTx buffers copies, so the
        reference we appended pre-commit carries a stale meta.version.
        Returns a COPY — control-surface callers own what they receive."""
        got = self.store.view().get(type(obj), obj.id)
        return got.copy() if got is not None else None

    # ------------------------------------------------------------ validation
    @staticmethod
    def _normalize(spec):
        """Shared wire-boundary prelude for every spec/annotations
        payload: the reference's proto wire cannot carry null in a
        non-pointer field (only omission, which decodes as the zero
        value), but this codec rebuilds dataclasses without field
        checks — fold hand-crafted Nones back to the declared defaults
        so validators and the stored object see proto-shaped data."""
        if spec is None:
            raise InvalidArgument("spec must be provided")
        return normalize_nones(spec)

    @staticmethod
    def _validate_annotations(annotations) -> None:
        if annotations is None:
            raise InvalidArgument("annotations must be provided")
        if not annotations.name:
            raise InvalidArgument("meta: name must be provided")
        if not _NAME_RE.match(annotations.name):
            raise InvalidArgument(
                f"invalid name {annotations.name!r}: must match "
                f"{_NAME_RE.pattern}")

    # minimum schedulable quanta (service.go validateResources:34-50)
    MIN_NANO_CPUS = 1_000_000           # 0.001 of a core
    MIN_MEMORY_BYTES = 4 * 1024 * 1024  # 4 MiB

    @classmethod
    def _validate_resources(cls, r, what: str) -> None:
        """service.go validateResources — a nonzero request below the
        schedulable quantum can never be satisfied sensibly."""
        if r is None:
            return
        nano = cls._num(r.nano_cpus, f"cpu value in {what}")
        if nano != 0 and nano < cls.MIN_NANO_CPUS:
            raise InvalidArgument(
                f"invalid cpu value in {what}: must be at least "
                f"{cls.MIN_NANO_CPUS / 1e9:g} cores")
        mem = cls._num(r.memory_bytes, f"memory value in {what}")
        if mem != 0 and mem < cls.MIN_MEMORY_BYTES:
            raise InvalidArgument(
                f"invalid memory value in {what}: must be at least 4MiB")
        if r.generic is not None and not isinstance(r.generic, dict):
            raise InvalidArgument(
                f"generic resources in {what} must be a mapping")
        for kind, qty in (r.generic or {}).items():
            if cls._num(qty, f"generic resource {kind!r} in {what}") < 0:
                raise InvalidArgument(
                    f"invalid generic resource {kind!r} in {what}: "
                    "quantity must be non-negative")

    @staticmethod
    def _num(v, what):
        """The wire codec rebuilds dataclasses without field type checks,
        so a hand-crafted payload can put a str (or anything) where a
        number belongs; comparing it would crash the handler with
        TypeError instead of rejecting the spec. NaN is rejected too —
        it compares False against every bound and would smuggle an
        unreconcilable value into the control loops."""
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not math.isfinite(v):
            raise InvalidArgument(f"{what} must be a number, not {v!r}")
        return v

    @classmethod
    def _count(cls, v, what):
        """Count fields (replicas, parallelism, attempts, ...) are proto
        uints: integers only — replicas=2.5 silently scaling to 3 tasks
        is a spec error, not an interpretation choice."""
        if cls._num(v, what) != int(v):
            raise InvalidArgument(f"{what} must be an integer, not {v!r}")
        return int(v)

    @classmethod
    def _validate_restart_policy(cls, rp) -> None:
        """service.go validateRestartPolicy:62-88."""
        if rp is None:
            return
        # same hand-crafted-payload concern for the enum field: later
        # checks dereference .value and would crash the handler
        if not isinstance(rp.condition, RestartCondition):
            raise InvalidArgument(
                f"invalid restart condition {rp.condition!r}")
        if cls._num(rp.delay, "restart-delay") < 0:
            raise InvalidArgument("restart-delay cannot be negative")
        if cls._num(rp.window, "restart-window") < 0:
            raise InvalidArgument("restart-window cannot be negative")
        if cls._count(rp.max_attempts, "restart-max-attempts") < 0:
            raise InvalidArgument("restart-max-attempts cannot be negative")

    @classmethod
    def _validate_update_config(cls, cfg, what: str) -> None:
        """service.go validateUpdate:98-122."""
        if cfg is None:
            return
        if not isinstance(cfg.failure_action, UpdateFailureAction):
            raise InvalidArgument(
                f"invalid {what} failure action {cfg.failure_action!r}")
        if not isinstance(cfg.order, UpdateOrder):
            raise InvalidArgument(f"invalid {what} order {cfg.order!r}")
        if cls._num(cfg.delay, f"{what}-delay") < 0:
            raise InvalidArgument(f"{what}-delay cannot be negative")
        if cls._num(cfg.monitor, f"{what}-monitor") < 0:
            raise InvalidArgument(f"{what}-monitor cannot be negative")
        if not 0 <= cls._num(cfg.max_failure_ratio,
                             f"{what}-maxfailureratio") <= 1:
            raise InvalidArgument(
                f"{what}-maxfailureratio cannot be less than 0 or bigger "
                "than 1")
        if cls._num(cfg.parallelism, f"{what}-parallelism") < 0:
            raise InvalidArgument(f"{what}-parallelism cannot be negative")

    @classmethod
    def _validate_endpoint_spec(cls, ep) -> None:
        """service.go validateEndpointSpec:316-355: DNSRR cannot publish
        through the routing mesh, and two ports may not claim the same
        (published port, protocol). Ports are proto uint32s bounded by
        the TCP port space — range-check them here or garbage flows into
        the allocator's published-port bookkeeping as 'valid'."""
        seen: set[tuple[int, str]] = set()
        for p in ep.ports:
            if p.protocol and p.protocol not in VALID_PORT_PROTOCOLS:
                raise InvalidArgument(f"invalid protocol {p.protocol!r}")
            if not 1 <= cls._count(p.target_port, "target_port") <= 65535:
                raise InvalidArgument(
                    "port config must include a target_port in 1-65535")
            if not 0 <= cls._count(p.published_port,
                                   "published_port") <= 65535:
                raise InvalidArgument(
                    "published_port must be in 0-65535 (0 = dynamic)")
            if p.publish_mode not in ("ingress", "host"):
                raise InvalidArgument(
                    f"invalid publish mode {p.publish_mode!r}")
            if ep.mode == "dnsrr" and p.publish_mode == "ingress":
                raise InvalidArgument(
                    "port published with ingress mode can't be used with "
                    "dnsrr mode")
            if p.published_port == 0:
                continue
            key = (p.published_port, p.protocol or "tcp")
            if key in seen:
                raise InvalidArgument(
                    "duplicate published ports provided")
            seen.add(key)

    @staticmethod
    def _validate_refs(refs, kind: str) -> None:
        """service.go validateSecretRefsSpec/validateConfigRefsSpec: ids,
        names, and targets are mandatory; file targets must be unique."""
        targets: dict[str, str] = {}
        for ref in refs:
            rid = getattr(ref, f"{kind}_id")
            rname = getattr(ref, f"{kind}_name")
            if not rid or not rname:
                raise InvalidArgument(f"malformed {kind} reference")
            if not ref.target:
                raise InvalidArgument(
                    f"malformed {kind} reference, no target provided")
            prev = targets.get(ref.target)
            if prev is not None:
                raise InvalidArgument(
                    f"{kind} references {prev!r} and {rname!r} have a "
                    f"conflicting target: {ref.target!r}")
            targets[ref.target] = rname

    @staticmethod
    def _validate_mounts(mounts) -> None:
        """service.go validateMounts:177-188: targets are mandatory and
        absolute (the sandbox mount namespace has no working directory)."""
        for m in mounts:
            if not m.target:
                raise InvalidArgument("mount target must be provided")
            if not m.target.startswith("/"):
                raise InvalidArgument(
                    f"mount target {m.target!r} must be an absolute path")

    def _validate_service_spec(self, tx, spec: ServiceSpec) -> None:
        """The create/update-time catalogue, mirroring
        controlapi/service.go validateServiceSpec + the Server-side
        existence/conflict checks (:527-726)."""
        if spec is None:
            raise InvalidArgument("spec must be provided")
        self._validate_annotations(spec.annotations)
        # placement constraints must parse (service.go validateTaskSpec)
        exprs = spec.task.placement.constraints
        if exprs:
            try:
                constraint_mod.parse(exprs)
            except constraint_mod.InvalidConstraint as e:
                raise InvalidArgument(f"invalid placement constraint: {e}")
        if self._count(spec.task.placement.max_replicas,
                       "max-replicas") < 0:
            raise InvalidArgument("max-replicas cannot be negative")
        res = spec.task.resources
        self._validate_resources(res.reservations, "reservations")
        self._validate_resources(res.limits, "limits")
        self._validate_restart_policy(spec.task.restart)
        if not isinstance(spec.mode, ServiceMode):
            raise InvalidArgument(f"invalid service mode {spec.mode!r}")
        if spec.mode == ServiceMode.REPLICATED \
                and self._count(spec.replicas, "replicas") < 0:
            raise InvalidArgument("replicas must be non-negative")
        if spec.mode == ServiceMode.REPLICATED_JOB:
            # service.go validateMode: blind int casts must not smuggle
            # huge values in as negatives
            if self._count(spec.job.max_concurrent,
                           "maximum concurrent jobs") < 0:
                raise InvalidArgument(
                    "maximum concurrent jobs must not be negative")
            if self._count(spec.job.total_completions,
                           "total completed jobs") < 0:
                raise InvalidArgument(
                    "total completed jobs must not be negative")
        if spec.mode in (ServiceMode.REPLICATED_JOB, ServiceMode.GLOBAL_JOB):
            # reference: service.go validateJob — a job task must stay
            # finished, so restart-on-success is invalid regardless of any
            # update config
            if spec.task.restart.condition.value == "any":
                raise InvalidArgument(
                    "jobs may not restart on success; use restart-condition "
                    "none or on-failure")
            # jobs may not carry an update config (service.go validateJob);
            # UpdateConfig is a non-optional field here, so 'carrying one'
            # means deviating from the defaults
            if spec.update != _DEFAULT_UPDATE_CONFIG:
                raise InvalidArgument("jobs may not have an update config")
        self._validate_endpoint_spec(spec.endpoint)
        self._validate_update_config(spec.update, "update")
        self._validate_update_config(spec.rollback, "rollback")
        # referenced secrets/configs/networks must exist; refs well-formed
        runtime = spec.task.runtime
        if runtime is not None:
            self._validate_refs(runtime.secrets, "secret")
            self._validate_refs(runtime.configs, "config")
            self._validate_mounts(getattr(runtime, "mounts", []) or [])
            # templated fields must parse at create time (service.go:128
            # validateTaskSpec → template errors reject the spec); bad
            # templates otherwise surface only as per-task REJECTED at the
            # worker, silently from the operator's seat
            if hasattr(runtime, "env"):
                from ..template.context import (
                    TemplateError,
                    validate_container_spec_templates,
                )

                try:
                    validate_container_spec_templates(runtime)
                except TemplateError as e:
                    raise InvalidArgument(f"invalid template: {e}")
            for ref in runtime.secrets:
                if tx.get_secret(ref.secret_id) is None:
                    raise InvalidArgument(
                        f"secret {ref.secret_id} not found")
            for ref in runtime.configs:
                if tx.get_config(ref.config_id) is None:
                    raise InvalidArgument(
                        f"config {ref.config_id} not found")
        for na in spec.task.networks + spec.networks:
            if na.target:
                net = tx.get_network(na.target)
                if net is None:
                    raise InvalidArgument(f"network {na.target} not found")
                if net.spec.ingress:
                    # service.go validateNetworks:468-483
                    raise InvalidArgument(
                        "service cannot be explicitly attached to the "
                        f"ingress network {net.spec.annotations.name!r}")

    def _check_port_conflicts(self, tx, spec: ServiceSpec,
                              service_id: str | None) -> None:
        """service.go checkPortConflicts:570-664: an ingress-published
        (port, protocol) must be cluster-unique; host-published ports may
        collide with each other (the scheduler spreads them) but not with
        an ingress port."""
        mine = [(p.published_port, p.protocol or "tcp", p.publish_mode)
                for p in spec.endpoint.ports if p.published_port != 0]
        if not mine:
            return
        my_ingress = {(pp, pr) for pp, pr, m in mine if m == "ingress"}
        my_host = {(pp, pr) for pp, pr, m in mine if m == "host"}
        for svc in tx.find_services():
            if service_id is not None and svc.id == service_id:
                continue
            # both the spec's ports AND the allocator-materialized endpoint
            # ports count (service.go:644-660): a dynamically assigned
            # ingress port lives only on svc.endpoint
            theirs = [(p.published_port, p.protocol or "tcp",
                       p.publish_mode) for p in svc.spec.endpoint.ports]
            theirs += [(pp, proto or "tcp", mode)
                       for (proto, _tp, pp, mode)
                       in (svc.endpoint or {}).get("ports", [])]
            for pp, proto, mode in theirs:
                if pp == 0:
                    continue
                key = (pp, proto)
                if mode == "ingress":
                    if key in my_ingress or key in my_host:
                        raise InvalidArgument(
                            f"port '{key[0]}' is already in use by service "
                            f"'{svc.spec.annotations.name}' ({svc.id}) as "
                            "an ingress port")
                elif key in my_ingress:
                    raise InvalidArgument(
                        f"port '{key[0]}' is already in use by service "
                        f"'{svc.spec.annotations.name}' ({svc.id}) as a "
                        "host-published port")

    # -------------------------------------------------------------- services
    def create_service(self, spec: ServiceSpec) -> Service:
        from ..api.defaults import merge_service_defaults

        spec = self._normalize(spec)
        merge_service_defaults(spec)
        svc = Service(id=new_id(), spec=spec)
        svc.spec_version = Version(1)

        def cb(tx):
            self._validate_service_spec(tx, spec)
            self._check_port_conflicts(tx, spec, None)
            if tx.find_services(by.ByName(spec.annotations.name)):
                raise AlreadyExists(
                    f"service {spec.annotations.name} already exists")
            tx.create(svc)

        self.store.update(cb)
        return self.store.view().get_service(svc.id).copy()

    def get_service(self, service_id: str) -> Service:
        s = self.store.view().get_service(service_id)
        if s is None or s.pending_delete:
            raise NotFound(f"service {service_id} not found")
        return s.copy()

    def update_service(self, service_id: str, version: Version,
                       spec: ServiceSpec, rollback: bool = False) -> Service:
        """reference: service.go UpdateService — version-gated, saves
        previous_spec for rollback, forbids renames and mode changes."""
        spec = self._normalize(spec)
        out: list[Service] = []

        def cb(tx):
            cur = tx.get_service(service_id)
            if cur is None or cur.pending_delete:
                raise NotFound(f"service {service_id} not found")
            self._validate_service_spec(tx, spec)
            # conflicts are checked only when the endpoint spec actually
            # changes (service.go:837 DeepEqual guard): pre-validation
            # state restored from an old WAL must stay updatable
            if spec.endpoint != cur.spec.endpoint:
                self._check_port_conflicts(tx, spec, service_id)
            if cur.meta.version.index != version.index:
                raise FailedPrecondition("update out of sequence")
            if spec.annotations.name != cur.spec.annotations.name:
                raise InvalidArgument("renaming services is not supported")
            if spec.mode != cur.spec.mode:
                raise InvalidArgument("service mode change is not supported")
            # service.go UpdateService:849-857: changing the deprecated
            # spec.networks alone (full attachment configs, not just
            # targets) is unsupported — unless task.networks is being
            # updated in the same request (a migration to it)
            if not rollback \
                    and (spec.networks or cur.spec.networks) \
                    and spec.networks != cur.spec.networks \
                    and spec.task.networks == cur.spec.task.networks:
                raise Unimplemented(
                    "changing network in service is not supported")
            nxt = cur.copy()
            if rollback:
                if cur.previous_spec is None:
                    raise FailedPrecondition("service has no previous spec")
                nxt.spec = cur.previous_spec
                nxt.previous_spec = None
                # manual rollback both unblocks a paused update and records
                # why the spec flipped (service.go UpdateService:903-907)
                import time as _time

                from ..api.types import UpdateStatusState

                nxt.update_status = {
                    "state": UpdateStatusState.ROLLBACK_STARTED.value,
                    "message": "manually requested rollback",
                    "timestamp": _time.time(),
                }
            else:
                nxt.previous_spec = cur.spec
                nxt.previous_spec_version = Version(cur.spec_version.index)
                nxt.spec = spec
                # a fresh spec resets any paused/completed update status so
                # the updater may run again (service.go UpdateService:919)
                nxt.update_status = None
            nxt.spec_version = Version(cur.spec_version.index + 1)
            tx.update(nxt)
            out.append(nxt)

        try:
            self.store.update(cb)
        except SequenceConflict:
            raise FailedPrecondition("update out of sequence")
        return self._committed(out[0])

    def remove_service(self, service_id: str) -> None:
        """Removal is deferred while tasks exist: the service is marked
        pending_delete (hidden from get/list), the orchestrator winds its
        tasks down, and the deallocator deletes the record once the last
        task is gone (manager/deallocator/deallocator.go — 'the only place
        services are ever deleted'). A service with no tasks left is
        deleted immediately."""

        def cb(tx):
            s = tx.get_service(service_id)
            if s is None or s.pending_delete:
                raise NotFound(f"service {service_id} not found")
            if not tx.find_tasks(by.ByServiceID(service_id)):
                tx.delete(Service, service_id)
                return
            s = s.copy()
            s.pending_delete = True
            tx.update(s)

        self.store.update(cb)

    def list_services(self, filters: ListFilters | None = None) -> list[Service]:
        out = []
        for s in self.store.view().find_services():
            if s.pending_delete:
                continue  # removal in progress: hidden from the surface
            if not _match_filters(s, filters):
                continue
            if filters and filters.modes and s.spec.mode not in filters.modes:
                continue
            out.append(s.copy())
        return out

    # ----------------------------------------------------------------- tasks
    def get_task(self, task_id: str) -> Task:
        t = self.store.view().get_task(task_id)
        if t is None:
            raise NotFound(f"task {task_id} not found")
        return t.copy()

    def remove_task(self, task_id: str) -> None:
        def cb(tx):
            if tx.get_task(task_id) is None:
                raise NotFound(f"task {task_id} not found")
            tx.delete(Task, task_id)

        self.store.update(cb)

    def list_tasks(self, filters: ListFilters | None = None) -> list[Task]:
        out = []
        for t in self.store.view().find_tasks():
            if not _match_filters(t, filters, annotations=t.annotations):
                continue
            if filters:
                if filters.service_ids and t.service_id not in filters.service_ids:
                    continue
                if filters.node_ids and t.node_id not in filters.node_ids:
                    continue
                if filters.desired_states and \
                        t.desired_state not in filters.desired_states:
                    continue
                if filters.up_to_date:
                    svc = self.store.view().get_service(t.service_id)
                    if svc is not None and t.spec_version is not None and \
                            t.spec_version.index != svc.spec_version.index:
                        continue
            out.append(t.copy())
        return out

    # --------------------------------------------------- lifecycle/SLO plane
    def get_task_timeline(self, task_id: str) -> list:
        """This task's lifecycle timeline [(stage, t), ...] from the
        armed recorder; [] when disarmed or untracked. Auto-exposed as
        `control.get_task_timeline` with leader forwarding — the
        recorder populates on the leader (where the orchestrator/
        scheduler/dispatcher write sites run), so a remote client always
        reads the authoritative copy."""
        from ..utils import lifecycle

        r = lifecycle.recorder()
        return r.timeline(task_id) if r is not None else []

    def get_slo_report(self, since: float | None = None) -> dict:
        """Cluster task-SLO snapshot for remote clients (swarmbench
        --slo attribution, operator tooling): startup percentiles +
        stage-attribution from the leader's lifecycle recorder. `since`
        (wall-clock seconds) restricts to tasks whose RUNNING landed in
        the trailing window — the recovery-SLO read."""
        from ..utils import lifecycle, slo

        return slo.report(lifecycle.recorder(), since=since)

    def get_cluster_telemetry(self, window: float | None = None,
                              include_local: bool = True) -> dict:
        """Cluster telemetry rollup (ISSUE 15): merged node metric
        snapshots + manager-local families + per-node freshness from
        the leader's TelemetryAggregator (the aggregator registers on
        the LEADER — this method is auto-exposed as
        `control.get_cluster_telemetry` with leader forwarding, so a
        remote client always reads the authoritative rollup). `window`
        adds nearest-rank percentile queries over the trailing window
        of the time-series ring; `{"armed": False}` when the plane is
        down or this manager holds no aggregator."""
        from ..utils import telemetry

        agg = telemetry.aggregator()
        if agg is None:
            return {"armed": False, "aggregator": False}
        return agg.rollup(window_s=window, include_local=include_local)

    # ----------------------------------------------------------------- nodes
    def get_node(self, node_id: str) -> Node:
        n = self.store.view().get_node(node_id)
        if n is None:
            raise NotFound(f"node {node_id} not found")
        return n.copy()

    def list_nodes(self, filters: ListFilters | None = None) -> list[Node]:
        out = []
        for n in self.store.view().find_nodes():
            if not _match_filters(n, filters):
                continue
            if filters:
                if filters.roles and n.spec.desired_role not in filters.roles:
                    continue
                if filters.memberships and \
                        n.spec.membership not in filters.memberships:
                    continue
            out.append(n.copy())
        return out

    def update_node(self, node_id: str, version: Version, spec) -> Node:
        """Availability / label / role changes. Demotion safety mirrors
        controlapi/node.go: the last manager cannot be demoted."""
        spec = self._normalize(spec)
        out: list[Node] = []

        def cb(tx):
            cur = tx.get_node(node_id)
            if cur is None:
                raise NotFound(f"node {node_id} not found")
            if cur.meta.version.index != version.index:
                raise FailedPrecondition("update out of sequence")
            if (cur.spec.desired_role == NodeRole.MANAGER
                    and spec.desired_role == NodeRole.WORKER):
                managers = [n for n in tx.find_nodes()
                            if n.spec.desired_role == NodeRole.MANAGER]
                if len(managers) <= 1:
                    raise FailedPrecondition(
                        "attempting to demote the last manager of the swarm")
            nxt = cur.copy()
            nxt.spec = spec
            tx.update(nxt)
            out.append(nxt)

        try:
            self.store.update(cb)
        except SequenceConflict:
            raise FailedPrecondition("update out of sequence")
        return self._committed(out[0])

    def remove_node(self, node_id: str, force: bool = False) -> None:
        """reference: node.go RemoveNode — managers and live nodes need
        force/demotion first."""
        def cb(tx):
            n = tx.get_node(node_id)
            if n is None:
                raise NotFound(f"node {node_id} not found")
            if n.spec.desired_role == NodeRole.MANAGER:
                raise FailedPrecondition(
                    "node is a manager; demote it before removal")
            from ..api.types import NodeStatusState
            if not force and n.status.state == NodeStatusState.READY:
                raise FailedPrecondition(
                    "node is not down and can't be removed; use force")
            tx.delete(Node, node_id)

        self.store.update(cb)

    # --------------------------------------------------------------- cluster
    @staticmethod
    def _redact_cluster(c: Cluster) -> Cluster:
        """Strip private key material before returning a cluster (reference:
        controlapi/cluster.go redactClusters — CA signing key and unlock
        keys never leave the manager; join tokens are part of the API).
        The sanctioned unlock-key read is `get_unlock_key`."""
        c = c.copy()
        c.unlock_keys = []
        if isinstance(c.root_ca, dict):
            c.root_ca.pop("ca_key", None)
            c.root_ca.pop("unlock_key", None)
        elif c.root_ca is not None:
            c.root_ca.ca_key_pem = b""
            if c.root_ca.root_rotation:
                rot = dict(c.root_ca.root_rotation)
                rot.pop("new_ca_key_pem", None)
                c.root_ca.root_rotation = rot
        if getattr(c.spec.ca, "signing_ca_key", b""):
            # operator-supplied signing key is as sensitive as the root key;
            # update_cluster restores it from the stored spec when the same
            # signing cert comes back key-less (redacted round-trip)
            c.spec.ca.signing_ca_key = b""
        return c

    def get_cluster(self, cluster_id: str) -> Cluster:
        c = self.store.view().get_cluster(cluster_id)
        if c is None:
            raise NotFound(f"cluster {cluster_id} not found")
        return self._redact_cluster(c)

    def list_clusters(self, filters: ListFilters | None = None) -> list[Cluster]:
        return [self._redact_cluster(c)
                for c in self.store.view().find_clusters()
                if _match_filters(c, filters)]

    def get_unlock_key(self, cluster_id: str) -> str:
        """reference: ca.proto GetUnlockKey — the one sanctioned way to read
        the autolock key after rotation."""
        c = self.store.view().get_cluster(cluster_id)
        if c is None:
            raise NotFound(f"cluster {cluster_id} not found")
        if c.unlock_keys:
            key = c.unlock_keys[0]
            return key.decode() if isinstance(key, bytes) else str(key)
        if isinstance(c.root_ca, dict):   # legacy shape
            return c.root_ca.get("unlock_key", "")
        return ""

    @staticmethod
    def _validate_ca_config(cur, spec: ClusterSpec) -> None:
        """reference controlapi/ca_rotation.go validateCAConfig:190-302:
        external-CA URL/protocol validation, signing cert/key pairing and
        match, cert-without-key must name an external CA for that root."""
        import urllib.parse

        from ..ca import RootCA

        cfg = spec.ca
        # tolerate redacted round-trips FIRST (reference validateCAConfig
        # does the same): an unchanged signing cert arriving key-less —
        # list/inspect strip the key — reuses the stored key
        if cfg.signing_ca_cert and not cfg.signing_ca_key \
                and cfg.signing_ca_cert == cur.spec.ca.signing_ca_cert \
                and cur.spec.ca.signing_ca_key:
            cfg.signing_ca_key = cur.spec.ca.signing_ca_key
        if cfg.signing_ca_key and not cfg.signing_ca_cert:
            raise InvalidArgument(
                "if a signing CA key is provided, the signing CA cert must "
                "also be provided")
        for ext in cfg.external_cas:
            proto = (ext.get("protocol") or "cfssl") \
                if isinstance(ext, dict) else None
            if proto != "cfssl":
                raise InvalidArgument(
                    f"unknown external CA protocol {proto!r}")
            url = ext.get("url", "")
            parsed = urllib.parse.urlparse(url)
            if parsed.scheme != "https" or not parsed.netloc:
                raise InvalidArgument(
                    f"invalid HTTPS URL for external CA: {url!r}")
            ca_cert = ext.get("ca_cert")
            if ca_cert:
                try:
                    RootCA(ca_cert if isinstance(ca_cert, bytes)
                           else ca_cert.encode())
                except Exception:
                    raise InvalidArgument(
                        "external CA entry carries an unparseable CA "
                        "certificate")
        if cfg.signing_ca_cert:
            try:
                desired = RootCA(cfg.signing_ca_cert,
                                 cfg.signing_ca_key or None)
            except Exception:
                raise InvalidArgument(
                    "signing CA cert/key material is not valid PEM")
            if cfg.signing_ca_key:
                if not desired.key_matches_cert():
                    raise InvalidArgument(
                        "signing CA cert does not match the signing CA key")
            else:
                norm = cfg.signing_ca_cert.strip()
                ext_certs = []
                for ext in cfg.external_cas:
                    c = ext.get("ca_cert") or b""
                    if isinstance(c, str):
                        c = c.encode()
                    ext_certs.append(c.strip())
                if norm not in ext_certs:
                    raise InvalidArgument(
                        "a signing CA cert without a key requires an "
                        "external CA entry for that certificate")

    @staticmethod
    def _maybe_kick_ca_rotation(cur, nxt) -> None:
        """Begin a phased root rotation when the CAConfig asks for one
        (reference ca_rotation.go newRootRotationObject:190-302 via
        UpdateCluster): a bumped ForceRotate counter rotates to a freshly
        generated root; a new signing cert(+key) rotates to that root. The
        record written here is the SAME one `CAServer.rotate_root_ca`
        writes — the CA server's reconciler drives it to completion
        (nodes re-CSR under the new epoch) with no further control-API
        involvement."""
        from ..ca import RootCA
        from ..ca.certificates import parse_cert_identity

        cfg = nxt.spec.ca
        cur_cfg = cur.spec.ca
        rca = nxt.root_ca
        force = cfg.force_rotate != cur_cfg.force_rotate
        in_flight = b""
        if rca is not None and rca.root_rotation:
            in_flight = rca.root_rotation.get("new_ca_cert_pem", b"")
        want_cert = cfg.signing_ca_cert
        # a rotation is OPERATOR INTENT, not spec residue: the signing cert
        # only triggers when it CHANGED in this update (or rides a
        # force-rotate bump). A stale signing_ca_cert left in the spec from
        # a completed rotation must not silently re-kick one on the next
        # unrelated update (e.g. token rotation round-tripping the spec).
        cert_changed = bool(want_cert) \
            and want_cert.strip() != cur_cfg.signing_ca_cert.strip()
        cert_is_new = bool(want_cert) and rca is not None \
            and want_cert.strip() != rca.ca_cert_pem.strip() \
            and want_cert.strip() != in_flight.strip()
        cert_rotation = cert_is_new and (cert_changed or force)
        if not (force or cert_rotation):
            return
        if rca is None or not rca.ca_cert_pem:
            raise FailedPrecondition("cluster has no root CA to rotate")
        old = RootCA(rca.ca_cert_pem, rca.ca_key_pem or None)
        if not old.can_sign:
            raise FailedPrecondition(
                "current root key is unavailable (externally held); "
                "cross-signing the new root requires it")
        if cert_rotation:
            new_root = RootCA(want_cert, cfg.signing_ca_key or None)
        else:
            if force and want_cert and not cert_is_new:
                # force-rotate with the CURRENT root as signing cert: the
                # operator asked for fresh material, drop the stale pin so
                # later updates can't read it as intent
                cfg.signing_ca_cert = b""
                cfg.signing_ca_key = b""
            try:
                org = parse_cert_identity(rca.ca_cert_pem).org
            except Exception:
                org = "swarmkit-tpu"
            new_root = RootCA.create(org or "swarmkit-tpu")
        cross = old.cross_sign(new_root)
        rca.root_rotation = {
            "new_ca_cert_pem": new_root.cert_pem,
            "new_ca_key_pem": new_root.key_pem or b"",
            "cross_signed_pem": cross,
        }
        rca.last_forced_rotation += 1

    def update_cluster(self, cluster_id: str, version: Version,
                       spec: ClusterSpec,
                       rotate_worker_token: bool = False,
                       rotate_manager_token: bool = False,
                       rotate_unlock_key: bool = False) -> Cluster:
        """reference: cluster.go UpdateCluster — spec swap + token rotation
        + CAConfig-driven root rotation (ca_rotation.go)."""
        spec = self._normalize(spec)
        out: list[Cluster] = []

        def cb(tx):
            cur = tx.get_cluster(cluster_id)
            if cur is None:
                raise NotFound(f"cluster {cluster_id} not found")
            if cur.meta.version.index != version.index:
                raise FailedPrecondition("update out of sequence")
            self._validate_ca_config(cur, spec)
            nxt = cur.copy()
            nxt.spec = spec
            self._maybe_kick_ca_rotation(cur, nxt)
            # token rotation mints REAL digest-pinned join tokens against
            # the cluster's root (cluster.go UpdateCluster rotation; a
            # token that doesn't pin the root digest would be rejected by
            # the CA's _role_from_token)
            rca = nxt.root_ca
            if (rotate_worker_token or rotate_manager_token) \
                    and (rca is None or not rca.ca_cert_pem):
                raise FailedPrecondition("cluster has no CA to pin tokens to")
            if rotate_worker_token or rotate_manager_token:
                from ..ca import RootCA
                from ..ca.config import generate_join_token

                root = RootCA(rca.ca_cert_pem)
                if rotate_worker_token:
                    rca.join_token_worker = generate_join_token(
                        root, fips=nxt.fips)
                if rotate_manager_token:
                    rca.join_token_manager = generate_join_token(
                        root, fips=nxt.fips)
            if rotate_unlock_key:
                import secrets as _secrets

                nxt.unlock_keys = [_secrets.token_hex(16).encode()]
            tx.update(nxt)
            out.append(nxt)

        try:
            self.store.update(cb)
        except SequenceConflict:
            raise FailedPrecondition("update out of sequence")
        return self._redact_cluster(self._committed(out[0]))

    # --------------------------------------------------------------- secrets
    def create_secret(self, spec: SecretSpec) -> Secret:
        spec = self._normalize(spec)
        self._validate_annotations(spec.annotations)
        if spec.driver is None and (
                not spec.data or len(spec.data) > MAX_SECRET_SIZE):
            raise InvalidArgument(
                f"secret data must be 1 - {MAX_SECRET_SIZE} bytes")
        sec = Secret(id=new_id(), spec=spec)

        def cb(tx):
            if tx.find_secrets(by.ByName(spec.annotations.name)):
                raise AlreadyExists(
                    f"secret {spec.annotations.name} already exists")
            tx.create(sec)

        self.store.update(cb)
        return self.store.view().get_secret(sec.id).copy()

    def get_secret(self, secret_id: str, clear_data: bool = True) -> Secret:
        s = self.store.view().get_secret(secret_id)
        if s is None:
            raise NotFound(f"secret {secret_id} not found")
        s = s.copy()
        if clear_data:
            # reference: secret.go GetSecret strips data on the read path
            s.spec.data = b""
        return s

    def update_secret(self, secret_id: str, version: Version,
                      spec: SecretSpec) -> Secret:
        """Only labels may change (reference: secret.go UpdateSecret)."""
        spec = self._normalize(spec)
        out: list[Secret] = []

        def cb(tx):
            cur = tx.get_secret(secret_id)
            if cur is None:
                raise NotFound(f"secret {secret_id} not found")
            if cur.meta.version.index != version.index:
                raise FailedPrecondition("update out of sequence")
            if spec.annotations.name != cur.spec.annotations.name or (
                    spec.data and spec.data != cur.spec.data):
                raise InvalidArgument(
                    "only updates to labels are allowed")
            nxt = cur.copy()
            nxt.spec.annotations.labels = dict(spec.annotations.labels)
            tx.update(nxt)
            out.append(nxt)

        self.store.update(cb)
        return self._committed(out[0])

    def remove_secret(self, secret_id: str) -> None:
        """Fails while any service references the secret."""
        def cb(tx):
            s = tx.get_secret(secret_id)
            if s is None:
                raise NotFound(f"secret {secret_id} not found")
            users = tx.find_services(by.ByReferencedSecretID(secret_id))
            if users:
                names = ", ".join(sorted(
                    u.spec.annotations.name for u in users)[:5])
                raise InvalidArgument(
                    f"secret is in use by services: {names}")
            tx.delete(Secret, secret_id)

        self.store.update(cb)

    def list_secrets(self, filters: ListFilters | None = None) -> list[Secret]:
        out = []
        for s in self.store.view().find_secrets():
            if _match_filters(s, filters):
                s = s.copy()
                s.spec.data = b""
                out.append(s)
        return out

    # --------------------------------------------------------------- configs
    def create_config(self, spec: ConfigSpec) -> Config:
        spec = self._normalize(spec)
        self._validate_annotations(spec.annotations)
        if not spec.data or len(spec.data) > MAX_CONFIG_SIZE:
            raise InvalidArgument(
                f"config data must be 1 - {MAX_CONFIG_SIZE} bytes")
        cfg = Config(id=new_id(), spec=spec)

        def cb(tx):
            if tx.find_configs(by.ByName(spec.annotations.name)):
                raise AlreadyExists(
                    f"config {spec.annotations.name} already exists")
            tx.create(cfg)

        self.store.update(cb)
        return self.store.view().get_config(cfg.id).copy()

    def get_config(self, config_id: str) -> Config:
        c = self.store.view().get_config(config_id)
        if c is None:
            raise NotFound(f"config {config_id} not found")
        return c.copy()

    def update_config(self, config_id: str, version: Version,
                      spec: ConfigSpec) -> Config:
        spec = self._normalize(spec)
        out: list[Config] = []

        def cb(tx):
            cur = tx.get_config(config_id)
            if cur is None:
                raise NotFound(f"config {config_id} not found")
            if cur.meta.version.index != version.index:
                raise FailedPrecondition("update out of sequence")
            if spec.annotations.name != cur.spec.annotations.name or (
                    spec.data and spec.data != cur.spec.data):
                raise InvalidArgument("only updates to labels are allowed")
            nxt = cur.copy()
            nxt.spec.annotations.labels = dict(spec.annotations.labels)
            tx.update(nxt)
            out.append(nxt)

        self.store.update(cb)
        return self._committed(out[0])

    def remove_config(self, config_id: str) -> None:
        def cb(tx):
            c = tx.get_config(config_id)
            if c is None:
                raise NotFound(f"config {config_id} not found")
            users = tx.find_services(by.ByReferencedConfigID(config_id))
            if users:
                names = ", ".join(sorted(
                    u.spec.annotations.name for u in users)[:5])
                raise InvalidArgument(
                    f"config is in use by services: {names}")
            tx.delete(Config, config_id)

        self.store.update(cb)

    def list_configs(self, filters: ListFilters | None = None) -> list[Config]:
        return [c.copy() for c in self.store.view().find_configs()
                if _match_filters(c, filters)]

    # -------------------------------------------------------------- networks
    def create_network(self, spec: NetworkSpec) -> Network:
        spec = self._normalize(spec)
        self._validate_annotations(spec.annotations)
        # reject bad operator subnets at the API so the failure is visible
        # immediately, not a background allocator warning (the reference
        # validates IPAM pools at create time too)
        wanted = (spec.ipam or {}).get("subnet") if spec.ipam else None
        if wanted:
            from ..allocator.ipam import IPAMError, validate_subnet

            try:
                validate_subnet(wanted)
            except IPAMError as exc:
                raise InvalidArgument(str(exc))
        net = Network(id=new_id(), spec=spec)

        def cb(tx):
            if tx.find_networks(by.ByName(spec.annotations.name)):
                raise AlreadyExists(
                    f"network {spec.annotations.name} already exists")
            if spec.ingress and any(
                    n.spec.ingress for n in tx.find_networks()):
                raise AlreadyExists("ingress network already exists")
            tx.create(net)

        self.store.update(cb)
        return self.store.view().get_network(net.id).copy()

    def get_network(self, network_id: str) -> Network:
        n = self.store.view().get_network(network_id)
        if n is None:
            raise NotFound(f"network {network_id} not found")
        return n.copy()

    def remove_network(self, network_id: str) -> None:
        """Fails while in use (reference: network.go RemoveNetwork)."""
        def cb(tx):
            n = tx.get_network(network_id)
            if n is None:
                raise NotFound(f"network {network_id} not found")
            for s in tx.find_services():
                targets = {na.target for na in s.spec.task.networks}
                targets |= {na.target for na in s.spec.networks}
                if network_id in targets:
                    raise FailedPrecondition(
                        f"network {network_id} is in use by service "
                        f"{s.spec.annotations.name}")
            tx.delete(Network, network_id)

        self.store.update(cb)

    def list_networks(self, filters: ListFilters | None = None) -> list[Network]:
        return [n.copy() for n in self.store.view().find_networks()
                if _match_filters(n, filters)]

    # --------------------------------------------------------------- volumes
    def create_volume(self, spec: VolumeSpec) -> Volume:
        spec = self._normalize(spec)
        self._validate_annotations(spec.annotations)
        if not spec.driver:
            raise InvalidArgument("driver must be specified")
        vol = Volume(id=new_id(), spec=spec)

        def cb(tx):
            if tx.find_volumes(by.ByName(spec.annotations.name)):
                raise AlreadyExists(
                    f"volume {spec.annotations.name} already exists")
            tx.create(vol)

        self.store.update(cb)
        return self.store.view().get_volume(vol.id).copy()

    def get_volume(self, volume_id: str) -> Volume:
        v = self.store.view().get_volume(volume_id)
        if v is None:
            raise NotFound(f"volume {volume_id} not found")
        return v.copy()

    def update_volume(self, volume_id: str, version: Version,
                      spec: VolumeSpec) -> Volume:
        """Only availability and labels may change
        (reference: volume.go UpdateVolume)."""
        spec = self._normalize(spec)
        out: list[Volume] = []

        def cb(tx):
            cur = tx.get_volume(volume_id)
            if cur is None:
                raise NotFound(f"volume {volume_id} not found")
            if cur.meta.version.index != version.index:
                raise FailedPrecondition("update out of sequence")
            nxt = cur.copy()
            nxt.spec.availability = spec.availability
            nxt.spec.annotations.labels = dict(spec.annotations.labels)
            tx.update(nxt)
            out.append(nxt)

        self.store.update(cb)
        return self._committed(out[0])

    def remove_volume(self, volume_id: str, force: bool = False) -> None:
        def cb(tx):
            v = tx.get_volume(volume_id)
            if v is None:
                raise NotFound(f"volume {volume_id} not found")
            if not force:
                for t in tx.find_tasks():
                    if volume_id in t.volumes and \
                            t.status.state <= TaskState.RUNNING:
                        raise FailedPrecondition(
                            f"volume {volume_id} is in use by task {t.id}")
            # mark pending_delete; the CSI manager finishes removal once
            # unpublished everywhere (reference: volume.go RemoveVolume)
            nxt = v.copy()
            nxt.pending_delete = True
            tx.update(nxt)

        self.store.update(cb)

    def list_volumes(self, filters: ListFilters | None = None) -> list[Volume]:
        return [v.copy() for v in self.store.view().find_volumes()
                if _match_filters(v, filters)]

    # ------------------------------------------------ extensions & resources
    def create_extension(self, annotations, description: str = "") -> Extension:
        annotations = self._normalize(annotations)
        self._validate_annotations(annotations)
        ext = Extension(id=new_id(), annotations=annotations,
                        description=description)

        def cb(tx):
            if tx.find_extensions(by.ByName(annotations.name)):
                raise AlreadyExists(
                    f"extension {annotations.name} already exists")
            tx.create(ext)

        self.store.update(cb)
        return self.store.view().get_extension(ext.id).copy()

    def get_extension(self, extension_id: str) -> Extension:
        e = self.store.view().get_extension(extension_id)
        if e is None:
            raise NotFound(f"extension {extension_id} not found")
        return e.copy()

    def remove_extension(self, extension_id: str) -> None:
        def cb(tx):
            e = tx.get_extension(extension_id)
            if e is None:
                raise NotFound(f"extension {extension_id} not found")
            ext_name = e.annotations.name
            for r in tx.find_resources(by.ByKind(ext_name)):
                raise FailedPrecondition(
                    f"extension {ext_name} is in use by resource {r.id}")
            tx.delete(Extension, extension_id)

        self.store.update(cb)

    def create_resource(self, annotations, kind: str,
                        payload: bytes = b"") -> Resource:
        annotations = self._normalize(annotations)
        self._validate_annotations(annotations)
        res = Resource(id=new_id(), annotations=annotations, kind=kind,
                       payload=payload)

        def cb(tx):
            if not tx.find_extensions(by.ByName(kind)):
                raise InvalidArgument(f"extension {kind} not registered")
            for other in tx.find_resources(by.ByKind(kind)):
                if other.annotations.name == annotations.name:
                    raise AlreadyExists(
                        f"resource {annotations.name} already exists")
            tx.create(res)

        self.store.update(cb)
        return self.store.view().get_resource(res.id).copy()

    def get_resource(self, resource_id: str) -> Resource:
        r = self.store.view().get_resource(resource_id)
        if r is None:
            raise NotFound(f"resource {resource_id} not found")
        return r.copy()

    def update_resource(self, resource_id: str, version: Version,
                        annotations, payload: bytes) -> Resource:
        out: list[Resource] = []

        def cb(tx):
            cur = tx.get_resource(resource_id)
            if cur is None:
                raise NotFound(f"resource {resource_id} not found")
            if cur.meta.version.index != version.index:
                raise FailedPrecondition("update out of sequence")
            nxt = cur.copy()
            nxt.annotations.labels = dict(annotations.labels)
            nxt.payload = payload
            tx.update(nxt)
            out.append(nxt)

        self.store.update(cb)
        return self._committed(out[0])

    def remove_resource(self, resource_id: str) -> None:
        def cb(tx):
            if tx.get_resource(resource_id) is None:
                raise NotFound(f"resource {resource_id} not found")
            tx.delete(Resource, resource_id)

        self.store.update(cb)

    def list_resources(self, kind: str | None = None,
                       filters: ListFilters | None = None) -> list[Resource]:
        sel = [by.ByKind(kind)] if kind else []
        return [r.copy() for r in self.store.view().find_resources(*sel)
                if _match_filters(r, filters, annotations=r.annotations)]
