"""Spec templating (reference: template/, SURVEY.md X2)."""
from .context import Context, TemplateError, expand_container_spec, expand_payload

__all__ = ["Context", "TemplateError", "expand_container_spec", "expand_payload"]
